"""Module API (parity: python/mxnet/module/module.py).

Module = intermediate/high-level trainer around a bound Symbol: bind →
init_params → init_optimizer → per-batch forward/backward/update, plus
`fit`, `score`, `predict` and checkpoint callbacks — the reference's
`mod.fit(train_iter, ...)` training loop, running on the jitted Executor
(forward+backward each one XLA computation).

Checkpoint format mirrors the reference (`prefix-symbol.json` +
`prefix-NNNN.params`), via `save_checkpoint` / `load_checkpoint`.
"""
from __future__ import annotations

import logging

import numpy as np

import jax.numpy as jnp

from .. import initializer as init_mod
from .. import metric as metric_mod
from .. import optimizer as opt_mod
from ..io import DataBatch, DataDesc
from ..ndarray import NDArray
from ..ndarray import random as ndrandom
from .. import symbol as sym_mod

__all__ = ["Module", "BaseModule", "BucketingModule",
           "save_checkpoint", "load_checkpoint"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Parity: mx.model.save_checkpoint — symbol json + params file."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    from .. import ndarray as nd
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_checkpoint(prefix, epoch):
    """Parity: mx.model.load_checkpoint → (symbol, arg_params, aux_params)."""
    from .. import ndarray as nd
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    loaded = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, name = k.split(":", 1)
        (arg_params if tp == "arg" else aux_params)[name] = v
    return symbol, arg_params, aux_params


class BaseModule:
    """Shared high-level loop (parity: module/base_module.py)."""

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        if isinstance(eval_metric, str):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        outputs = []
        for i, batch in enumerate(eval_data):
            if num_batch is not None and i >= num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            n = batch.data[0].shape[0] - batch.pad
            outputs.append(outs[0].asnumpy()[:n])
        from .. import ndarray as nd
        return nd.array(np.concatenate(outputs, axis=0))

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd", optimizer_params=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_init=False, begin_epoch=0,
            num_epoch=None, validation_metric=None):
        """Parity: BaseModule.fit — the classic epoch/batch training loop."""
        assert num_epoch is not None, "num_epoch is required"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        self.init_params(initializer=initializer or init_mod.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params or {})
        if isinstance(eval_metric, str):
            eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric))
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self._symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric)
                for name, val in res:
                    logging.info("Epoch[%d] Validation-%s=%f", epoch, name, val)


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 fixed_param_names=None):
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._fixed = set(fixed_param_names or [])
        self._ctx = context
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.for_training = False

    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    # -- bind -------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write",
             shared_module=None):
        """`shared_module` (parity: Module.bind shared_module): reuse the
        other module's parameter/gradient/aux NDArray objects so the two
        executors train the same weights (the BucketingModule mechanism —
        grads are written in-place, so updates through either are seen by
        both)."""
        if self.binded and not force_rebind:
            return
        shapes = {}
        for desc in data_shapes:
            name, shape = (desc.name, desc.shape) if isinstance(desc, DataDesc) \
                else (desc[0], desc[1])
            shapes[name] = tuple(shape)
        if label_shapes:
            for desc in label_shapes:
                name, shape = (desc.name, desc.shape) \
                    if isinstance(desc, DataDesc) else (desc[0], desc[1])
                shapes[name] = tuple(shape)
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names:
                req[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names or n in self._fixed:
                req[n] = "null"
            else:
                req[n] = grad_req
        self._exec = self._symbol.simple_bind(self._ctx, grad_req=req, **shapes)
        if shared_module is not None and shared_module._exec is not None:
            sx = shared_module._exec
            for n in self._param_names:
                if n in sx.arg_dict and n in self._exec.arg_dict:
                    self._exec.arg_dict[n] = sx.arg_dict[n]
                    if n in sx.grad_dict and n in self._exec.grad_dict:
                        self._exec.grad_dict[n] = sx.grad_dict[n]
            for n in self._aux_names:
                if n in sx.aux_dict:
                    self._exec.aux_dict[n] = sx.aux_dict[n]
            self.params_initialized = shared_module.params_initialized
        self.binded = True
        self.for_training = for_training
        self._data_shapes = shapes

    # -- params -----------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        assert self.binded, "bind before init_params"
        if self.params_initialized and not force_init:
            return
        if arg_params is None and getattr(self, "_preloaded", None):
            # Module.load(...) path: checkpoint weights win over re-init so
            # the reference's load→fit resume workflow keeps them.
            arg_params, aux_params = self._preloaded
        initializer = initializer or init_mod.Uniform(0.01)
        sym_attrs = self._symbol.attr_dict()
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name not in arg_params and not allow_missing:
                raise ValueError(
                    f"parameter {name!r} missing from arg_params; pass "
                    f"allow_missing=True to re-initialize missing params")
            if arg_params and name in arg_params:
                src = arg_params[name]
                arr._data = jnp.asarray(
                    src.asnumpy() if isinstance(src, NDArray) else src,
                    arr._data.dtype)
            else:
                ini = initializer
                attr_init = sym_attrs.get(name, {}).get("__init__")
                if attr_init:
                    # Variable(init=...) wins over name rules, like the
                    # reference's InitDesc attr dispatch
                    ini = _init_from_attr(attr_init)
                    if (isinstance(ini, init_mod.FusedRNN)
                            and ini.init is None):
                        # deferred inner: the user's initializer fills the
                        # packed vector; FusedRNN only stamps the
                        # forget-gate biases on top
                        inner = initializer
                        if isinstance(inner, init_mod.Mixed):
                            # no-pattern-match raises, same as any other
                            # parameter under Mixed
                            inner = inner.init_for(name)
                        ini = ini.with_inner(inner)
                elif isinstance(ini, init_mod.Mixed):
                    ini = ini.init_for(name)
                elif _is_special(name):
                    ini = _special_init(name)
                arr._data = ini(ndrandom._key(), arr.shape, arr._data.dtype)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params and name in aux_params:
                src = aux_params[name]
                arr._data = jnp.asarray(
                    src.asnumpy() if isinstance(src, NDArray) else src,
                    arr._data.dtype)
            else:
                if name.endswith("moving_var") or name.endswith("running_var"):
                    arr._data = jnp.ones(arr.shape, arr._data.dtype)
                else:
                    arr._data = jnp.zeros(arr.shape, arr._data.dtype)
        self.params_initialized = True

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    # -- optimizer --------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self._optimizer = optimizer
        self._opt_states = {
            n: optimizer.create_state(i, self._exec.arg_dict[n]._data)
            for i, n in enumerate(self._param_names)}
        self._num_update = 0
        self.optimizer_initialized = True

    # -- execution --------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                if name in self._exec.arg_dict:
                    feed[name] = arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        assert self.optimizer_initialized
        self._num_update += 1
        self._optimizer.num_update = self._num_update
        for i, n in enumerate(self._param_names):
            w = self._exec.arg_dict[n]
            g = self._exec.grad_dict.get(n)
            if g is None:
                continue
            lr, wd = self._optimizer._get_lr_wd(i)
            new_w, new_s = self._optimizer.update_step(
                w._data, g._data, self._opt_states[n], jnp.float32(lr),
                jnp.float32(wd), jnp.int32(self._num_update),
                rescale=self._optimizer.rescale_grad,
                clip=self._optimizer.clip_gradient)
            w._data = new_w
            self._opt_states[n] = new_s

    def get_outputs(self):
        return self._exec.outputs

    def get_input_grads(self):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # -- checkpoint -------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        return mod



_attr_initializer_create = None


def _init_from_attr(attr):
    """Variable __init__ attr -> initializer, via the shared
    mx.registry create (handles registered names and the json form
    Initializer.to_attr_str emits)."""
    global _attr_initializer_create
    if _attr_initializer_create is None:
        from .. import registry as _registry
        _attr_initializer_create = _registry.get_create_func(
            init_mod.Initializer, "initializer")
    return _attr_initializer_create(str(attr))


def _is_special(name):
    return name.endswith(("_bias", "_beta", "_gamma", "_moving_mean",
                          "_moving_var"))


def _special_init(name):
    if name.endswith(("_gamma", "_moving_var")):
        return init_mod.One()
    return init_mod.Zero()


class BucketingModule(BaseModule):
    """Variable-length training over bucketed shapes (parity:
    python/mxnet/module/bucketing_module.py).

    `sym_gen(bucket_key) -> symbol | (symbol, data_names, label_names)`.
    One Module per bucket; every bucket binds with
    `shared_module=<default bucket>`, so all buckets train the SAME
    parameter/gradient arrays. TPU-native note: each bucket is its own
    static-shape XLA executable (jit caches per shape) — exactly the
    compilation model buckets were invented for; the optimizer runs once,
    on the default module, over the shared arrays.
    """

    def __init__(self, sym_gen, default_bucket_key=None, logger=None,
                 context=None, fixed_param_names=None):
        if default_bucket_key is None:
            raise ValueError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_key = default_bucket_key
        self._ctx = context
        self._fixed = fixed_param_names
        self._buckets = {}
        self._curr = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.for_training = False

    def _gen(self, key):
        out = self._sym_gen(key)
        if isinstance(out, tuple):
            sym, data_names, label_names = out
        else:
            sym, data_names, label_names = out, ("data",), ("softmax_label",)
        return sym, data_names, label_names

    @property
    def _default_mod(self):
        return self._buckets[self._default_key]

    @property
    def symbol(self):
        return self._default_mod.symbol

    # -- bind / switch ----------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        if self.binded and not force_rebind:
            return
        # a rebind allocates NEW parameter arrays: stale bucket modules
        # would keep training the old ones — drop them all
        self._buckets = {}
        self.params_initialized = False
        self.optimizer_initialized = False
        sym, dn, ln = self._gen(self._default_key)
        mod = Module(sym, data_names=dn, label_names=ln, context=self._ctx,
                     fixed_param_names=self._fixed)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 grad_req=grad_req)
        self._buckets[self._default_key] = mod
        self._curr = mod
        self._grad_req = grad_req
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        assert self.binded, "bind() first"
        if bucket_key not in self._buckets:
            sym, dn, ln = self._gen(bucket_key)
            mod = Module(sym, data_names=dn, label_names=ln,
                         context=self._ctx, fixed_param_names=self._fixed)
            mod.bind(data_shapes, label_shapes, self.for_training,
                     grad_req=self._grad_req,
                     shared_module=self._default_mod)
            self._buckets[bucket_key] = mod
        self._curr = self._buckets[bucket_key]

    # -- params / optimizer (always on the default bucket: arrays shared) --
    def init_params(self, *args, **kwargs):
        self._default_mod.init_params(*args, **kwargs)
        self.params_initialized = True
        for m in self._buckets.values():
            m.params_initialized = True

    def get_params(self):
        return self._default_mod.get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True):
        self._default_mod.set_params(arg_params, aux_params,
                                     allow_missing=allow_missing,
                                     force_init=force_init)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        self._default_mod.init_optimizer(kvstore, optimizer,
                                         optimizer_params, force_init)
        self.optimizer_initialized = True

    # -- execution (forward picks the bucket from the batch) ---------------
    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", None)
        if key is None:
            key = self._default_key
        self.switch_bucket(key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr.params_initialized = self.params_initialized
        self._curr.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr.backward(out_grads)

    def update(self):
        self._default_mod.update()

    def get_outputs(self):
        return self._curr.get_outputs()

    def get_input_grads(self):
        return self._curr.get_input_grads()

    def update_metric(self, eval_metric, labels):
        self._curr.update_metric(eval_metric, labels)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._default_mod.save_checkpoint(prefix, epoch,
                                          save_optimizer_states)
