"""RecordIO: sequential + indexed record files and the image-record header
(parity: reference python/mxnet/recordio.py + src/io/ recordio readers;
ImageRecordIter's on-disk format).

Format per record: [magic u32 | lrecord u32 | payload | pad-to-4].
magic = 0xced7230a; lrecord = (cflag << 29) | length (cflag unused here —
records are written unsplit). Image records prepend an IRHeader to the
payload: (flag u32, label f32, id u64, id2 u64) packed little-endian; when
flag > 0 the scalar label is followed by `flag` float32 labels.

TPU-first note: this is pure host-side IO — the decode/augment path feeds
numpy batches to the chip; nothing here traces into XLA.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "shard_keys",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_LMASK = (1 << 29) - 1

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential record file reader/writer (reference MXRecordIO)."""

    def __init__(self, uri, flag):
        if flag not in ("r", "w"):
            raise ValueError(f"invalid flag {flag!r}: expected 'r' or 'w'")
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        self.record = open(self.uri, "rb" if self.flag == "r" else "wb")

    def close(self):
        if self.record is not None and not self.record.closed:
            self.record.close()

    def reset(self):
        self.close()
        self.open()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def tell(self):
        return self.record.tell()

    def write(self, buf):
        assert self.flag == "w", "not opened for writing"
        if not isinstance(buf, (bytes, bytearray, memoryview)):
            raise TypeError("write() expects bytes")
        self.record.write(struct.pack("<II", _MAGIC, len(buf)))
        self.record.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        """Next record payload, or None at EOF."""
        assert self.flag == "r", "not opened for reading"
        header = self.record.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise IOError(f"invalid record magic {magic:#x} at "
                          f"{self.record.tell() - 8}")
        length = lrec & _LMASK
        buf = self.record.read(length)
        if len(buf) < length:
            raise IOError("truncated record")
        pad = (4 - length % 4) % 4
        if pad:
            self.record.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Record file with a .idx sidecar mapping integer keys to byte offsets
    (reference MXIndexedRecordIO): random access via read_idx/write_idx."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.split("\t")
                    if len(parts) >= 2:
                        key = key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)

    def close(self):
        if self.flag == "w" and self.idx:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        assert self.flag == "r"
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


def shard_keys(keys, rank, num_ranks):
    """Deterministic interleaved shard of an index: ``keys[rank::num_ranks]``.

    The shard is a pure function of (keys, rank, num_ranks) — no state,
    no coordination — so fleet replicas and elastic re-joins
    (mxtpu.resilience) that agree on the index and the rank geometry
    read disjoint record sets in a reproducible order, and a restarted
    rank resumes exactly the shard it was reading. Interleaving (rather
    than contiguous blocks) keeps shard sizes within one record of each
    other and spreads any on-disk locality skew across ranks."""
    n = int(num_ranks)
    r = int(rank)
    if n < 1:
        raise ValueError(f"num_ranks must be >= 1, got {num_ranks}")
    if not 0 <= r < n:
        raise ValueError(f"rank must be in [0, {n}), got {rank}")
    return list(keys)[r::n]


# ---------------------------------------------------------------------------
# image record packing (IRHeader + encoded image payload)
# ---------------------------------------------------------------------------

def pack(header, s):
    """IRHeader + image bytes -> record payload (reference recordio.pack)."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (np.ndarray, list, tuple)):
        label = np.asarray(label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        return struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, float(label), header.id,
                       header.id2) + s


def unpack(s):
    """Record payload -> (IRHeader, image bytes). Multi-label records return
    the label vector as header.label (float32 ndarray)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """IRHeader + HWC uint8 array -> record payload with an encoded image
    (reference pack_img; PIL replaces the reference's cv2 encoder)."""
    import io as _io

    from PIL import Image

    img = np.asarray(img, dtype=np.uint8)
    if img.ndim == 2:
        pil = Image.fromarray(img, mode="L")
    else:
        pil = Image.fromarray(img)
    buf = _io.BytesIO()
    fmt = img_fmt.lower().lstrip(".")
    if fmt in ("jpg", "jpeg"):
        pil.save(buf, format="JPEG", quality=quality)
    elif fmt == "png":
        pil.save(buf, format="PNG")
    else:
        raise ValueError(f"unsupported image format {img_fmt!r}")
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=1):
    """Record payload -> (IRHeader, decoded HWC uint8 array)."""
    from . import image as _image

    header, img_bytes = unpack(s)
    return header, _image.imdecode(img_bytes, to_rgb=True,
                                   flag=iscolor).asnumpy().astype(np.uint8)
