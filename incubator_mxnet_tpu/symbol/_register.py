"""Symbol op registry: pure-jax op fns + shape-inference hints + the
symbol-level builder functions (sym.FullyConnected, ...).

Op fns take (rt, attrs, *raw_inputs) and return a raw array or tuple. Ops
with auxiliary inputs (BatchNorm moving stats) return (out, *new_aux) and
declare aux_pos; the executor writes new aux back after forward, matching
the reference's in-place aux update (src/operator/nn/batch_norm.cc).

Output-layer ops keep classic MXNet backward semantics via jax.custom_vjp:
SoftmaxOutput's gradient is (p - one_hot(label)) * grad_scale regardless of
head gradients (src/operator/softmax_output-inl.h).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import normalize_dtype
from ..ops import _raw
from . import Symbol, _make_op, register_op

import sys as _sys

_sym_mod = _sys.modules["incubator_mxnet_tpu.symbol"]


# ---------------------------------------------------------------------------
# elementwise / scalar
# ---------------------------------------------------------------------------

def _reg_binary(name, jfn):
    register_op(name, lambda rt, a, x, y: jfn(x, y), ("lhs", "rhs"))


def _reg_scalar(name, jfn):
    register_op(name + "_scalar",
                lambda rt, a, x: jfn(x, a["scalar"]), ("data",))


_reg_binary("_plus", jnp.add)
_reg_binary("_minus", jnp.subtract)
_reg_binary("_rminus", lambda x, y: y - x)
_reg_binary("_mul", jnp.multiply)
_reg_binary("_div", jnp.divide)
_reg_binary("_rdiv", lambda x, y: y / x)
_reg_binary("_power", jnp.power)
_reg_binary("_rpower", lambda x, y: jnp.power(y, x))
for _n in ("add", "sub", "mul", "div", "maximum", "minimum", "power",
           "equal", "not_equal", "greater", "greater_equal", "lesser",
           "lesser_equal"):
    _jf = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide, "maximum": jnp.maximum, "minimum": jnp.minimum,
           "power": jnp.power, "equal": jnp.equal, "not_equal": jnp.not_equal,
           "greater": jnp.greater, "greater_equal": jnp.greater_equal,
           "lesser": jnp.less, "lesser_equal": jnp.less_equal}[_n]
    _is_cmp = _n in ("equal", "not_equal", "greater", "greater_equal",
                     "lesser", "lesser_equal")
    _reg_binary("broadcast_" + _n,
                (lambda x, y, _f=_jf: _f(x, y).astype(x.dtype)) if _is_cmp
                else (lambda x, y, _f=_jf: _f(x, y)))

_reg_scalar("_plus", lambda x, s: x + s)
_reg_scalar("_minus", lambda x, s: x - s)
_reg_scalar("_rminus", lambda x, s: s - x)
_reg_scalar("_mul", lambda x, s: x * s)
_reg_scalar("_div", lambda x, s: x / s)
_reg_scalar("_rdiv", lambda x, s: s / x)
_reg_scalar("_power", lambda x, s: jnp.power(x, s))
_reg_scalar("_rpower", lambda x, s: jnp.power(s, x))


def _reg_unary(name, jfn):
    register_op(name, lambda rt, a, x: jfn(x), ("data",))


for _name, _fn in {
    "negative": jnp.negative, "exp": jnp.exp, "log": jnp.log,
    "sqrt": jnp.sqrt, "square": jnp.square, "abs": jnp.abs,
    "tanh": jnp.tanh, "sigmoid": jax.nn.sigmoid, "relu": jax.nn.relu,
    "erf": jax.lax.erf, "rsqrt": jax.lax.rsqrt,
    "sin": jnp.sin, "cos": jnp.cos, "sign": jnp.sign,
    "BlockGrad": jax.lax.stop_gradient, "stop_gradient": jax.lax.stop_gradient,
    "zeros_like": jnp.zeros_like, "ones_like": jnp.ones_like,
    "MakeLoss": lambda x: x,
}.items():
    _reg_unary(_name, _fn)

register_op("gelu",
            lambda rt, a, x: jax.nn.gelu(x, approximate=a.get("approximate",
                                                              True)),
            ("data",))
register_op("silu", lambda rt, a, x: jax.nn.silu(x), ("data",))
def _add_n_fn(rt, a, *xs):
    total = xs[0]  # builtins.sum is shadowed by the reduce builder below
    for x in xs[1:]:
        total = total + x
    return total


register_op("add_n", _add_n_fn, ())


# "Pad" (capitalized classic name) registers in the nd-mirror section at
# the bottom of this file so it shares nd.pad's single implementation.

def _arange_fn(rt, a):
    start, stop = a["start"], a.get("stop")
    if stop is None:                      # mx.arange(N) == [0, N)
        start, stop = 0.0, start
    r = jnp.arange(start, stop, a["step"], normalize_dtype(a["dtype"]))
    rep = int(a.get("repeat", 1))
    return jnp.repeat(r, rep) if rep > 1 else r


register_op("_arange", _arange_fn, ())
register_op("_zeros", lambda rt, a: jnp.zeros(tuple(a["shape"]),
                                              normalize_dtype(a["dtype"])), ())
register_op("_ones", lambda rt, a: jnp.ones(tuple(a["shape"]),
                                            normalize_dtype(a["dtype"])), ())
register_op("softmax", lambda rt, a, x: jax.nn.softmax(x, axis=a.get("axis", -1)),
            ("data",))
register_op("log_softmax",
            lambda rt, a, x: jax.nn.log_softmax(x, axis=a.get("axis", -1)),
            ("data",))
register_op("clip", lambda rt, a, x: jnp.clip(x, a["a_min"], a["a_max"]),
            ("data",))
register_op("dot",
            lambda rt, a, x, y: _raw.dot_mx(x, y, a.get("transpose_a"),
                                            a.get("transpose_b")),
            ("lhs", "rhs"))
# numpy-matmul semantics (stacked leading dims, broadcasting) — matches
# nd.batch_dot exactly (the 3-D MXNet case is a subset) and ONNX MatMul
register_op("batch_dot", lambda rt, a, x, y: jnp.matmul(
    x if not a.get("transpose_a") else jnp.swapaxes(x, -1, -2),
    y if not a.get("transpose_b") else jnp.swapaxes(y, -1, -2)),
    ("lhs", "rhs"))

# -- shape manipulation -----------------------------------------------------
register_op("Flatten", lambda rt, a, x: x.reshape(x.shape[0], -1), ("data",))
register_op("Reshape", lambda rt, a, x: _mx_reshape(x, tuple(a["shape"])),
            ("data",))
register_op("transpose",
            lambda rt, a, x: jnp.transpose(x, a.get("axes") or None), ("data",))
register_op("expand_dims", lambda rt, a, x: jnp.expand_dims(x, a["axis"]),
            ("data",))
register_op("squeeze", lambda rt, a, x: jnp.squeeze(x, a.get("axis")), ("data",))
register_op("Concat",
            lambda rt, a, *xs: jnp.concatenate(xs, axis=a.get("dim", 1)),
            ())
register_op("stack", lambda rt, a, *xs: jnp.stack(xs, axis=a.get("axis", 0)), ())
register_op("slice_axis",
            lambda rt, a, x: jax.lax.slice_in_dim(
                x, a["begin"], x.shape[a["axis"]] if a.get("end") is None else a["end"],
                axis=a["axis"]),
            ("data",))
register_op("SliceChannel",
            lambda rt, a, x: tuple(
                jnp.squeeze(p, a.get("axis", 1)) if a.get("squeeze_axis") else p
                for p in jnp.split(x, a["num_outputs"], axis=a.get("axis", 1))),
            ("data",), n_out=lambda a: a["num_outputs"])

for _name, _ax in (("sum", None), ("mean", None), ("max", None), ("min", None),
                   ("prod", None)):
    register_op(_name, lambda rt, a, x, _f=getattr(jnp, _name): _f(
        x, axis=a.get("axis"), keepdims=bool(a.get("keepdims", False))),
        ("data",))
register_op("argmax", lambda rt, a, x: jnp.argmax(
    x, axis=a.get("axis")).astype(jnp.float32), ("data",))


def _mx_reshape(x, shape):
    """MXNet Reshape with 0 (copy dim) and -1 (infer) specials."""
    out = []
    for i, s in enumerate(shape):
        out.append(x.shape[i] if s == 0 else s)
    return x.reshape(tuple(out))


# ---------------------------------------------------------------------------
# NN layers
# ---------------------------------------------------------------------------

def _fc_hint(in_shapes, attrs):
    d = in_shapes[0]
    if d is None:
        return None
    nh = attrs["num_hidden"]
    in_units = int(np.prod(d[1:])) if attrs.get("flatten", True) else d[-1]
    fills = {}
    if len(in_shapes) > 1 and in_shapes[1] is None:
        fills[1] = (nh, in_units)
    if len(in_shapes) > 2 and in_shapes[2] is None:
        fills[2] = (nh,)
    return fills


register_op(
    "FullyConnected",
    lambda rt, a, x, w, *b: _raw.dense(x, w, b[0] if b else None,
                                       a.get("flatten", True)),
    ("data", "weight", "bias"), infer_hint=_fc_hint)


def _conv_hint(in_shapes, attrs):
    d = in_shapes[0]
    if d is None:
        return None
    layout = attrs.get("layout") or "NCHW"
    c_in = d[1] if layout.startswith("NC") else d[-1]
    k = tuple(attrs["kernel"])
    nf = attrs["num_filter"]
    g = attrs.get("num_group", 1)
    fills = {}
    if len(in_shapes) > 1 and in_shapes[1] is None:
        if layout == "NHWC":
            fills[1] = k + (c_in // g, nf)
        else:
            fills[1] = (nf, c_in // g) + k
    if len(in_shapes) > 2 and in_shapes[2] is None:
        fills[2] = (nf,)
    return fills


register_op(
    "Convolution",
    lambda rt, a, x, w, *b: _raw.conv(
        x, w, b[0] if b else None, kernel=a.get("kernel"),
        stride=a.get("stride"), pad=a.get("pad"), dilate=a.get("dilate"),
        num_group=a.get("num_group", 1), layout=a.get("layout") or "NCHW"),
    ("data", "weight", "bias"), infer_hint=_conv_hint)

def _deconv_hint(in_shapes, attrs):
    d = in_shapes[0]
    if d is None:
        return None
    layout = attrs.get("layout") or "NCHW"
    c_in = d[1] if layout.startswith("NC") else d[-1]
    k = tuple(attrs["kernel"])
    nf = attrs["num_filter"]
    g = attrs.get("num_group", 1)
    fills = {}
    if len(in_shapes) > 1 and in_shapes[1] is None:
        # IOHW for NCHW (lax IOHW spec), HWIO for NHWC — see _raw.conv_transpose
        fills[1] = (k + (nf // g, c_in) if layout == "NHWC"
                    else (c_in, nf // g) + k)
    if len(in_shapes) > 2 and in_shapes[2] is None:
        fills[2] = (nf,)
    return fills


register_op(
    "Deconvolution",
    lambda rt, a, x, w, *b: _raw.conv_transpose(
        x, w, b[0] if b else None, stride=a.get("stride"), pad=a.get("pad"),
        dilate=a.get("dilate"), adj=a.get("adj"),
        num_group=a.get("num_group", 1), layout=a.get("layout") or "NCHW"),
    ("data", "weight", "bias"), infer_hint=_deconv_hint)

register_op(
    "Pooling",
    lambda rt, a, x: _raw.pooling(
        x, a.get("pool_type", "max"), tuple(a.get("kernel", (2, 2))),
        a.get("stride"), a.get("pad"), a.get("global_pool", False),
        a.get("count_include_pad", True), a.get("layout") or "NCHW",
        a.get("ceil_mode", False)),
    ("data",))

register_op(
    "Activation",
    lambda rt, a, x: _raw.activation(x, a.get("act_type", "relu")), ("data",))

register_op(
    "LeakyReLU",
    lambda rt, a, x: jax.nn.leaky_relu(x, a.get("slope", 0.25))
    if a.get("act_type", "leaky") == "leaky"
    else _raw.activation(x, a["act_type"]),
    ("data",))


def _channel_hint_at(axis_attr_default):
    def hint(in_shapes, attrs):
        d = in_shapes[0]
        if d is None:
            return None
        axis = attrs.get("axis", axis_attr_default)
        c = d[axis % len(d)]
        return {i: (c,) for i in range(1, len(in_shapes)) if in_shapes[i] is None}
    return hint


def _batch_norm_fn(rt, a, x, gamma, beta, mm, mv):
    y, new_mm, new_mv = _raw.batch_norm(
        x, gamma, beta, mm, mv, axis=a.get("axis", 1), eps=a.get("eps", 1e-5),
        momentum=a.get("momentum", 0.9), training=rt.is_train,
        use_global_stats=a.get("use_global_stats", False),
        fix_gamma=a.get("fix_gamma", False))
    return y, new_mm, new_mv


register_op("BatchNorm", _batch_norm_fn,
            ("data", "gamma", "beta", "moving_mean", "moving_var"),
            aux_pos=(3, 4), infer_hint=_channel_hint_at(1))

register_op(
    "LayerNorm",
    lambda rt, a, x, g, b: _raw.layer_norm(x, g, b, a.get("axis", -1),
                                           a.get("eps", 1e-5)),
    ("data", "gamma", "beta"), infer_hint=_channel_hint_at(-1))


def _dropout_fn(rt, a, x):
    training = rt.is_train or a.get("mode") == "always"
    if not training or a.get("p", 0.5) == 0.0:
        return x
    return _raw.dropout(x, rt.next_key(), a.get("p", 0.5), True,
                        tuple(a.get("axes", ())))


register_op("Dropout", _dropout_fn, ("data",))


def _embedding_hint(in_shapes, attrs):
    if in_shapes[1] is None:
        return {1: (attrs["input_dim"], attrs["output_dim"])}
    return None


register_op(
    "Embedding",
    lambda rt, a, idx, w: jnp.take(w, idx.astype(jnp.int32), axis=0),
    ("data", "weight"), infer_hint=_embedding_hint)

register_op("smooth_l1",
            lambda rt, a, x: _raw.smooth_l1(x, a.get("scalar", 1.0)), ("data",))
register_op("softmax_cross_entropy",
            lambda rt, a, x, l: _raw.softmax_cross_entropy(x, l), ("data", "label"))


# ---------------------------------------------------------------------------
# classic output ops (custom backward, reference semantics)
# ---------------------------------------------------------------------------

def _softmax_output_fn(rt, a, x, label):
    grad_scale = a.get("grad_scale", 1.0)
    normalization = a.get("normalization", "null")
    ignore_label = a.get("ignore_label", -1) if a.get("use_ignore") else None

    @jax.custom_vjp
    def f(x, label):
        return jax.nn.softmax(x, axis=-1)

    def fwd(x, label):
        p = jax.nn.softmax(x, axis=-1)
        return p, (p, label)

    def bwd(res, g):
        p, label = res
        lab = label.astype(jnp.int32)
        oh = jax.nn.one_hot(lab, p.shape[-1], dtype=p.dtype)
        grad = (p - oh) * grad_scale
        if ignore_label is not None:
            keep = (lab != ignore_label).astype(p.dtype)[..., None]
            grad = grad * keep
        if normalization == "batch":
            grad = grad / p.shape[0]
        elif normalization == "valid":
            n = jnp.maximum(jnp.sum((lab != (ignore_label if ignore_label
                                             is not None else -10**9))
                                    .astype(p.dtype)), 1.0)
            grad = grad / n
        return grad, jnp.zeros_like(label)

    f.defvjp(fwd, bwd)
    return f(x, label)


register_op("SoftmaxOutput", _softmax_output_fn, ("data", "label"))


def _make_regression(tname, pred_fn, grad_fn):
    def op_fn(rt, a, x, label):
        grad_scale = a.get("grad_scale", 1.0)

        @jax.custom_vjp
        def f(x, label):
            return pred_fn(x)

        def fwd(x, label):
            p = pred_fn(x)
            return p, (p, label)

        def bwd(res, g):
            p, label = res
            return grad_fn(p, label) * grad_scale, jnp.zeros_like(label)

        f.defvjp(fwd, bwd)
        return f(x, label)

    register_op(tname, op_fn, ("data", "label"))


_make_regression("LinearRegressionOutput", lambda x: x, lambda p, l: p - l)
_make_regression("MAERegressionOutput", lambda x: x, lambda p, l: jnp.sign(p - l))
_make_regression("LogisticRegressionOutput", jax.nn.sigmoid, lambda p, l: p - l)


def _svm_output_fn(rt, a, x, label):
    """Parity: mx.sym.SVMOutput (src/operator/svm_output.cc). Forward is
    identity over the class scores; backward is the one-vs-all hinge
    gradient with targets y=+1 for the labelled class and -1 otherwise —
    squared hinge (L2-SVM) by default, linear hinge with use_linear.
    Like SoftmaxOutput, the gradient ignores head cotangents."""
    margin = float(a.get("margin", 1.0))
    C = float(a.get("regularization_coefficient", 1.0))
    use_linear = bool(a.get("use_linear", False))

    @jax.custom_vjp
    def f(x, label):
        return x

    def fwd(x, label):
        return x, (x, label)

    def bwd(res, g):
        x, label = res
        y = 2.0 * jax.nn.one_hot(label.astype(jnp.int32), x.shape[-1],
                                 dtype=x.dtype) - 1.0
        viol = margin - y * x
        if use_linear:                       # L1-SVM: -C*y on violations
            grad = -C * y * (viol > 0).astype(x.dtype)
        else:                                # L2-SVM: -2C*y*max(0, viol)
            grad = -2.0 * C * y * jnp.maximum(viol, 0.0)
        return grad, jnp.zeros_like(label)

    f.defvjp(fwd, bwd)
    return f(x, label)


register_op("SVMOutput", _svm_output_fn, ("data", "label"))


# ---------------------------------------------------------------------------
# symbol-level builders (the sym.* functions)
# ---------------------------------------------------------------------------

def _attrs(**kwargs):
    return {k: v for k, v in kwargs.items() if v is not None}


def FullyConnected(data=None, weight=None, bias=None, num_hidden=None,
                   no_bias=False, flatten=True, name=None):
    ins = [data, weight] + ([] if no_bias else [bias])
    return _make_op("FullyConnected", ins,
                    _attrs(num_hidden=num_hidden, flatten=flatten), name)


def Convolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                pad=None, dilate=None, num_filter=None, num_group=1,
                no_bias=False, layout=None, name=None):
    ins = [data, weight] + ([] if no_bias else [bias])
    return _make_op("Convolution", ins,
                    _attrs(kernel=kernel, stride=stride, pad=pad, dilate=dilate,
                           num_filter=num_filter, num_group=num_group,
                           layout=layout), name)


def Deconvolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                  pad=None, dilate=None, adj=None, num_filter=None,
                  num_group=1, no_bias=False, layout=None, name=None):
    ins = [data, weight] + ([] if no_bias else [bias])
    return _make_op("Deconvolution", ins,
                    _attrs(kernel=kernel, stride=stride, pad=pad, dilate=dilate,
                           adj=adj, num_filter=num_filter, num_group=num_group,
                           layout=layout), name)


def Pooling(data=None, pool_type="max", kernel=(2, 2), stride=None, pad=None,
            global_pool=False, count_include_pad=True, layout=None,
            ceil_mode=False, name=None):
    return _make_op("Pooling", [data],
                    _attrs(pool_type=pool_type, kernel=kernel, stride=stride,
                           pad=pad, global_pool=global_pool,
                           count_include_pad=count_include_pad, layout=layout,
                           ceil_mode=ceil_mode), name)


def Activation(data=None, act_type="relu", name=None):
    return _make_op("Activation", [data], {"act_type": act_type}, name)


def LeakyReLU(data=None, act_type="leaky", slope=0.25, name=None):
    return _make_op("LeakyReLU", [data],
                    {"act_type": act_type, "slope": slope}, name)


def BatchNorm(data=None, gamma=None, beta=None, moving_mean=None,
              moving_var=None, axis=1, eps=1e-5, momentum=0.9,
              fix_gamma=False, use_global_stats=False, name=None):
    return _make_op("BatchNorm", [data, gamma, beta, moving_mean, moving_var],
                    _attrs(axis=axis, eps=eps, momentum=momentum,
                           fix_gamma=fix_gamma,
                           use_global_stats=use_global_stats), name)


def LayerNorm(data=None, gamma=None, beta=None, axis=-1, eps=1e-5, name=None):
    return _make_op("LayerNorm", [data, gamma, beta],
                    _attrs(axis=axis, eps=eps), name)


def Dropout(data=None, p=0.5, mode="training", axes=(), name=None):
    return _make_op("Dropout", [data], _attrs(p=p, mode=mode, axes=axes), name)


def Embedding(data=None, weight=None, input_dim=None, output_dim=None,
              name=None):
    return _make_op("Embedding", [data, weight],
                    _attrs(input_dim=input_dim, output_dim=output_dim), name)


def SoftmaxOutput(data=None, label=None, grad_scale=1.0, normalization="null",
                  use_ignore=False, ignore_label=-1, name=None):
    return _make_op("SoftmaxOutput", [data, label],
                    _attrs(grad_scale=grad_scale, normalization=normalization,
                           use_ignore=use_ignore, ignore_label=ignore_label),
                    name or "softmax")


def LinearRegressionOutput(data=None, label=None, grad_scale=1.0, name=None):
    return _make_op("LinearRegressionOutput", [data, label],
                    {"grad_scale": grad_scale}, name)


def MAERegressionOutput(data=None, label=None, grad_scale=1.0, name=None):
    return _make_op("MAERegressionOutput", [data, label],
                    {"grad_scale": grad_scale}, name)


def LogisticRegressionOutput(data=None, label=None, grad_scale=1.0, name=None):
    return _make_op("LogisticRegressionOutput", [data, label],
                    {"grad_scale": grad_scale}, name)


def SVMOutput(data=None, label=None, margin=1.0,
              regularization_coefficient=1.0, use_linear=False, name=None):
    return _make_op("SVMOutput", [data, label],
                    {"margin": margin,
                     "regularization_coefficient": regularization_coefficient,
                     "use_linear": use_linear}, name)


def MakeLoss(data=None, grad_scale=1.0, name=None):
    return _make_op("MakeLoss", [data], {"grad_scale": grad_scale}, name)


def BlockGrad(data=None, name=None):
    return _make_op("BlockGrad", [data], {}, name)


def Flatten(data=None, name=None):
    return _make_op("Flatten", [data], {}, name)


def Reshape(data=None, shape=None, name=None):
    return _make_op("Reshape", [data], {"shape": tuple(shape)}, name)


def transpose(data=None, axes=None, name=None):
    return _make_op("transpose", [data], _attrs(axes=axes), name)


def expand_dims(data=None, axis=0, name=None):
    return _make_op("expand_dims", [data], {"axis": axis}, name)


def squeeze(data=None, axis=None, name=None):
    return _make_op("squeeze", [data], _attrs(axis=axis), name)


def Concat(*args, dim=1, name=None):
    return _make_op("Concat", list(args), {"dim": dim}, name)


concat = Concat


def stack(*args, axis=0, name=None):
    return _make_op("stack", list(args), {"axis": axis}, name)


def slice_axis(data=None, axis=0, begin=0, end=None, name=None):
    return _make_op("slice_axis", [data],
                    {"axis": axis, "begin": begin, "end": end}, name)


def SliceChannel(data=None, num_outputs=None, axis=1, squeeze_axis=False,
                 name=None):
    return _make_op("SliceChannel", [data],
                    {"num_outputs": num_outputs, "axis": axis,
                     "squeeze_axis": squeeze_axis}, name)


split = SliceChannel


def softmax(data=None, axis=-1, name=None):
    return _make_op("softmax", [data], {"axis": axis}, name)


def log_softmax(data=None, axis=-1, name=None):
    return _make_op("log_softmax", [data], {"axis": axis}, name)


def clip(data=None, a_min=None, a_max=None, name=None):
    return _make_op("clip", [data], {"a_min": a_min, "a_max": a_max}, name)


def dot(lhs=None, rhs=None, transpose_a=False, transpose_b=False,
        name=None):
    return _make_op("dot", [lhs, rhs],
                    _attrs(transpose_a=bool(transpose_a) or None,
                           transpose_b=bool(transpose_b) or None), name)


def batch_dot(lhs=None, rhs=None, transpose_a=False, transpose_b=False,
              name=None):
    return _make_op("batch_dot", [lhs, rhs],
                    {"transpose_a": transpose_a, "transpose_b": transpose_b},
                    name)


def smooth_l1(data=None, scalar=1.0, name=None):
    return _make_op("smooth_l1", [data], {"scalar": scalar}, name)


def softmax_cross_entropy(data=None, label=None, name=None):
    return _make_op("softmax_cross_entropy", [data, label], {}, name)


def _make_unary_builder(opname):
    def builder(data=None, name=None):
        return _make_op(opname, [data], {}, name)
    builder.__name__ = opname
    return builder


_UNARY_BUILDERS = ["negative", "exp", "log", "sqrt", "square", "abs", "tanh",
                   "sigmoid", "relu", "erf", "rsqrt", "sin", "cos", "sign",
                   "zeros_like", "ones_like", "stop_gradient"]
for _n in _UNARY_BUILDERS:
    globals()[_n] = _make_unary_builder(_n)


def _make_reduce_builder(opname):
    def builder(data=None, axis=None, keepdims=False, name=None):
        return _make_op(opname, [data], _attrs(axis=axis, keepdims=keepdims),
                        name)
    builder.__name__ = opname
    return builder


for _n in ["sum", "mean", "max", "min", "prod", "argmax"]:
    globals()[_n] = _make_reduce_builder(_n)


def broadcast_op_builder(opname):
    def builder(lhs=None, rhs=None, name=None):
        return _make_op(opname, [lhs, rhs], {}, name)
    builder.__name__ = opname
    return builder


for _n in ["broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
           "broadcast_maximum", "broadcast_minimum", "broadcast_power",
           "broadcast_equal", "broadcast_not_equal", "broadcast_greater",
           "broadcast_greater_equal", "broadcast_lesser",
           "broadcast_lesser_equal"]:
    globals()[_n] = broadcast_op_builder(_n)


def gelu(data=None, approximate=True, name=None):
    return _make_op("gelu", [data], {"approximate": approximate}, name)


def silu(data=None, name=None):
    return _make_op("silu", [data], {}, name)


def add_n(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return _make_op("add_n", list(args), {}, name)


def Pad(data=None, mode="constant", pad_width=None, constant_value=0,
        name=None):
    """Parity: mx.sym.Pad (src/operator/pad.cc); pad_width is the flat
    (before0, after0, before1, ...) tuple."""
    return _make_op("Pad", [data],
                    _attrs(mode=mode, pad_width=tuple(pad_width),
                           constant_value=constant_value), name)


# Export the builders onto the `symbol` module namespace.
_EXPORTS = [n for n in list(globals()) if n[0].isupper() or n in (
    "concat", "split", "softmax", "log_softmax", "clip", "dot", "batch_dot",
    "smooth_l1", "softmax_cross_entropy", "transpose", "expand_dims",
    "squeeze", "slice_axis", "stack", "gelu", "silu", "add_n",
) or n in _UNARY_BUILDERS or n in ("sum", "mean", "max", "min", "prod",
                                   "argmax")
    or n.startswith("broadcast_")]
for _n in _EXPORTS:
    if not _n.startswith("_"):
        setattr(_sym_mod, _n, globals()[_n])


# NDArray-method mirrors on Symbol: eager-written Gluon forwards call
# x.relu()/x.flatten()/... on their tensors; under symbol tracing
# (gluon/symbolize.py) those tensors are Symbols, so the same spelling must
# build graph nodes.
def _attach_symbol_methods():
    def _method(builder):
        def m(self, *args, **kwargs):
            return builder(self, *args, **kwargs)
        m.__name__ = builder.__name__
        return m

    for _n in ("relu", "sigmoid", "tanh", "exp", "log", "sqrt", "square",
               "abs", "erf", "sum", "mean", "max", "min", "prod"):
        if not hasattr(Symbol, _n):
            setattr(Symbol, _n, _method(globals()[_n]))
    if not hasattr(Symbol, "flatten"):
        Symbol.flatten = lambda self: globals()["Flatten"](self)
    if not hasattr(Symbol, "softmax"):
        Symbol.softmax = lambda self, axis=-1: globals()["softmax"](
            self, axis=axis)

    def _sym_reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return globals()["Reshape"](self, shape=shape)
    if not hasattr(Symbol, "reshape"):
        Symbol.reshape = _sym_reshape
    def _sym_transpose(self, *axes):
        # accept all NDArray spellings: x.transpose((0,2,1)),
        # x.transpose(0, 2, 1), x.transpose(None), bare x.transpose()
        # (the None/bare forms reverse dims)
        if len(axes) == 1 and axes[0] is None:
            axes = ()
        elif len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return globals()["transpose"](self, axes=(axes if axes else None))
    if not hasattr(Symbol, "transpose"):
        Symbol.transpose = _sym_transpose


_attach_symbol_methods()


# ---------------------------------------------------------------------------
# InstanceNorm / UpSampling / fused RNN (parity: src/operator/instance_norm,
# nn/upsampling, rnn.cc — mx.sym surface)
# ---------------------------------------------------------------------------

register_op(
    "InstanceNorm",
    lambda rt, a, x, g, b: _raw.instance_norm(x, g, b, a.get("eps", 1e-3)),
    ("data", "gamma", "beta"), infer_hint=_channel_hint_at(1))

register_op(
    "UpSampling",
    lambda rt, a, x: _raw.upsampling(x, a.get("scale", 2),
                                     a.get("sample_type", "nearest"),
                                     a.get("layout") or "NCHW"),
    ("data",))


def _unpack_rnn_params(p, mode, num_layers, D, I, H):
    """Reference flat packing (rnn-inl.h): all i2h/h2h weights in
    (layer, dir) order, then all biases in the same order."""
    from ..ops._rnn import GATES
    G = GATES[mode]
    shapes = []
    for layer in range(num_layers):
        il = I if layer == 0 else D * H
        for _ in range(D):
            shapes.append(((G * H, il), (G * H, H)))
    off = 0
    weights = []
    for s1, s2 in shapes:
        n1 = s1[0] * s1[1]
        w1 = p[off:off + n1].reshape(s1)
        off += n1
        n2 = s2[0] * s2[1]
        w2 = p[off:off + n2].reshape(s2)
        off += n2
        weights.append((w1, w2))
    biases = []
    for s1, s2 in shapes:
        b1 = p[off:off + s1[0]]
        off += s1[0]
        b2 = p[off:off + s2[0]]
        off += s2[0]
        biases.append((b1, b2))
    return [(w1, w2, b1, b2)
            for (w1, w2), (b1, b2) in zip(weights, biases)]


def _rnn_fn(rt, a, x, params, *states):
    from ..ops import _rnn as _rnn_mod
    mode = a.get("mode", "lstm")
    H = int(a["state_size"])
    L = int(a.get("num_layers", 1))
    bid = bool(a.get("bidirectional", False))
    D = 2 if bid else 1
    I = x.shape[-1]
    layer_params = _unpack_rnn_params(params, mode, L, D, I, H)
    dropout = float(a.get("p", 0.0))
    key = rt.next_key() if (dropout > 0.0 and rt.is_train) else None
    out, new_states = _rnn_mod.rnn_forward(
        x, list(states), layer_params, mode, bidirectional=bid,
        dropout=dropout, dropout_key=key, training=rt.is_train)
    if a.get("state_outputs", False):
        return (out, *new_states)
    return out


def _rnn_nout(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


def _rnn_hint(in_shapes, attrs):
    """Fill parameters/state shapes from data (T,N,I) + attrs, so
    simple_bind works with an auto-created packed parameter Variable."""
    d = in_shapes[0]
    if d is None:
        return None
    from ..ops._rnn import packed_param_size
    mode = attrs.get("mode", "lstm")
    H = int(attrs["state_size"])
    L = int(attrs.get("num_layers", 1))
    D = 2 if attrs.get("bidirectional", False) else 1
    T, N, I = d
    size = packed_param_size(mode, L, D == 2, I, H)
    fills = {}
    if len(in_shapes) > 1 and in_shapes[1] is None:
        fills[1] = (size,)
    if len(in_shapes) > 2 and in_shapes[2] is None:
        fills[2] = (L * D, N, H)
    if len(in_shapes) > 3 and in_shapes[3] is None:
        fills[3] = (L * D, N, H)
    return fills


register_op("RNN", _rnn_fn, ("data", "parameters", "state", "state_cell"),
            n_out=_rnn_nout, infer_hint=_rnn_hint)


def InstanceNorm(data=None, gamma=None, beta=None, eps=1e-3, name=None):
    return _make_op("InstanceNorm", [data, gamma, beta], _attrs(eps=eps),
                    name)


def UpSampling(data=None, scale=2, sample_type="nearest", num_filter=None,
               layout=None, name=None):
    return _make_op("UpSampling", [data],
                    _attrs(scale=scale, sample_type=sample_type,
                           layout=layout), name)


def RNN(data=None, parameters=None, state=None, state_cell=None, mode="lstm",
        state_size=None, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False, name=None):
    """Fused multi-layer RNN (parity: mx.sym.RNN / src/operator/rnn.cc).
    data (T,N,I); parameters flat packed (rnn-inl.h layout); state
    (L*D,N,H); state_cell for lstm."""
    inputs = [data, parameters, state]
    if mode == "lstm":
        inputs.append(state_cell)
    return _make_op("RNN", inputs, _attrs(
        mode=mode, state_size=state_size, num_layers=num_layers,
        bidirectional=bidirectional, p=p, state_outputs=state_outputs), name)


for _n in ["InstanceNorm", "UpSampling", "RNN"]:
    setattr(_sym_mod, _n, globals()[_n])


# ---------------------------------------------------------------------------
# Custom operator (parity: mx.sym.Custom / python/mxnet/operator.py)
# ---------------------------------------------------------------------------

from .. import operator as _operator  # noqa: E402

register_op("Custom", _operator.custom_sym_fn, (),
            n_out=_operator.custom_n_out,
            aux_pos=_operator.custom_aux_pos,
            infer_hint=_operator.custom_infer_hint)


def Custom(*args, op_type=None, name=None, **kwargs):
    """mx.sym.Custom(data, ..., op_type='my_op', **string_kwargs).
    Auxiliary states declared by the prop but not passed explicitly are
    auto-created as `{name}_{auxname}` variables (reference behavior:
    simple_bind allocates declared aux automatically)."""
    if op_type is None:
        raise ValueError("Custom requires op_type=")
    attrs = dict(kwargs, op_type=op_type)
    prop = _operator._make_prop(op_type, attrs)
    total = _operator._n_args(prop) + _operator._n_aux(prop)
    inputs = list(args)
    if len(inputs) < total:
        name = name or _sym_auto_name("custom")
        slot_names = (list(prop.list_arguments())
                      + list(prop.list_auxiliary_states()))
        for pos in range(len(inputs), total):
            inputs.append(_Variable(f"{name}_{slot_names[pos]}"))
    return _make_op("Custom", inputs, attrs, name)


setattr(_sym_mod, "Custom", Custom)


# ---------------------------------------------------------------------------
# slice / elemwise mirrors (reference op names used by classic scripts)
# ---------------------------------------------------------------------------

_builtin_slice = slice


def _mx_slice(x, begin, end, step):
    idx = []
    for d in range(len(begin)):
        b, e = begin[d], end[d]
        s = (step[d] if step and d < len(step) else None) or 1
        idx.append(_builtin_slice(b, e, s))
    return x[tuple(idx)]


register_op("slice", lambda rt, a, x: _mx_slice(
    x, a["begin"], a["end"], a.get("step")), ("data",))


def slice(data=None, begin=None, end=None, step=None, name=None):  # noqa: A001
    return _make_op("slice", [data],
                    _attrs(begin=tuple(begin), end=tuple(end),
                           step=tuple(step) if step else None), name)


for _n, _jf in (("elemwise_add", jnp.add), ("elemwise_sub", jnp.subtract),
                ("elemwise_mul", jnp.multiply), ("elemwise_div", jnp.divide)):
    register_op(_n, (lambda f: lambda rt, a, x, y: f(x, y))(_jf),
                ("lhs", "rhs"))
    def _mk(op):
        def builder(lhs=None, rhs=None, name=None):
            return _make_op(op, [lhs, rhs], None, name)
        builder.__name__ = op
        return builder
    setattr(_sym_mod, _n, _mk(_n))

setattr(_sym_mod, "slice", slice)


# ---------------------------------------------------------------------------
# sym.contrib: box/SSD family + attention, symbol mirrors of nd.contrib
# (reference: mx.sym.contrib.* — src/operator/contrib/multibox_*.cc)
# ---------------------------------------------------------------------------

from ..ops import box as _box  # noqa: E402


def _prior_fn(rt, a, x):
    return _box._multibox_prior_raw(x, a["sizes"], a["ratios"], a["steps"],
                                    a["offsets"], a.get("clip", False),
                                    a.get("layout", "NCHW"))


register_op("_contrib_MultiBoxPrior", _prior_fn, ("data",))


def _target_fn(rt, a, anc, lab, cp):
    return _box._multibox_target_raw(
        anc, lab, cp, a["overlap_threshold"], a["negative_mining_ratio"],
        a["negative_mining_thresh"], a["ignore_label"],
        a["minimum_negative_samples"],
        jnp.asarray(a.get("variances", (0.1, 0.1, 0.2, 0.2))))


register_op("_contrib_MultiBoxTarget", _target_fn,
            ("anchor", "label", "cls_pred"), n_out=3)


def _detection_fn(rt, a, cp, lp, anc):
    return _box._multibox_detection_raw(
        cp, lp, anc, a["threshold"], a["clip"], a["nms_threshold"],
        a["force_suppress"], a["nms_topk"],
        jnp.asarray(a.get("variances", (0.1, 0.1, 0.2, 0.2))))


register_op("_contrib_MultiBoxDetection", _detection_fn,
            ("cls_prob", "loc_pred", "anchor"))


def _box_nms_fn(rt, a, d):
    one = d.ndim == 2
    db = d[None] if one else d
    out = _box._box_nms(db, a["overlap_thresh"], a["valid_thresh"],
                        a["topk"], a["coord_start"], a["score_index"],
                        a["id_index"], a["force_suppress"],
                        a["background_id"], a["in_format"],
                        a.get("out_format", a["in_format"]))
    return out[0] if one else out


register_op("_contrib_box_nms", _box_nms_fn, ("data",))


def _box_iou_fn(rt, a, x, y):
    if a.get("format", "corner") == "center":
        x, y = _box._center_to_corner(x), _box._center_to_corner(y)
    return _box._iou_corner(x, y)


register_op("_contrib_box_iou", _box_iou_fn, ("lhs", "rhs"))


def _contrib_MultiBoxPrior(data=None, sizes=(1.0,), ratios=(1.0,),
                           clip=False, steps=(-1.0, -1.0),
                           offsets=(0.5, 0.5), layout="NCHW", name=None):
    """Argument order matches the reference op (clip before steps), same
    as nd.contrib.MultiBoxPrior."""
    return _make_op("_contrib_MultiBoxPrior", [data],
                    _attrs(sizes=tuple(sizes), ratios=tuple(ratios),
                           steps=tuple(steps), offsets=tuple(offsets),
                           layout=layout, clip=clip), name)


def _contrib_MultiBoxTarget(anchor=None, label=None, cls_pred=None,
                            overlap_threshold=0.5, ignore_label=-1,
                            negative_mining_ratio=-1,
                            negative_mining_thresh=0.5,
                            minimum_negative_samples=0,
                            variances=(0.1, 0.1, 0.2, 0.2), name=None):
    return _make_op("_contrib_MultiBoxTarget", [anchor, label, cls_pred],
                    _attrs(overlap_threshold=overlap_threshold,
                           ignore_label=ignore_label,
                           negative_mining_ratio=negative_mining_ratio,
                           negative_mining_thresh=negative_mining_thresh,
                           minimum_negative_samples=minimum_negative_samples,
                           variances=tuple(variances)),
                    name)


def _contrib_MultiBoxDetection(cls_prob=None, loc_pred=None, anchor=None,
                               threshold=0.01, clip=True, nms_threshold=0.5,
                               force_suppress=False,
                               variances=(0.1, 0.1, 0.2, 0.2),
                               nms_topk=-1, name=None):
    return _make_op("_contrib_MultiBoxDetection", [cls_prob, loc_pred, anchor],
                    _attrs(threshold=threshold, clip=clip,
                           nms_threshold=nms_threshold,
                           force_suppress=force_suppress, nms_topk=nms_topk,
                           variances=tuple(variances)),
                    name)


def _contrib_box_nms(data=None, overlap_thresh=0.5, valid_thresh=0.0,
                     topk=-1, coord_start=2, score_index=1, id_index=-1,
                     background_id=-1, force_suppress=False,
                     in_format="corner", out_format=None, name=None):
    _box._validate_nms_formats(in_format, out_format or in_format)
    return _make_op("_contrib_box_nms", [data],
                    _attrs(overlap_thresh=overlap_thresh,
                           valid_thresh=valid_thresh, topk=topk,
                           coord_start=coord_start, score_index=score_index,
                           id_index=id_index, background_id=background_id,
                           force_suppress=force_suppress,
                           in_format=in_format,
                           out_format=out_format or in_format), name)


def _contrib_box_iou(lhs=None, rhs=None, format="corner", name=None):  # noqa: A002
    return _make_op("_contrib_box_iou", [lhs, rhs],
                    _attrs(format=format), name)


# contrib vision ops (reference src/operator/contrib/roi_align.cc,
# bilinear_resize.cc, adaptive_avg_pooling.cc)
register_op(
    "ROIAlign",
    lambda rt, a, x, r: _raw.roi_align(x, r, tuple(a["pooled_size"]),
                                       a.get("spatial_scale", 1.0),
                                       a.get("sample_ratio", -1)),
    ("data", "rois"))
register_op(
    "BilinearResize2D",
    lambda rt, a, x: _raw.bilinear_resize(x, a["height"], a["width"]),
    ("data",))
register_op(
    "AdaptiveAvgPooling2D",
    lambda rt, a, x: _raw.adaptive_avg_pool(x, a.get("output_size", 1)),
    ("data",))
register_op(
    "ROIPooling",
    lambda rt, a, x, r: _raw.roi_pooling(x, r, tuple(a["pooled_size"]),
                                         a.get("spatial_scale", 1.0)),
    ("data", "rois"))


def ROIAlign(data=None, rois=None, pooled_size=(7, 7), spatial_scale=1.0,
             sample_ratio=-1, name=None):
    return _make_op("ROIAlign", [data, rois],
                    _attrs(pooled_size=tuple(pooled_size),
                           spatial_scale=spatial_scale,
                           sample_ratio=sample_ratio), name)


def BilinearResize2D(data=None, height=None, width=None, name=None):
    height, width = _raw.validate_resize_sizes(height, width)
    return _make_op("BilinearResize2D", [data],
                    _attrs(height=height, width=width), name)


def AdaptiveAvgPooling2D(data=None, output_size=1, name=None):
    return _make_op("AdaptiveAvgPooling2D", [data],
                    _attrs(output_size=output_size), name)


def ROIPooling(data=None, rois=None, pooled_size=(7, 7), spatial_scale=1.0,
               name=None):
    return _make_op("ROIPooling", [data, rois],
                    _attrs(pooled_size=tuple(pooled_size),
                           spatial_scale=spatial_scale), name)


for _n in ("ROIAlign", "BilinearResize2D", "AdaptiveAvgPooling2D",
           "ROIPooling"):
    setattr(_sym_mod, _n, globals()[_n])


# -- attention as a first-class symbol op (reference: the symbol-level
#    interleaved_matmul_selfatt_* / multihead attention ops of
#    src/operator/contrib/transformer.cc) --------------------------------

def _mha_fn(rt, a, q, k, v, *rest):
    mask = rest[0] if a.get("has_mask") else None
    # symbol executors run inference semantics for dropout (reference
    # symbol attention ops carry no dropout either): rate 0, no key
    return _raw.multihead_attention(q, k, v, a["num_heads"], mask, 0.0,
                                    None, False, a.get("scale"),
                                    a.get("causal", False))


register_op("multihead_attention", _mha_fn, ("queries", "keys", "values"))


def multihead_attention(queries=None, keys=None, values=None, num_heads=1,
                        mask=None, scale=None, causal=False, name=None):
    ins = [queries, keys, values] + ([mask] if mask is not None else [])
    return _make_op("multihead_attention", ins,
                    _attrs(num_heads=int(num_heads), scale=scale,
                           causal=bool(causal) or None,
                           has_mask=True if mask is not None else None),
                    name)


_sym_mod.multihead_attention = multihead_attention


def _arange_like_fn(rt, a, x):
    from .. import ops as _ops_mod
    from ..ndarray import NDArray
    out = _ops_mod.arange_like(NDArray(x), a.get("start", 0.0),
                               a.get("step", 1.0), a.get("repeat", 1),
                               a.get("axis"))
    return out._data


register_op("arange_like", _arange_like_fn, ("data",))


def arange_like(data=None, start=0.0, step=1.0, repeat=1, axis=None,
                name=None):
    return _make_op("arange_like", [data],
                    _attrs(start=float(start), step=float(step),
                           repeat=int(repeat), axis=axis), name)


_sym_mod.arange_like = arange_like


def _install_sym_contrib():
    import sys
    import types
    contrib = types.ModuleType("incubator_mxnet_tpu.symbol.contrib")
    contrib.MultiBoxPrior = _contrib_MultiBoxPrior
    contrib.MultiBoxTarget = _contrib_MultiBoxTarget
    contrib.MultiBoxDetection = _contrib_MultiBoxDetection
    contrib.box_nms = _contrib_box_nms
    contrib.box_iou = _contrib_box_iou
    contrib.ROIAlign = ROIAlign
    contrib.BilinearResize2D = BilinearResize2D
    contrib.AdaptiveAvgPooling2D = AdaptiveAvgPooling2D
    contrib.arange_like = arange_like
    _sym_mod.contrib = contrib
    sys.modules["incubator_mxnet_tpu.symbol.contrib"] = contrib


_install_sym_contrib()


# ---------------------------------------------------------------------------
# nd-mirror long tail: the symbol surface reuses the nd implementations
# verbatim (op fns call the nd function on NDArray-wrapped tracers inside
# the executor's jit trace — the same machinery hybridized Gluon uses), so
# sym.<op> and nd.<op> can never diverge. (reference: every nd op has a
# sym mirror generated from the same C++ op registration.)
# ---------------------------------------------------------------------------

from ..ndarray import NDArray as _NDW  # noqa: E402
from .. import ndarray as _nd_mod  # noqa: E402


def _reg_nd_mirror(opname, arg_names, n_out=None):
    def op_fn(rt, a, *raws, _op=opname):
        nd_fn = getattr(_nd_mod, _op)
        out = nd_fn(*[_NDW(r) for r in raws], **a)
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data

    register_op(opname, op_fn, arg_names, n_out=n_out)

    def builder(*args, name=None, _op=opname, _names=arg_names, **kwargs):
        ins = list(args)
        if len(ins) > len(_names):
            raise TypeError(f"{_op} takes at most {len(_names)} "
                            f"symbol inputs")
        # inputs may come as keywords (sym.ceil(data=x)) like every
        # hand-written builder; route them into the input list in order
        for i, an in enumerate(_names):
            if an in kwargs:
                if i < len(ins):
                    raise TypeError(
                        f"{_op}: got multiple values for input {an!r}")
                if len(ins) != i:
                    raise TypeError(
                        f"{_op}: input {an!r} given by keyword but earlier "
                        f"inputs are missing")
                ins.append(kwargs.pop(an))
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                raise TypeError(f"{_op}: unexpected Symbol keyword {k!r} "
                                f"(inputs are {_names})")
        return _make_op(_op, ins, _attrs(**kwargs), name)

    builder.__name__ = opname
    setattr(_sym_mod, opname, builder)
    return builder


for _n in ["ceil", "floor", "trunc", "fix", "rint", "round", "cbrt", "rcbrt",
           "reciprocal", "gammaln", "erfinv", "digamma", "expm1", "log1p", "log2",
           "log10", "sinh", "cosh", "arcsin", "arccos", "arctan", "arcsinh",
           "arccosh", "arctanh", "softsign", "isnan", "isinf", "logical_not",
           "gamma", "shape_array", "size_array"]:
    _reg_nd_mirror(_n, ("data",))

for _n in ["hypot", "arctan2", "logical_and", "logical_or", "logical_xor"]:
    _reg_nd_mirror(_n, ("lhs", "rhs"))

for _n in ["tile", "repeat", "swapaxes", "reverse", "flip", "diag", "cast",
           "one_hot", "nansum", "argmin", "norm", "sort", "argsort",
           "depth_to_space", "space_to_depth", "hard_sigmoid", "pad",
           "L2Normalization", "SequenceMask"]:
    _reg_nd_mirror(_n, ("data",))

for _n in ["take", "pick", "gather_nd", "batch_take"]:
    _reg_nd_mirror(_n, ("data", "indices"))

_reg_nd_mirror("where", ("condition", "x", "y"))
_reg_nd_mirror("topk", ("data",),
               n_out=lambda a: 2 if a.get("ret_typ") == "both" else 1)

for _n in ["broadcast_to", "cumsum", "nanprod", "radians", "degrees",
           "unravel_index", "ravel_multi_index", "softmin"]:
    _reg_nd_mirror(_n, ("data",))
_reg_nd_mirror("moments", ("data",), n_out=2)
for _n in ["maximum", "minimum", "mod"]:
    _reg_nd_mirror(_n, ("lhs", "rhs"))
_reg_nd_mirror("slice_like", ("data", "shape_like"))
_reg_nd_mirror("broadcast_like", ("data", "other"))
_reg_nd_mirror("scatter_nd", ("data", "indices"))
# generator ops: no tensor inputs, everything rides in attrs
_reg_nd_mirror("linspace", ())
_reg_nd_mirror("full", ())
_reg_nd_mirror("crop", ("data",))


def _pad_runtime(rt, a, x):
    # same single implementation as graph op "pad" (nd.pad) — the classic
    # capitalized name must not drift from the nd mirror
    return _nd_mod.pad(_NDW(x), **a)._data


register_op("Pad", _pad_runtime, ("data",))


# ---------------------------------------------------------------------------
# sym.contrib control flow: foreach / while_loop / cond
# (reference: mx.sym.contrib control-flow ops, src/operator/control_flow.cc)
#
# TPU-first: the Python body builds a SUB-GRAPH once (placeholder Variables
# stand in for the loop slice/states); execution lowers to lax.scan (with a
# liveness mask for while_loop) / lax.cond inside the executor's single
# jitted program, so the loop never unrolls and never leaves the device.
# Outer-graph symbols the body closes over (weights) become extra node
# inputs automatically. The loop body runs with its own per-step RNG key
# threaded through the scan carry (independent dropout masks per step);
# aux-state updates (BatchNorm moving stats) inside a control-flow body are
# dropped, as in inference mode.
# Control-flow graphs SERIALIZE: each body is traced into a local-index
# spec nested inside the node attrs (reference: nnvm stores subgraphs as
# attributes in the symbol JSON, src/operator/subgraph_op_common.cc), and
# load_json rebuilds the runner from the spec via the same interpreter.
# ---------------------------------------------------------------------------

from . import Variable as _Variable  # noqa: E402
from . import _OPS as _SYM_OPS  # noqa: E402
from . import _Runtime as _SubRuntime  # noqa: E402
from . import _auto_name as _sym_auto_name  # noqa: E402


def _as_sym_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


from ..base import make_loop_caller as _make_loop_caller  # noqa: E402


def _trace_subgraph(build, placeholders):
    """Call user code on placeholder symbols -> (flat output entries,
    captured outer entries, runner, spec).

    Capture is by node CREATION ORDER: every node that existed before
    `build` ran (weights, but also computed outer symbols like a Dropout
    output the body closes over) becomes a lifted input — evaluated ONCE
    in the outer graph and fed into the loop, exactly like the
    reference's subgraph inputs. Only nodes the body itself builds run
    per iteration.

    `spec` is the serializable local-index form of the body (nodes,
    heads, input arity) — nested into the symbol JSON by tojson, and the
    thing the shared _runner_from_spec interpreter executes, so traced
    and json-loaded graphs run identical code."""
    from . import _NODE_SEQ, _runner_from_spec
    mark = _NODE_SEQ[0]
    outs = build()
    entries = []
    for s in outs:
        entries.extend(s._entries)
    ph_ids = {id(p._entries[0][0]) for p in placeholders}

    # traverse the body graph, cutting off at outer nodes; record which
    # (outer_node, out_idx) entries the body actually consumes
    captured = []            # ordered (node, idx)
    cap_seen = set()
    inner_seen = set()
    inner_order = []

    def visit(node, idx):
        if id(node) in ph_ids:
            return
        # Outer nodes are lifted; so are Variables DECLARED inside the
        # body (seq > mark but is_var) — the reference lifts body-declared
        # variables as subgraph inputs too, so `sym.Variable('w')` inside
        # a foreach body binds like any other weight instead of crashing
        # the runner (it has no op to execute per-iteration).
        if node._seq <= mark or node.is_var:
            if (id(node), idx) not in cap_seen:
                cap_seen.add((id(node), idx))
                captured.append((node, idx))
            return
        if id(node) in inner_seen:
            return
        inner_seen.add(id(node))
        for n, i in node.inputs:
            visit(n, i)
        inner_order.append(node)

    for n, i in entries:
        visit(n, i)

    # serializable local-index spec: [placeholders..., captures..., inner...]
    n_ph, n_cap = len(placeholders), len(captured)
    local = {}               # id(node) -> local index (ph + inner nodes)
    cap_local = {}           # (id(node), out_idx) -> local index
    nodes_spec = []
    for i, p in enumerate(placeholders):
        n = p._entries[0][0]
        local[id(n)] = i
        nodes_spec.append({"op": "null", "name": n.name, "attrs": {},
                           "inputs": []})
    for ci, (cn, cj) in enumerate(captured):
        cap_local[(id(cn), cj)] = n_ph + ci
        nodes_spec.append({"op": "null", "name": f"__cap{ci}__",
                           "attrs": {}, "inputs": []})

    def local_entry(n, j):
        if id(n) in ph_ids:
            return [local[id(n)], 0]
        if (id(n), j) in cap_local:
            return [cap_local[(id(n), j)], 0]
        return [local[id(n)], j]

    for node in inner_order:
        local[id(node)] = len(nodes_spec)
        nodes_spec.append({
            "op": node.op, "name": node.name, "attrs": node.attrs,
            "inputs": [local_entry(n, j) for n, j in node.inputs]})
    spec = {"nodes": nodes_spec,
            "heads": [local_entry(n, j) for n, j in entries],
            "n_ph": n_ph, "n_cap": n_cap}
    return entries, captured, _runner_from_spec(spec), spec


def _foreach_fn(rt, a, *rest):
    nd_in, ns, nc = a["n_data"], a["n_states"], a["n_captured"]
    n_out = a["n_out"]
    data = rest[:nd_in]
    states0 = rest[nd_in:nd_in + ns]
    captured = rest[nd_in + ns:nd_in + ns + nc]
    runner = a["__subgraph__"]

    def step(carry, xs):
        states, key = carry
        key, sub = jax.random.split(key)
        sub_rt = _SubRuntime(rt.is_train, sub)
        outs, _ = runner(sub_rt, list(xs) + list(states) + list(captured),
                         [])
        return (tuple(outs[n_out:]), key), tuple(outs[:n_out])

    (final_states, _), outs = jax.lax.scan(
        step, (tuple(states0), rt.next_key()), tuple(data))
    return tuple(outs) + tuple(final_states)


register_op("_foreach", _foreach_fn, (),
            n_out=lambda a: a["n_out"] + a["n_states"])


def _contrib_foreach(body, data, init_states, name=None):
    """out, states = sym.contrib.foreach(body, data, init_states):
    body(slice, states) -> (outs, new_states); scans over the data's
    leading axis (reference mx.sym.contrib.foreach). `data` may be one
    Symbol or a list scanned in lockstep; single (non-list) init_states
    round-trips as a single state, like the nd.contrib counterpart."""
    name = name or _sym_auto_name("foreach")
    single_state = not isinstance(init_states, (list, tuple))
    single_data = not isinstance(data, (list, tuple))
    data_list = _as_sym_list(data)
    if not data_list:
        raise ValueError("foreach requires non-empty `data`")
    init_states = _as_sym_list(init_states)
    slice_phs = [_Variable(f"__{name}_slice{i}__")
                 for i in range(len(data_list))]
    state_phs = [_Variable(f"__{name}_state{i}__")
                 for i in range(len(init_states))]
    result = {}

    def build():
        x_arg = slice_phs[0] if single_data else list(slice_phs)
        s_arg = state_phs[0] if single_state else list(state_phs)
        outs, new_states = body(x_arg, s_arg)
        outs = _as_sym_list(outs)
        new_states = _as_sym_list(new_states)
        if len(new_states) != len(init_states):
            raise ValueError(
                f"foreach body returned {len(new_states)} states, expected "
                f"{len(init_states)}")
        result["n_out"] = len(outs)
        return outs + new_states

    entries, captured, runner, spec = _trace_subgraph(
        build, slice_phs + state_phs)
    cap_syms = [Symbol([(n, i)]) for n, i in captured]
    node_out = _make_op(
        "_foreach", data_list + init_states + cap_syms,
        {"n_data": len(data_list), "n_states": len(init_states),
         "n_captured": len(captured),
         "n_out": result["n_out"], "__subgraph__": runner,
         "__subgraph_spec__": spec}, name)
    n_out = result["n_out"]
    outs = [node_out[i] for i in range(n_out)]
    states = [node_out[i] for i in range(n_out, n_out + len(init_states))]
    return (outs[0] if n_out == 1 else outs,
            states[0] if single_state else states)


def _while_loop_fn(rt, a, *rest):
    ns, nc_c, nc_b = a["n_loop_vars"], a["n_cond_captured"], a["n_captured"]
    max_iter = a["max_iterations"]
    loop0 = rest[:ns]
    cond_cap = rest[ns:ns + nc_c]
    body_cap = rest[ns + nc_c:ns + nc_c + nc_b]
    cond_runner = a["__cond_subgraph__"]
    body_runner = a["__subgraph__"]
    n_out = a["n_out"]

    def cond_val(sub_rt, lv):
        (c,), _ = cond_runner(sub_rt, list(lv) + list(cond_cap), [])
        return c.astype(jnp.bool_).reshape(())

    def step(carry, _):
        lv, key = carry
        key, k1, k2 = jax.random.split(key, 3)
        alive = cond_val(_SubRuntime(rt.is_train, k1), lv)

        def run_body(args):
            lv_, k_ = args
            outs, _ = body_runner(_SubRuntime(rt.is_train, k_),
                                  list(lv_) + list(body_cap), [])
            return tuple(outs[n_out:]), tuple(outs[:n_out])

        def skip_body(args):
            # dead iteration: the body NEVER executes (lax.cond takes one
            # branch), so out-of-domain math past termination can't
            # poison values or gradients with NaNs
            lv_, _ = args
            shapes = jax.eval_shape(run_body, args)
            return lv_, tuple(jnp.zeros(s.shape, s.dtype)
                              for s in shapes[1])

        lv, step_outs = jax.lax.cond(alive, run_body, skip_body, (lv, k2))
        return (lv, key), step_outs

    (final_lv, _), outs = jax.lax.scan(
        step, (tuple(loop0), rt.next_key()), None, length=max_iter)
    return tuple(outs) + tuple(final_lv)


register_op("_while_loop", _while_loop_fn, (),
            n_out=lambda a: a["n_out"] + a["n_loop_vars"])


def _contrib_while_loop(cond, func, loop_vars, max_iterations, name=None):
    """outputs, final_loop_vars = sym.contrib.while_loop(cond, func,
    loop_vars, max_iterations): runs func while cond is true; per-step
    outputs are stacked over a fixed max_iterations axis (iterations past
    termination are zero) — the static-shape contract XLA needs, same as
    the reference's padded outputs.

    Calling convention: with multiple loop vars, cond/func written against
    upstream MXNet (`def func(a, b)`, called as func(*loop_vars)) AND this
    repo's list convention (`def func(vs)`) are both supported — the
    signature decides (see base.make_loop_caller)."""
    name = name or _sym_auto_name("while_loop")
    single_var = not isinstance(loop_vars, (list, tuple))
    loop_vars = _as_sym_list(loop_vars)
    phs = [_Variable(f"__{name}_var{i}__") for i in range(len(loop_vars))]
    call_cond = _make_loop_caller(cond, len(loop_vars), single_var)
    call_func = _make_loop_caller(func, len(loop_vars), single_var)
    result = {}

    def build_cond():
        return [call_cond(phs)]

    c_entries, c_captured, c_runner, c_spec = _trace_subgraph(
        build_cond, phs)

    def build_body():
        outs, new_vars = call_func(phs)
        outs = _as_sym_list(outs)
        new_vars = _as_sym_list(new_vars)
        if len(new_vars) != len(loop_vars):
            raise ValueError(
                f"while_loop body returned {len(new_vars)} loop vars, "
                f"expected {len(loop_vars)}")
        result["n_out"] = len(outs)
        return outs + new_vars

    b_entries, b_captured, b_runner, b_spec = _trace_subgraph(
        build_body, phs)
    cap_syms = ([Symbol([(n, i)]) for n, i in c_captured]
                + [Symbol([(n, i)]) for n, i in b_captured])
    node_out = _make_op(
        "_while_loop", loop_vars + cap_syms,
        {"n_loop_vars": len(loop_vars), "n_cond_captured": len(c_captured),
         "n_captured": len(b_captured), "n_out": result["n_out"],
         "max_iterations": int(max_iterations),
         "__cond_subgraph__": c_runner, "__cond_subgraph_spec__": c_spec,
         "__subgraph__": b_runner, "__subgraph_spec__": b_spec}, name)
    n_out = result["n_out"]
    outs = [node_out[i] for i in range(n_out)]
    final = [node_out[i] for i in range(n_out, n_out + len(loop_vars))]
    return (outs[0] if n_out == 1 else outs,
            final[0] if single_var else final)


def _cond_fn(rt, a, pred, *rest):
    nt, ne = a["n_then_captured"], a["n_else_captured"]
    then_cap = rest[:nt]
    else_cap = rest[nt:nt + ne]
    then_runner = a["__subgraph__"]
    else_runner = a["__else_subgraph__"]

    def then_branch(_):
        outs, _ = then_runner(rt, list(then_cap), [])
        return tuple(outs)

    def else_branch(_):
        outs, _ = else_runner(rt, list(else_cap), [])
        return tuple(outs)

    return jax.lax.cond(pred.astype(jnp.bool_).reshape(()),
                        then_branch, else_branch, None)


register_op("_cond", _cond_fn, ("pred",), n_out=lambda a: a["n_out"])


def _contrib_cond(pred, then_func, else_func, name=None):
    """sym.contrib.cond(pred, then_func, else_func): lowers to lax.cond —
    both branches compiled, one executed on device. Branch outputs must
    match in count/shape (XLA static-shape contract, like the
    reference)."""
    name = name or _sym_auto_name("cond")
    t_entries, t_captured, t_runner, t_spec = _trace_subgraph(
        lambda: _as_sym_list(then_func()), [])
    e_entries, e_captured, e_runner, e_spec = _trace_subgraph(
        lambda: _as_sym_list(else_func()), [])
    n_out = len(t_entries)
    if n_out != len(e_entries):
        raise ValueError(f"cond branches return {n_out} vs "
                         f"{len(e_entries)} outputs; they must match")
    cap_syms = ([Symbol([(n, i)]) for n, i in t_captured]
                + [Symbol([(n, i)]) for n, i in e_captured])
    node_out = _make_op(
        "_cond", [pred] + cap_syms,
        {"n_then_captured": len(t_captured),
         "n_else_captured": len(e_captured), "n_out": n_out,
         "__subgraph__": t_runner, "__subgraph_spec__": t_spec,
         "__else_subgraph__": e_runner, "__else_subgraph_spec__": e_spec},
        name)
    return node_out if n_out > 1 else node_out[0]


_sym_mod.contrib.foreach = _contrib_foreach
_sym_mod.contrib.while_loop = _contrib_while_loop
_sym_mod.contrib.cond = _contrib_cond


# ---------------------------------------------------------------------------
# autograd.get_symbol support: tape -> Symbol lifting (reference
# python/mxnet/autograd.py get_symbol). Each recorded eager op replays as a
# graph node executing the same pure function.
# ---------------------------------------------------------------------------

register_op("_traced_fn",
            lambda rt, a, *ins: a["__fn__"](*ins),
            (), n_out=lambda a: a.get("n_out", 1))
register_op("_traced_const", lambda rt, a: a["__value__"], ())
