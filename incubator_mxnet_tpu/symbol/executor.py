"""Executor: compiled evaluation of a bound Symbol graph.

Parity: python/mxnet/executor.py + src/executor/graph_executor.cc. Where the
reference interprets the nnvm graph through the threaded engine, `bind` here
closes the graph over its argument order and compiles ONE jitted forward and
ONE jitted backward (vjp) executable per training mode — forward+backward
each run as a single fused XLA computation on the TPU.

The same rng key is threaded into forward and backward so stochastic ops
(Dropout) use identical masks in both passes, matching the reference's
cached-mask backward.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from ..context import current_context
from ..ndarray import NDArray
from . import _OPS, _Runtime, _aux_positions, _num_outputs, _topo

__all__ = ["Executor", "simple_bind"]


def _graph_runner(entries, arg_nodes, aux_nodes):
    """Build run(rt, arg_raws, aux_raws) -> (outputs, new_aux) over the DAG."""
    order = _topo(entries)
    arg_ids = [id(n) for n in arg_nodes]
    aux_ids = [id(n) for n in aux_nodes]

    def run(rt, arg_raws, aux_raws):
        env = {}
        for nid, raw in zip(arg_ids, arg_raws):
            env[(nid, 0)] = raw
        for nid, raw in zip(aux_ids, aux_raws):
            env[(nid, 0)] = raw
        for node in order:
            if node.is_var:
                if (id(node), 0) not in env:
                    raise ValueError(f"unbound variable {node.name!r}")
                continue
            od = _OPS[node.op]
            ins = [env[(id(n), i)] for n, i in node.inputs]
            res = od.fn(rt, node.attrs, *ins)
            res = res if isinstance(res, tuple) else (res,)
            n_real = _num_outputs(node)
            aux_pos = _aux_positions(od, node.attrs)
            if aux_pos:
                for pos, new in zip(aux_pos, res[n_real:]):
                    rt.aux_updates[id(node.inputs[pos][0])] = new
                res = res[:n_real]
            for i, r in enumerate(res):
                env[(id(node), i)] = r
        outs = tuple(env[(id(n), i)] for n, i in entries)
        new_aux = tuple(rt.aux_updates.get(nid, env[(nid, 0)])
                        for nid in aux_ids)
        return outs, new_aux

    return run


class Executor:
    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self.arg_dict = OrderedDict()
        self.aux_dict = OrderedDict()

        if isinstance(args, dict):
            for n in arg_names:
                if n not in args:
                    raise ValueError(f"missing argument {n!r}")
                self.arg_dict[n] = _as_nd(args[n])
        elif args is not None:
            for n, a in zip(arg_names, args):
                self.arg_dict[n] = _as_nd(a)
        else:
            raise ValueError("bind needs args; use simple_bind to allocate")

        if isinstance(aux_states, dict):
            for n in aux_names:
                self.aux_dict[n] = _as_nd(aux_states[n])
        elif aux_states is not None:
            for n, a in zip(aux_names, aux_states):
                self.aux_dict[n] = _as_nd(a)
        else:
            for n in aux_names:
                raise ValueError(f"missing auxiliary state {n!r}")

        # grad_req: str | list | dict
        if isinstance(grad_req, str):
            self._req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._req = dict(zip(arg_names, grad_req))
        else:
            self._req = {n: grad_req.get(n, "null") for n in arg_names}

        self.grad_dict = OrderedDict()
        if isinstance(args_grad, dict):
            for n in arg_names:
                if self._req[n] != "null":
                    self.grad_dict[n] = _as_nd(
                        args_grad.get(n, np.zeros(self.arg_dict[n].shape,
                                                  dtype=np.float32)))
        else:
            if args_grad is not None:
                for n, g in zip(arg_names, args_grad):
                    if self._req[n] != "null":
                        self.grad_dict[n] = _as_nd(g)
            for n in arg_names:
                if self._req[n] != "null" and n not in self.grad_dict:
                    a = self.arg_dict[n]
                    self.grad_dict[n] = NDArray(jnp.zeros(a.shape, a._data.dtype))

        self._arg_names = arg_names
        self._aux_names = aux_names
        self._train_names = [n for n in arg_names if self._req[n] != "null"]
        self._fixed_names = [n for n in arg_names if self._req[n] == "null"]

        order = _topo(symbol._entries)
        var_by_name = {n.name: n for n in order if n.is_var}
        self._run = _graph_runner(symbol._entries,
                                  [var_by_name[n] for n in arg_names],
                                  [var_by_name[n] for n in aux_names])
        self._fwd_jit = {}
        self._bwd_jit = {}
        self.outputs = []
        self._last = None   # (is_train, key) of the last forward

    # -- forward ----------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise ValueError(f"unknown input {k!r}")
            self.arg_dict[k] = _as_nd(v)
        is_train = bool(is_train)
        if is_train not in self._fwd_jit:
            run = self._run

            def fwd(arg_raws, aux_raws, key, _t=is_train):
                return run(_Runtime(_t, key), arg_raws, aux_raws)

            self._fwd_jit[is_train] = jax.jit(fwd)
        key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        arg_raws = [self.arg_dict[n]._data for n in self._arg_names]
        aux_raws = [self.aux_dict[n]._data for n in self._aux_names]
        outs, new_aux = self._fwd_jit[is_train](arg_raws, aux_raws, key)
        if is_train:
            for n, new in zip(self._aux_names, new_aux):
                self.aux_dict[n]._data = new
        self.outputs = [NDArray(o) for o in outs]
        self._last = (is_train, key)
        return self.outputs

    # -- backward ---------------------------------------------------------
    def backward(self, out_grads=None):
        if self._last is None:
            raise RuntimeError("call forward(is_train=True) before backward()")
        is_train, key = self._last
        if is_train not in self._bwd_jit:
            run = self._run
            n_train = len(self._train_names)
            arg_names, train_names = self._arg_names, self._train_names
            fixed_names = self._fixed_names
            train_pos = [arg_names.index(n) for n in train_names]
            fixed_pos = [arg_names.index(n) for n in fixed_names]

            def bwd(train_raws, fixed_raws, aux_raws, key, cots, _t=is_train):
                def f(*train_raws_):
                    raws = [None] * len(arg_names)
                    for p, r in zip(train_pos, train_raws_):
                        raws[p] = r
                    for p, r in zip(fixed_pos, fixed_raws):
                        raws[p] = r
                    outs, _ = run(_Runtime(_t, key), raws, aux_raws)
                    return outs

                _, pull = jax.vjp(f, *train_raws)
                return pull(tuple(cots))

            self._bwd_jit[is_train] = jax.jit(bwd)
        if out_grads is None:
            cots = [jnp.ones(o.shape, o._data.dtype) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                    for g in out_grads]
        train_raws = [self.arg_dict[n]._data for n in self._train_names]
        fixed_raws = [self.arg_dict[n]._data for n in self._fixed_names]
        aux_raws = [self.aux_dict[n]._data for n in self._aux_names]
        grads = self._bwd_jit[is_train](train_raws, fixed_raws, aux_raws, key,
                                        cots)
        for n, g in zip(self._train_names, grads):
            if self._req[n] == "add":
                self.grad_dict[n]._data = self.grad_dict[n]._data + g
            else:
                self.grad_dict[n]._data = g
        return [self.grad_dict[n] for n in self._train_names]

    # -- views ------------------------------------------------------------
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = _as_nd(v)._data
            elif not allow_extra_params:
                raise ValueError(f"unknown argument {k!r}")
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k]._data = _as_nd(v)._data
                elif not allow_extra_params:
                    raise ValueError(f"unknown aux state {k!r}")


def _as_nd(x):
    if isinstance(x, NDArray):
        return x
    return NDArray(jnp.asarray(x))


def simple_bind(symbol, ctx=None, grad_req="write", type_dict=None, **kwargs):
    """Infer every argument/aux shape from the given input shapes and
    allocate zero-filled arrays (parity: Symbol.simple_bind)."""
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    type_dict = type_dict or {}
    args, auxs = {}, {}
    for n, s in zip(arg_names, arg_shapes):
        if s is None:
            raise ValueError(f"could not infer shape for argument {n!r}; "
                             f"pass its shape to simple_bind")
        dt = type_dict.get(n, jnp.float32)
        args[n] = NDArray(jnp.zeros(s, dt))
    for n, s in zip(aux_names, aux_shapes):
        if s is None:
            raise ValueError(f"could not infer shape for aux state {n!r}")
        auxs[n] = NDArray(jnp.zeros(s, type_dict.get(n, jnp.float32)))
    return Executor(symbol, ctx, args, None, grad_req, auxs)
