"""Symbol API (parity: python/mxnet/symbol/symbol.py, nnvm graph).

A Symbol is an immutable DAG of pure ops over named Variables. Where the
reference lowers the graph through nnvm into a C++ Executor, here `bind`
traces the graph ONCE into a single `jax.jit` computation (forward) and a
jitted `jax.vjp` pullback (backward) — the whole symbolic program becomes
one fused XLA executable per signature, which is the TPU-native meaning of
`simple_bind`.

Key surfaces (reference: python/mxnet/symbol/symbol.py):
  sym.Variable / sym.var, op mirrors (FullyConnected, Convolution, ...),
  arithmetic operators, infer_shape / infer_type, list_arguments /
  list_outputs / list_auxiliary_states, Group, tojson / load_json,
  bind / simple_bind -> executor.Executor.

Classic output ops (SoftmaxOutput, LinearRegressionOutput, ...) keep their
reference backward semantics (src/operator/softmax_output.cc: grad =
p - one_hot(label), ignoring head gradients) via `jax.custom_vjp`.
"""
from __future__ import annotations

import json as _json

import numpy as np

import jax
import jax.numpy as jnp

from ..base import normalize_dtype
from ..ops import _raw

__all__ = ["Symbol", "Variable", "var", "Group", "load_json", "load"]


# ---------------------------------------------------------------------------
# graph model
# ---------------------------------------------------------------------------

_NODE_SEQ = [0]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "is_aux", "user_attrs",
                 "_seq")

    def __init__(self, op, name, attrs=None, inputs=(), is_aux=False):
        self.op = op                    # None for variables
        self.name = name
        self.attrs = dict(attrs or {})  # op hyper-params (json-serializable)
        self.inputs = list(inputs)      # list of (node, out_index)
        self.is_aux = is_aux            # variable holds auxiliary state
        self.user_attrs = {}            # __attrs__ from user (lr_mult etc.)
        # creation order: lets control-flow subgraph tracing tell outer
        # (pre-existing) nodes from ones the loop body just built
        _NODE_SEQ[0] += 1
        self._seq = _NODE_SEQ[0]

    @property
    def is_var(self):
        return self.op is None


_NAME_COUNTER = {}


def _auto_name(hint):
    i = _NAME_COUNTER.get(hint, 0)
    _NAME_COUNTER[hint] = i + 1
    return f"{hint}{i}"


def _topo(entries):
    """Topological order of nodes reachable from output entries.
    Iterative: graphs lifted from eager loops (autograd.get_symbol) can be
    thousands of nodes deep, past Python's recursion limit."""
    seen, order = set(), []
    stack = [(n, False) for n, _ in reversed(list(entries))]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for n, _ in reversed(node.inputs):
            if id(n) not in seen:
                stack.append((n, False))
    return order


# ---------------------------------------------------------------------------
# op registry
# ---------------------------------------------------------------------------

class _OpDef:
    __slots__ = ("name", "fn", "arg_names", "aux_pos", "n_out", "infer_hint")

    def __init__(self, name, fn, arg_names, aux_pos=(), n_out=None,
                 infer_hint=None):
        self.name = name
        self.fn = fn                  # fn(rt, attrs, *raw_inputs) -> raw | tuple
        self.arg_names = arg_names    # suffixes for auto-created inputs
        # static tuple, or callable(attrs)->tuple for ops whose aux input
        # positions depend on the node (Custom: after the prop's arguments)
        self.aux_pos = aux_pos if callable(aux_pos) else tuple(aux_pos)
        self.n_out = n_out            # None=1, or callable(attrs)->int
        self.infer_hint = infer_hint  # (in_shapes, attrs) -> partial fills


_OPS: dict[str, _OpDef] = {}


def register_op(name, fn, arg_names, aux_pos=(), n_out=None, infer_hint=None):
    _OPS[name] = _OpDef(name, fn, arg_names, aux_pos, n_out, infer_hint)


def _num_outputs(node):
    od = _OPS[node.op]
    if od.n_out is None:
        return 1
    return od.n_out(node.attrs) if callable(od.n_out) else od.n_out


def _aux_positions(od, attrs):
    return tuple(od.aux_pos(attrs)) if callable(od.aux_pos) else od.aux_pos


class _Runtime:
    """Per-execution context threaded through op fns: train flag + rng."""

    __slots__ = ("is_train", "_key", "aux_updates")

    def __init__(self, is_train, key):
        self.is_train = is_train
        self._key = key
        self.aux_updates = {}     # id(var_node) -> new raw value

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub


# ---------------------------------------------------------------------------
# Symbol
# ---------------------------------------------------------------------------

class Symbol:
    """Handle to one or more output entries of the graph."""

    __slots__ = ("_entries",)

    def __init__(self, entries):
        self._entries = list(entries)

    # -- identity ---------------------------------------------------------
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def attr(self, key):
        return self._entries[0][0].user_attrs.get(key)

    def _set_attr(self, **kwargs):
        self._entries[0][0].user_attrs.update(kwargs)
        return self

    def list_attr(self):
        return dict(self._entries[0][0].user_attrs)

    def attr_dict(self):
        """{node_name: user attrs} over the whole reachable graph
        (reference symbol.attr_dict)."""
        out = {}
        for node in _topo(self._entries):
            if node.user_attrs:
                out[node.name] = dict(node.user_attrs)
        return out

    def __repr__(self):
        outs = ", ".join(self._out_names())
        return f"<Symbol {outs}>"

    # -- graph queries ----------------------------------------------------
    def list_arguments(self):
        return [n.name for n in _topo(self._entries) if n.is_var and not n.is_aux]

    def list_auxiliary_states(self):
        return [n.name for n in _topo(self._entries) if n.is_var and n.is_aux]

    def _out_names(self):
        names = []
        for node, idx in self._entries:
            base = node.name
            if node.is_var:
                names.append(base)
            elif _num_outputs(node) > 1:
                names.append(f"{base}_output{idx}")
            else:
                names.append(f"{base}_output")
        return names

    def list_outputs(self):
        return self._out_names()

    def list_inputs(self):
        return [n.name for n in _topo(self._entries) if n.is_var]

    def get_internals(self):
        """All node outputs as a grouped Symbol (parity: sym.get_internals)."""
        entries = []
        for node in _topo(self._entries):
            for i in range(1 if node.is_var else _num_outputs(node)):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    def __getitem__(self, index):
        if isinstance(index, str):
            for node, i in self.get_internals()._entries:
                names = Symbol([(node, i)])._out_names()
                if names[0] == index or node.name == index:
                    return Symbol([(node, i)])
            raise ValueError(f"no output named {index!r}")
        return Symbol([self._entries[index]])

    def __len__(self):
        return len(self._entries)

    def __iter__(self):
        return (Symbol([e]) for e in self._entries)

    # -- arithmetic -------------------------------------------------------
    def __add__(self, other):
        return _elemwise("_plus", self, other)

    def __radd__(self, other):
        return _elemwise("_plus", self, other)

    def __sub__(self, other):
        return _elemwise("_minus", self, other)

    def __rsub__(self, other):
        return _elemwise("_rminus", self, other)

    def __mul__(self, other):
        return _elemwise("_mul", self, other)

    def __rmul__(self, other):
        return _elemwise("_mul", self, other)

    def __truediv__(self, other):
        return _elemwise("_div", self, other)

    def __rtruediv__(self, other):
        return _elemwise("_rdiv", self, other)

    def __pow__(self, other):
        return _elemwise("_power", self, other)

    def __neg__(self):
        return _make_op("negative", [self])

    # -- shape / type inference ------------------------------------------
    def infer_shape(self, **kwargs):
        """Forward shape inference + parameter-shape filling.

        Mirrors the reference's nnvm InferShape pass: data shapes in, every
        argument/output/aux shape out (layer hints fill weight shapes the
        way deferred shape inference does in Gluon).
        """
        shapes, dtypes = self._infer(kwargs, {})
        args = [shapes.get(n) for n in self.list_arguments()]
        auxs = [shapes.get(n) for n in self.list_auxiliary_states()]
        outs = [shapes.get(e) for e in self._entry_keys()]
        return args, outs, auxs

    def infer_type(self, **kwargs):
        """Dtype propagation without shapes: unknown variables adopt the
        promoted dtype of their consumers' known inputs (the common
        same-dtype rule of the reference's InferType pass)."""
        order = _topo(self._entries)
        dt = {}
        for node in order:
            if node.is_var and node.name in kwargs:
                dt[id(node)] = np.dtype(normalize_dtype(kwargs[node.name]))
        for _ in range(len(order) + 1):
            progress = False
            for node in order:
                if node.is_var:
                    continue
                in_dts = [dt.get(id(n)) for n, _ in node.inputs]
                known = [d for d in in_dts if d is not None]
                if not known:
                    continue
                prom = known[0]
                for d in known[1:]:
                    prom = np.promote_types(prom, d)
                for (n, _), d in zip(node.inputs, in_dts):
                    if d is None and id(n) not in dt:
                        dt[id(n)] = prom
                        progress = True
                if id(node) not in dt:
                    dt[id(node)] = prom
                    progress = True
            if not progress:
                break
        name2dt = {n.name: dt.get(id(n)) for n in order if n.is_var}
        args = [name2dt.get(n) for n in self.list_arguments()]
        auxs = [name2dt.get(n) for n in self.list_auxiliary_states()]
        outs = [dt.get(id(n)) for n, _ in self._entries]
        return args, outs, auxs

    def _entry_keys(self):
        return [(id(n), i) for n, i in self._entries]

    def _infer(self, shape_kwargs, dtype_kwargs):
        """Iterate: hint-fill variable shapes, then eval_shape ops whose
        inputs are fully known. Returns ({name|entrykey: shape}, {...: dtype})."""
        order = _topo(self._entries)
        var_shape = dict(shape_kwargs)
        var_dtype = {k: normalize_dtype(v) for k, v in dtype_kwargs.items()}
        # Variable(shape=..., dtype=...) declarations participate in
        # inference (reference: nnvm reads the node's __shape__ attr);
        # explicit kwargs win over declared attrs
        for node in order:
            if not node.is_var:
                continue
            ushape = node.user_attrs.get("__shape__")
            if ushape is not None and node.name not in var_shape:
                var_shape[node.name] = tuple(ushape)
            udt = node.user_attrs.get("__dtype__")
            if udt is not None and node.name not in var_dtype:
                var_dtype[node.name] = normalize_dtype(udt)
        known = {}   # (id(node), idx) -> jax.ShapeDtypeStruct

        for _ in range(len(order) + 2):   # fixed-point; graph is a DAG
            progress = False
            for node in order:
                if node.is_var:
                    key = (id(node), 0)
                    if key not in known and node.name in var_shape:
                        dt = var_dtype.get(node.name, jnp.float32)
                        known[key] = jax.ShapeDtypeStruct(
                            tuple(var_shape[node.name]), dt)
                        progress = True
                    continue
                od = _OPS[node.op]
                in_specs = [known.get((id(n), i)) for n, i in node.inputs]
                if any(s is None for s in in_specs) and od.infer_hint:
                    fills = od.infer_hint(
                        [None if s is None else s.shape for s in in_specs],
                        node.attrs)
                    if fills:
                        for pos, shape in fills.items():
                            n, i = node.inputs[pos]
                            if n.is_var and n.name not in var_shape:
                                var_shape[n.name] = tuple(shape)
                                progress = True
                    in_specs = [known.get((id(n), i)) for n, i in node.inputs]
                if any(s is None for s in in_specs):
                    continue
                if (id(node), 0) in known:
                    continue
                rt = _Runtime(False, jax.random.PRNGKey(0))
                out = jax.eval_shape(
                    lambda *raws, _n=node, _rt=rt: _OPS[_n.op].fn(_rt, _n.attrs, *raws),
                    *in_specs)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                n_real = _num_outputs(node)
                for i in range(n_real):
                    known[(id(node), i)] = outs[i]
                progress = True
            if not progress:
                break

        shapes, dtypes = {}, {}
        for node in order:
            if node.is_var:
                spec = known.get((id(node), 0))
                if spec is not None:
                    shapes[node.name] = tuple(spec.shape)
                    dtypes[node.name] = spec.dtype
                elif node.name in var_shape:
                    shapes[node.name] = tuple(var_shape[node.name])
        for node, i in self._entries:
            spec = known.get((id(node), i))
            if spec is not None:
                shapes[(id(node), i)] = tuple(spec.shape)
                dtypes[(id(node), i)] = spec.dtype
        return shapes, dtypes

    # -- evaluation -------------------------------------------------------
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None):
        from .executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None, **kwargs):
        from .executor import simple_bind
        return simple_bind(self, ctx, grad_req, type_dict, **kwargs)

    def eval(self, ctx=None, **kwargs):
        """One-shot evaluation: bind with the given arrays and run forward."""
        ex = self.bind(ctx, args=kwargs, grad_req="null")
        return ex.forward(is_train=False)

    # -- serialization ----------------------------------------------------
    def tojson(self):
        """Graph JSON (same role as the reference's nnvm::Graph json)."""
        order = _topo(self._entries)
        idx = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            nodes.append({
                "op": "null" if n.is_var else n.op,
                "name": n.name,
                "attrs": _jsonable(n.attrs),
                "inputs": [[idx[id(m)], i] for m, i in n.inputs],
                "is_aux": n.is_aux,
                "user_attrs": _jsonable(n.user_attrs),
            })
        heads = [[idx[id(n)], i] for n, i in self._entries]
        return _json.dumps({"nodes": nodes, "heads": heads,
                            "format": "incubator_mxnet_tpu-symbol-v1"},
                           indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())


# Control-flow nodes carry their traced body twice: a runner CLOSURE (the
# executable, under a _RUNNER key) and a serializable SPEC (nested graph
# json, under the paired _SPEC key) — mirroring the reference, which stores
# subgraphs as attributes inside the symbol JSON
# (src/operator/subgraph_op_common.cc). tojson emits the spec and drops the
# closure; load_json rebuilds the closure from the spec with
# _runner_from_spec, the same interpreter used at trace time.
_RUNNER_TO_SPEC = {"__subgraph__": "__subgraph_spec__",
                   "__cond_subgraph__": "__cond_subgraph_spec__",
                   "__else_subgraph__": "__else_subgraph_spec__"}
_SPEC_KEYS = frozenset(_RUNNER_TO_SPEC.values())


def _jsonable(d):
    out = {}
    for k, v in d.items():
        if callable(v):
            if _RUNNER_TO_SPEC.get(k) in d:
                continue                      # serialized via its spec
            raise NotImplementedError(
                "graph attribute {!r} is a callable with no serializable "
                "subgraph spec; this graph cannot be saved to json".format(k))
        if k in _SPEC_KEYS:
            v = _spec_jsonable(v)
        elif isinstance(v, tuple):
            v = list(v)
        out[k] = v
    return out


def _spec_jsonable(spec):
    return {"nodes": [{"op": n["op"], "name": n["name"],
                       "attrs": _jsonable(n["attrs"]),
                       "inputs": n["inputs"]} for n in spec["nodes"]],
            "heads": spec["heads"],
            "n_ph": spec["n_ph"], "n_cap": spec["n_cap"]}


def _runner_from_spec(spec):
    """Interpreter over a subgraph spec (local-index node list): executes
    the inner nodes with the registered op implementations. Used both for
    freshly traced control-flow bodies and for bodies rebuilt from JSON,
    so a save/load round trip runs the identical code path."""
    nodes = spec["nodes"]
    n_in = spec["n_ph"] + spec["n_cap"]
    heads = [tuple(h) for h in spec["heads"]]

    def runner(rt, arg_raws, _aux_unused):
        env = {}
        for i in range(n_in):
            env[(i, 0)] = arg_raws[i]
        for li in range(n_in, len(nodes)):
            nd_ = nodes[li]
            od = _OPS[nd_["op"]]
            ins = [env[(i, j)] for i, j in nd_["inputs"]]
            res = od.fn(rt, nd_["attrs"], *ins)
            res = res if isinstance(res, tuple) else (res,)
            for j, r in enumerate(res):
                env[(li, j)] = r
        return tuple(env[h] for h in heads), ()

    return runner


def _attrs_from_json(d):
    """Node attrs, JSON form -> executable form: lists back to tuples,
    control-flow runners rebuilt from their specs. Single decode rule for
    top-level graphs (load_json) and nested subgraph specs (_load_spec)."""
    attrs = {k: tuple(v) if isinstance(v, list) else v
             for k, v in d.items()}
    _rebuild_runners(attrs)
    return attrs


def _load_spec(spec):
    """JSON form of a subgraph spec -> executable form."""
    nodes = [{"op": nd_["op"], "name": nd_["name"],
              "attrs": _attrs_from_json(nd_.get("attrs", {})),
              "inputs": [tuple(i) for i in nd_["inputs"]]}
             for nd_ in spec["nodes"]]
    return {"nodes": nodes, "heads": spec["heads"],
            "n_ph": spec["n_ph"], "n_cap": spec["n_cap"]}


def _rebuild_runners(attrs):
    """Rebuild runner closures for any subgraph specs present in attrs
    (recursing through nested control flow)."""
    for rk, sk in _RUNNER_TO_SPEC.items():
        if sk in attrs and rk not in attrs:
            spec = attrs[sk]
            if isinstance(spec, dict) and "nodes" in spec:
                loaded = _load_spec(spec)
                attrs[sk] = loaded
                attrs[rk] = _runner_from_spec(loaded)


def load_json(json_str):
    import re as _re
    data = _json.loads(json_str)
    # Bump auto-name counters past loaded names so new ops composed onto a
    # loaded graph in a fresh process cannot collide with them.
    for nd_ in data["nodes"]:
        m = _re.fullmatch(r"([a-z_]+?)(\d+)", nd_["name"])
        if m:
            hint, i = m.group(1), int(m.group(2))
            if _NAME_COUNTER.get(hint, 0) <= i:
                _NAME_COUNTER[hint] = i + 1
    nodes = []
    for nd_ in data["nodes"]:
        op = None if nd_["op"] == "null" else nd_["op"]
        attrs = _attrs_from_json(nd_.get("attrs", {}))
        node = _Node(op, nd_["name"], attrs,
                     [(nodes[i], j) for i, j in nd_.get("inputs", [])],
                     is_aux=nd_.get("is_aux", False))
        node.user_attrs = dict(nd_.get("user_attrs", {}))
        nodes.append(node)
    return Symbol([(nodes[i], j) for i, j in data["heads"]])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------

class AttrScope:
    """Parity: mx.AttrScope / python/mxnet/attribute.py — `with
    AttrScope(lr_mult="2", __group__="stage1"):` applies the attrs to every
    Variable created inside the scope (nested scopes merge, inner wins)."""

    _stack: list = []

    def __init__(self, **attrs):
        self._attrs = attrs

    def __enter__(self):
        AttrScope._stack.append(self._attrs)
        return self

    def __exit__(self, *exc):
        AttrScope._stack.pop()
        return False

    @staticmethod
    def current_attrs():
        merged = {}
        for frame in AttrScope._stack:
            merged.update(frame)
        return merged


def Variable(name, shape=None, dtype=None, init=None, lr_mult=None,
             wd_mult=None, **kwargs):
    node = _Node(None, name)
    node.user_attrs.update(AttrScope.current_attrs())
    if shape is not None:
        node.user_attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        node.user_attrs["__dtype__"] = str(np.dtype(normalize_dtype(dtype)))
    if lr_mult is not None:
        node.user_attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        node.user_attrs["__wd_mult__"] = wd_mult
    if init is not None:
        node.user_attrs["__init__"] = (init.to_attr_str()
                                       if hasattr(init, "to_attr_str")
                                       else str(init))
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def zeros(shape, dtype="float32", name=None):
    return _make_op("_zeros", [], attrs={"shape": tuple(shape), "dtype": str(dtype)},
                    name=name)


def ones(shape, dtype="float32", name=None):
    return _make_op("_ones", [], attrs={"shape": tuple(shape), "dtype": str(dtype)},
                    name=name)


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", name=None):
    """Parity: mx.sym.arange (src/operator/tensor/init_op.cc)."""
    return _make_op("_arange", [],
                    attrs={"start": float(start),
                           "stop": None if stop is None else float(stop),
                           "step": float(step), "repeat": int(repeat),
                           "dtype": str(dtype)}, name=name)


# ---------------------------------------------------------------------------
# op application
# ---------------------------------------------------------------------------

def _make_op(op, inputs, attrs=None, name=None):
    """Create an op node. `inputs` are Symbols (single-entry) or None for
    auto-created parameter variables (named {name}_{argname}, like the
    reference's auto `fc1_weight`)."""
    od = _OPS[op]
    name = name or _auto_name(op.lower().lstrip("_"))
    aux_pos = _aux_positions(od, attrs or {})
    entries = []
    for pos, s in enumerate(inputs):
        if s is None:
            argname = od.arg_names[pos] if pos < len(od.arg_names) else f"in{pos}"
            vnode = _Node(None, f"{name}_{argname}", is_aux=pos in aux_pos)
            entries.append((vnode, 0))
        else:
            if len(s._entries) != 1:
                raise ValueError(f"op {op} input {pos}: expected single-output "
                                 f"symbol, got {len(s._entries)} outputs")
            node, idx = s._entries[0]
            if pos in aux_pos and node.is_var:
                node.is_aux = True
            entries.append((node, idx))
    node = _Node(op, name, attrs or {}, entries)
    n_out = _num_outputs(node)
    return Symbol([(node, i) for i in range(n_out)])


def _elemwise(op, lhs, rhs):
    if isinstance(rhs, Symbol):
        return _make_op(op, [lhs, rhs])
    from ..gluon.symbolize import active_scope, to_input
    if active_scope() is not None and hasattr(rhs, "_data"):
        # NDArray operand during Gluon symbol tracing: registered params
        # become named Variables (even 1-element ones — float() would bake
        # the current value into the graph as a constant, detaching the
        # parameter from checkpoints); in-forward constants raise clearly.
        return _make_op(op, [lhs, to_input(rhs)])
    return _make_op(op + "_scalar", [lhs], attrs={"scalar": float(rhs)})


from . import _register  # noqa: E402,F401  (populates the op registry)
from .executor import Executor  # noqa: E402
