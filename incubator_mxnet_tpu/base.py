"""Shared helpers: dtype normalization, registries, name management.

Reference parity: python/mxnet/base.py (registries, name manager) — minus the
ctypes handle plumbing, which XLA makes unnecessary on the compute path.
"""
from __future__ import annotations

import re
import threading

import jax.numpy as jnp
import numpy as np

_DTYPE_ALIASES = {
    "float32": jnp.float32, "float64": jnp.float64, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "uint8": jnp.uint8, "int8": jnp.int8,
    "int32": jnp.int32, "int64": jnp.int64, "bool": jnp.bool_,
    "uint16": jnp.uint16, "uint32": jnp.uint32, "uint64": jnp.uint64,
    "int16": jnp.int16,
}


def normalize_dtype(dtype):
    """Accept strings, numpy dtypes, jnp dtypes; return a numpy dtype object."""
    if dtype is None:
        return np.dtype("float32")
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
    return np.dtype(dtype)


class _Registry:
    """String-keyed registry with `register` decorator and `create` factory
    (parity with mx.operator/optimizer/initializer registries)."""

    def __init__(self, kind):
        self.kind = kind
        self._map = {}

    def register(self, name=None):
        def deco(cls):
            key = (name or cls.__name__).lower()
            self._map[key] = cls
            return cls
        return deco

    def create(self, name, *args, **kwargs):
        if not isinstance(name, str):
            return name  # already an instance
        key = name.lower()
        if key not in self._map:
            raise ValueError(f"Unknown {self.kind} {name!r}. Registered: {sorted(self._map)}")
        return self._map[key](*args, **kwargs)

    def get(self, name):
        return self._map[name.lower()]

    def __contains__(self, name):
        return isinstance(name, str) and name.lower() in self._map


class NameManager:
    """Auto-generates unique names per prefix (parity: mx.name.NameManager)."""

    _tls = threading.local()

    def __init__(self):
        self._counts = {}

    def get(self, name, hint):
        if name is not None:
            return name
        idx = self._counts.get(hint, 0)
        self._counts[hint] = idx + 1
        return f"{hint}{idx}"

    @classmethod
    def current(cls):
        if not hasattr(cls._tls, "nm"):
            cls._tls.nm = NameManager()
        return cls._tls.nm


# Acronym-aware: "LSTMCell" -> "lstm_cell", "Conv2D" -> "conv2d",
# "HybridSequential" -> "hybrid_sequential" (split at lower→upper and
# acronym→word boundaries only; digits don't split).
_SNAKE_RE = re.compile(r"(?<=[a-z])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])")


def camel_to_snake(name: str) -> str:
    return _SNAKE_RE.sub("_", name).lower()


def make_loop_caller(f, n_vars, single):
    """Resolve the control-flow calling convention for a user cond/func
    ONCE (reference python/mxnet/ndarray/contrib.py calls f(*loop_vars);
    this repo's historical convention passes the list as one argument).
    Returns caller(vars_list) -> f's result.

    - single (loop_vars was not a list): f receives the bare variable.
    - 1-element list: f receives the list (historical behavior kept —
      upstream f(*loop_vars) is indistinguishable by signature here).
    - multi-var: the signature decides. A function that can accept ONE
      positional argument (e.g. `def f(vs)`, `def f(vs, debug=False)`)
      keeps the historical list convention; only a function that needs
      all n (e.g. `def f(a, b)`) is called unpacked, reference style.
      Ambiguous shapes resolve toward the list convention so existing
      callers never change behavior.
    """
    import inspect
    if single:
        return lambda vs: f(vs[0])
    if n_vars == 1:
        return lambda vs: f(list(vs))
    try:
        sig = inspect.signature(f)
        pos = [p for p in sig.parameters.values()
               if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                             p.VAR_POSITIONAL)]
        if len(pos) == 1 and pos[0].kind == pos[0].VAR_POSITIONAL:
            unpacked = True      # pure *args: reference style
        else:
            try:
                sig.bind(None)
                unpacked = False  # accepts a single positional: list style
            except TypeError:
                sig.bind(*([None] * n_vars))  # must bind unpacked else raise
                unpacked = True
    except TypeError:
        unpacked = False
    except ValueError:          # builtins/C callables: assume reference style
        unpacked = True
    if unpacked:
        return lambda vs: f(*vs)
    return lambda vs: f(list(vs))
