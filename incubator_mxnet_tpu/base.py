"""Shared helpers: dtype normalization, registries, name management.

Reference parity: python/mxnet/base.py (registries, name manager) — minus the
ctypes handle plumbing, which XLA makes unnecessary on the compute path.
"""
from __future__ import annotations

import re
import threading

import jax.numpy as jnp
import numpy as np

_DTYPE_ALIASES = {
    "float32": jnp.float32, "float64": jnp.float64, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "uint8": jnp.uint8, "int8": jnp.int8,
    "int32": jnp.int32, "int64": jnp.int64, "bool": jnp.bool_,
    "uint16": jnp.uint16, "uint32": jnp.uint32, "uint64": jnp.uint64,
    "int16": jnp.int16,
}


def normalize_dtype(dtype):
    """Accept strings, numpy dtypes, jnp dtypes; return a numpy dtype object."""
    if dtype is None:
        return np.dtype("float32")
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
    return np.dtype(dtype)


class _Registry:
    """String-keyed registry with `register` decorator and `create` factory
    (parity with mx.operator/optimizer/initializer registries)."""

    def __init__(self, kind):
        self.kind = kind
        self._map = {}

    def register(self, name=None):
        def deco(cls):
            key = (name or cls.__name__).lower()
            self._map[key] = cls
            return cls
        return deco

    def create(self, name, *args, **kwargs):
        if not isinstance(name, str):
            return name  # already an instance
        key = name.lower()
        if key not in self._map:
            raise ValueError(f"Unknown {self.kind} {name!r}. Registered: {sorted(self._map)}")
        return self._map[key](*args, **kwargs)

    def get(self, name):
        return self._map[name.lower()]

    def __contains__(self, name):
        return isinstance(name, str) and name.lower() in self._map


class NameManager:
    """Auto-generates unique names per prefix (parity: mx.name.NameManager)."""

    _tls = threading.local()

    def __init__(self):
        self._counts = {}

    def get(self, name, hint):
        if name is not None:
            return name
        idx = self._counts.get(hint, 0)
        self._counts[hint] = idx + 1
        return f"{hint}{idx}"

    @classmethod
    def current(cls):
        if not hasattr(cls._tls, "nm"):
            cls._tls.nm = NameManager()
        return cls._tls.nm


# Acronym-aware: "LSTMCell" -> "lstm_cell", "Conv2D" -> "conv2d",
# "HybridSequential" -> "hybrid_sequential" (split at lower→upper and
# acronym→word boundaries only; digits don't split).
_SNAKE_RE = re.compile(r"(?<=[a-z])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])")


def camel_to_snake(name: str) -> str:
    return _SNAKE_RE.sub("_", name).lower()
