"""Small runtime utilities (reference parity: python/mxnet/util.py).

The reference's numpy-mode switches don't apply here — NDArray is
numpy-semantic natively — so the mode queries are honest constants rather
than global flags.
"""
from __future__ import annotations

import functools
import os

__all__ = ["makedirs", "getenv", "setenv", "use_np_shape", "is_np_shape",
           "is_np_array", "np_shape", "wrap_ctx_to_device_func"]


def makedirs(d):
    """mkdir -p (reference util.makedirs)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def getenv(name):
    """Read an environment variable (reference MXGetEnv path)."""
    # mxlint: disable=raw-env-read -- MXNet-parity MXGetEnv passthrough
    return os.environ.get(name)


def setenv(name, value):
    """Set an environment variable (reference MXSetEnv path)."""
    os.environ[name] = value


def is_np_shape():
    """Zero-dim/zero-size shapes are always legal here (jax is numpy-
    semantic), so numpy-shape mode is permanently on."""
    return True


def is_np_array():
    """The nd namespace already follows numpy broadcasting/dtype rules;
    there is no separate legacy-array mode to switch from."""
    return True


class np_shape:
    """No-op context manager kept for reference-API compatibility
    (`with mx.util.np_shape():`)."""

    def __init__(self, active=True):
        self._active = active

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def use_np_shape(func):
    """Decorator form of :class:`np_shape` (reference util.use_np_shape)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape():
            return func(*args, **kwargs)
    return wrapper


def wrap_ctx_to_device_func(func):
    """Reference 2.x helper that translated ctx= to device=; both spellings
    already reach Context here, so this is identity."""
    return func
