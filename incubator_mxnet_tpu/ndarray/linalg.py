"""Linear-algebra operator family (parity: python/mxnet/ndarray/linalg.py,
src/operator/tensor/la_op.cc).

Batched throughout (leading dims broadcast), differentiable through the
tape like every other op. The matmul-shaped ops (gemm/gemm2/trmm/syrk) land
on the MXU; the factorizations (potrf/syevd/gelqf) lower to XLA's native
kernels. `lower=True` defaults match the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import NDArray, _apply, _as_nd

__all__ = ["gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "sumlogdiag",
           "syrk", "gelqf", "syevd", "inverse", "det", "slogdet",
           "makediag", "extractdiag", "maketrian", "extracttrian"]


def _mt(a, transpose):
    return jnp.swapaxes(a, -1, -2) if transpose else a


def gemm(A, B, C, alpha=1.0, beta=1.0, transpose_a=False, transpose_b=False):
    """alpha * op(A) @ op(B) + beta * C."""
    C = _as_nd(C)
    return _apply(lambda a, b, c: alpha * _mt(a, transpose_a)
                  @ _mt(b, transpose_b) + beta * c,
                  [A, B, C], name="linalg_gemm")


def gemm2(A, B, alpha=1.0, transpose_a=False, transpose_b=False):
    """alpha * op(A) @ op(B)."""
    return _apply(lambda a, b: alpha * _mt(a, transpose_a)
                  @ _mt(b, transpose_b),
                  [A, B], name="linalg_gemm2")


def potrf(A, lower=True):
    """Cholesky factor (reference: positive-definite A = L @ L.T)."""
    def f(a):
        ch = jnp.linalg.cholesky(a)
        return ch if lower else jnp.swapaxes(ch, -1, -2)
    return _apply(f, [A], name="linalg_potrf")


def potri(A, lower=True):
    """Inverse from a Cholesky factor: (L @ L.T)^-1 given L."""
    def f(l):
        lt = l if lower else jnp.swapaxes(l, -1, -2)
        eye = jnp.broadcast_to(jnp.eye(lt.shape[-1], dtype=lt.dtype),
                               lt.shape)
        linv = jax.scipy.linalg.solve_triangular(lt, eye, lower=True)
        return jnp.swapaxes(linv, -1, -2) @ linv
    return _apply(f, [A], name="linalg_potri")


def trmm(A, B, alpha=1.0, transpose=False, rightside=False, lower=True):
    """Triangular matrix multiply: alpha * op(tri(A)) @ B (or B @ op)."""
    def f(a, b):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        tri = _mt(tri, transpose)
        return alpha * (b @ tri if rightside else tri @ b)
    return _apply(f, [A, B], name="linalg_trmm")


def trsm(A, B, alpha=1.0, transpose=False, rightside=False, lower=True):
    """Solve op(tri(A)) @ X = alpha * B (or X @ op(tri(A)))."""
    def f(a, b):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        lo = lower != transpose
        if rightside:
            # X @ op(T) = aB  <=>  op(T).T @ X.T = a B.T
            sol = jax.scipy.linalg.solve_triangular(
                jnp.swapaxes(_mt(tri, transpose), -1, -2),
                jnp.swapaxes(alpha * b, -1, -2), lower=not lo)
            return jnp.swapaxes(sol, -1, -2)
        return jax.scipy.linalg.solve_triangular(
            _mt(tri, transpose), alpha * b, lower=lo)
    return _apply(f, [A, B], name="linalg_trsm")


def sumlogdiag(A):
    """sum(log(diag(A))) per matrix (reference log-det helper)."""
    return _apply(lambda a: jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1))
                  .sum(axis=-1), [A], name="linalg_sumlogdiag")


def syrk(A, alpha=1.0, transpose=False):
    """alpha * A @ A.T (or A.T @ A)."""
    def f(a):
        at = jnp.swapaxes(a, -1, -2)
        return alpha * ((at @ a) if transpose else (a @ at))
    return _apply(f, [A], name="linalg_syrk")


def gelqf(A):
    """LQ factorization A = L @ Q with Q orthonormal rows (m <= n)."""
    def f(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
        return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
    return _apply(f, [A], n_out=2, name="linalg_gelqf")


def syevd(A):
    """Symmetric eigendecomposition: returns (U, lam) with A = U.T diag(lam) U
    (reference row-eigenvector convention)."""
    def f(a):
        lam, v = jnp.linalg.eigh(a)
        return jnp.swapaxes(v, -1, -2), lam
    return _apply(f, [A], n_out=2, name="linalg_syevd")


def inverse(A):
    return _apply(jnp.linalg.inv, [A], name="linalg_inverse")


def det(A):
    return _apply(jnp.linalg.det, [A], name="linalg_det")


def slogdet(A):
    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return sign, logabs
    return _apply(f, [A], n_out=2, name="linalg_slogdet")


def makediag(A, offset=0):
    """Vector(s) -> diagonal matrix (reference linalg.makediag)."""
    return _apply(lambda a: _batched_diag(a, offset), [A],
                  name="linalg_makediag")


def _batched_diag(a, offset):
    n = a.shape[-1] + abs(offset)
    out_shape = a.shape[:-1] + (n, n)
    flat = a.reshape(-1, a.shape[-1])
    mats = jax.vmap(lambda v: jnp.diag(v, k=offset))(flat)
    return mats.reshape(out_shape)


def extractdiag(A, offset=0):
    return _apply(lambda a: jnp.diagonal(a, offset=offset, axis1=-2,
                                         axis2=-1),
                  [A], name="linalg_extractdiag")


def _trian_indices(n, offset, lower):
    """Reference la_op semantics: the offset SIGN picks the triangle
    (positive → upper band, negative → lower band); `lower` only breaks
    the tie at offset=0."""
    if offset > 0:
        return jnp.triu_indices(n, k=offset)
    if offset < 0:
        return jnp.tril_indices(n, k=offset)
    return jnp.tril_indices(n) if lower else jnp.triu_indices(n)


def maketrian(A, offset=0, lower=True):
    """Packed vector(s) -> triangular matrix (reference maketrian)."""
    def f(a):
        import math
        k = a.shape[-1]
        n = int((math.isqrt(8 * k + 1) - 1) // 2) + abs(offset)
        idx = _trian_indices(n, offset, lower)
        flat = a.reshape(-1, k)

        def one(v):
            return jnp.zeros((n, n), a.dtype).at[idx].set(v)
        return jax.vmap(one)(flat).reshape(a.shape[:-1] + (n, n))
    return _apply(f, [A], name="linalg_maketrian")


def extracttrian(A, offset=0, lower=True):
    """Triangular part of matrix(es) packed into a vector."""
    def f(a):
        n = a.shape[-1]
        idx = _trian_indices(n, offset, lower)
        flat = a.reshape(-1, n, n)
        return jax.vmap(lambda m: m[idx])(flat).reshape(
            a.shape[:-2] + (len(idx[0]),))
    return _apply(f, [A], name="linalg_extracttrian")
