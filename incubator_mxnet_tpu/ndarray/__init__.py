"""NDArray: the imperative array type, backed by `jax.Array`.

Reference parity: python/mxnet/ndarray/ndarray.py + src/ndarray/. The
reference NDArray is a mutable chunk scheduled on the threaded engine; here
the storage is an immutable `jax.Array` and mutation swaps the underlying
buffer (functional update via `.at[]`), while XLA's async dispatch plays the
role of the engine (`wait_to_read` == `block_until_ready`). Every eager op
funnels through `_apply`, which records a tape Node while
`autograd.record()` is active — so the same op surface works eagerly, under
the tape, and under `jax.jit` tracing (HybridBlock), where `_data` is a
tracer.

Design choice vs reference: numpy-style implicit broadcasting everywhere
(like mx.np), with the legacy `broadcast_*` names kept as aliases.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import autograd
from .. import bulk as _bulk
from ..base import normalize_dtype
from ..context import Context, ctx_from_device, current_context

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "concatenate", "stack", "split", "dot", "batch_dot",
           "save", "load", "waitall"]

# the profiler subsystem installs a timing wrapper here while imperative
# profiling is active (profiler.set_state("run")); None = zero-overhead path
_op_hook = None

# diagnostics hooks, same zero-overhead-off discipline (one module-global
# check each): _mem_hook registers every new NDArray with the allocation
# ledger (diagnostics.memory); _flight_hook records each op dispatch in
# the flight-recorder ring (diagnostics.flight)
_mem_hook = None
_flight_hook = None

# mxlint strict-mode host-sync sentinel (mxlint/runtime.py): armed under
# MXTPU_STRICT=1, it counts NDArray host materializations that happen
# inside a guarded steady-loop dispatch — the CPU backend's zero-copy
# arrays never trip jax's transfer guard, so the framework's own sync
# funnel is the detection channel tier-1 can prove. None = off (one
# predicate per materialization, the _op_hook discipline).
_STRICT_SYNC = None


def _apply(fn, inputs: Sequence["NDArray"], n_out: int = 1, name: Optional[str] = None,
           fn_fwd=None, fn_vjp=None):
    """Run a pure jax function on NDArray inputs; record on the tape if
    autograd is recording. The single funnel for all eager ops.

    Inside an engine.bulk scope (or auto-bulk mode) the op is appended to
    a deferred segment instead of dispatching — one compiled XLA call per
    segment (see bulk.py). Recording and the profiler's per-op timing hook
    keep the eager path (the tape needs concrete values; the hook needs
    per-op durations).

    fn_fwd: optional compiled variant used for execution (fn stays on the
    tape for differentiation); fn_vjp: optional precompiled pullback
    (primals..., out_cots...) -> input cots (HybridBlock CachedOp path).
    """
    if _flight_hook is not None:
        _flight_hook(name)
    if _bulk._ON:
        if _op_hook is None and not autograd.is_recording():
            res = _bulk.defer(fn_fwd or fn, [x._data for x in inputs],
                              n_out, name)
            if res is not None:
                return res[0] if n_out == 1 else tuple(res)
        for x in inputs:                 # eager fallback: concrete inputs
            if _bulk.is_deferred(x._data):
                x._data = _bulk.materialize_one(x._data)
    raws = [x._data for x in inputs]
    if _op_hook is None:
        outs = (fn_fwd or fn)(*raws)
    else:
        outs = _op_hook(fn_fwd or fn, raws, name)  # profiler timing path
    outs_t = (outs,) if n_out == 1 else tuple(outs)
    results = [NDArray(o) for o in outs_t]
    if autograd.is_recording():
        autograd._record_op(fn, inputs, raws, results, name, fn_vjp=fn_vjp)
    return results[0] if n_out == 1 else tuple(results)


def _wrap_deferred(raw) -> "NDArray":
    """NDArray around a bulk DeferredArray, bypassing __init__ coercion."""
    out = NDArray.__new__(NDArray)
    out._data = raw
    out._node = None
    out._grad = None
    out._grad_req = None
    out._grad_hook = None
    if _mem_hook is not None:
        _mem_hook(out)
    return out


_bulk._WRAP = _wrap_deferred


def _as_nd(x, ref: Optional["NDArray"] = None):
    if isinstance(x, NDArray):
        return x
    dtype = ref._data.dtype if ref is not None and not isinstance(x, (bool, np.bool_)) else None
    return NDArray(jnp.asarray(x, dtype=dtype))


def _is_sparse_operand(x):
    return hasattr(x, "stype") and not isinstance(x, NDArray)


# dunder/function short-name -> storage-aware kernel in ndarray.sparse
_SPARSE_BINARY = {"add": "add", "sub": "subtract", "subtract": "subtract",
                  "mul": "multiply", "multiply": "multiply",
                  "div": "divide", "divide": "divide"}


def _binary(jfn, x, y, name=None):
    if _is_sparse_operand(x) or _is_sparse_operand(y):
        # route through the storage-aware sparse kernels (pattern-keeping
        # where one exists, dense fallback with warning where not) instead
        # of crashing inside jnp coercion
        from . import sparse as _sp
        opname = _SPARSE_BINARY.get(name)
        if opname is not None:
            return getattr(_sp, opname)(x, y)
        _sp._warn_fallback(name or "binary", x, y)
        x = x.todense() if _is_sparse_operand(x) else x
        y = y.todense() if _is_sparse_operand(y) else y
    if isinstance(x, NDArray) and isinstance(y, NDArray):
        return _apply(jfn, [x, y], name=name)
    if isinstance(x, NDArray):
        return _apply(lambda a: jfn(a, y), [x], name=name)
    return _apply(lambda b: jfn(x, b), [y], name=name)


def _unary(jfn, x, name=None, **kw):
    if kw:
        return _apply(lambda a: jfn(a, **kw), [x], name=name)
    return _apply(jfn, [x], name=name)


def _symbolic(x):
    """True while a Gluon forward runs under symbol tracing and `x` is a
    Symbol (see gluon/symbolize.py); routes nd.* helpers to builders."""
    return not isinstance(x, NDArray) and type(x).__name__ == "Symbol"


def _sym_call(name, out_index=None, **kw):
    from ..gluon.symbolize import sym_call
    return sym_call(name, out_index=out_index, **kw)


class NDArray:
    """An n-dimensional array on a device (TPU-first)."""

    __slots__ = ("_data", "_node", "_grad", "_grad_req", "_grad_hook",
                 "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None, _node=None):
        if isinstance(data, NDArray):
            data = data._data
        if _bulk.is_deferred(data):
            # keep the value deferred (detach/copy of a pending result)
            # unless a dtype cast or an explicit device placement forces
            # materialization (deferred outputs land on the segment's
            # device, so honoring ctx needs a concrete array)
            if dtype is not None or ctx is not None:
                data = _bulk.materialize_one(data)
            else:
                self._data = data
                self._node = _node
                self._grad = None
                self._grad_req = None
                self._grad_hook = None
                if _mem_hook is not None:
                    _mem_hook(self)
                return
        if not isinstance(data, jax.Array) or dtype is not None:
            dt = None if dtype is None else normalize_dtype(dtype)
            data = jnp.asarray(data, dtype=dt)
        if ctx is not None and isinstance(data, jax.Array) and not _is_tracer(data):
            dev = ctx.device
            if _device_of(data) is not dev:
                data = jax.device_put(data, dev)
        self._data = data
        self._node = _node
        self._grad = None
        self._grad_req = None
        # fires with this NDArray the moment its gradient is FINALIZED
        # during a backward walk (not at the end) — the readiness signal
        # overlapped gradient communication schedules on
        self._grad_hook = None
        if _mem_hook is not None:
            _mem_hook(self)

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype) if self._data.dtype != jnp.bfloat16 else self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        if _is_tracer(self._data):
            return current_context()
        return ctx_from_device(_device_of(self._data))

    ctx = context

    @property
    def T(self):
        return self.transpose()

    @property
    def grad(self):
        return self._grad

    # -- materialization --------------------------------------------------
    def asnumpy(self) -> np.ndarray:
        if _STRICT_SYNC is not None:
            _STRICT_SYNC("asnumpy")
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        if _STRICT_SYNC is not None:
            _STRICT_SYNC("__array__")
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        if _STRICT_SYNC is not None:
            _STRICT_SYNC("asscalar")
        return self._data.reshape(()).item()

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def wait_to_read(self):
        if _STRICT_SYNC is not None:
            # not a transfer, but a barrier: it serializes the async
            # dispatch pipeline just the same inside a measured loop
            _STRICT_SYNC("wait_to_read")
        if not _is_tracer(self._data):
            self._data.block_until_ready()
        return self

    def jax(self) -> jax.Array:
        """Raw backing jax.Array (escape hatch for interop); flushes any
        pending bulk segment so the result is always concrete."""
        return _bulk.materialize_one(self._data)

    # -- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req: str = "write"):
        if grad_req == "null":
            self._grad_req = None
            self._grad = None
        else:
            self._grad_req = grad_req
            self._grad = zeros_like(self)
        self._node = None  # becomes a fresh leaf (parity: attach_grad detaches)
        return self

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph, train_mode)

    def detach(self) -> "NDArray":
        return NDArray(self._data)

    # -- movement / casting ----------------------------------------------
    def astype(self, dtype, copy=True):
        dt = normalize_dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return _apply(lambda a: a.astype(dt), [self], name="astype")

    def copy(self) -> "NDArray":
        return _apply(lambda a: a + 0 if a.dtype != jnp.bool_ else jnp.copy(a), [self], name="copy")

    def copyto(self, other):
        if isinstance(other, Context):
            return NDArray(self._data, ctx=other)
        other._data = jax.device_put(self._data.astype(other._data.dtype),
                                     _device_of(other._data))
        other._node = self._node
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if not _is_tracer(self._data) and _device_of(self._data) is ctx.device:
            return self
        out = NDArray(jax.device_put(self._data, ctx.device))
        out._node = self._node
        return out

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    # -- indexing ---------------------------------------------------------
    def __getitem__(self, key):
        key = _fix_index(key)
        return _apply(lambda a: a[key], [self], name="getitem")

    def __setitem__(self, key, value):
        key = _fix_index(key)
        if isinstance(value, NDArray):
            new = _apply(lambda a, v: a.at[key].set(v.astype(a.dtype)), [self, value],
                         name="setitem")
        else:
            new = _apply(lambda a: a.at[key].set(value), [self], name="setitem")
        self._data = new._data
        self._node = new._node

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("truth value of multi-element NDArray is ambiguous")
        return bool(self._data.reshape(()).item())

    def __int__(self):
        return int(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    __hash__ = object.__hash__  # identity hash; __eq__ below is elementwise

    # -- arithmetic -------------------------------------------------------
    def __add__(self, o): return _binary(jnp.add, self, o, "add")
    def __radd__(self, o): return _binary(jnp.add, o, self, "add")
    def __sub__(self, o): return _binary(jnp.subtract, self, o, "sub")
    def __rsub__(self, o): return _binary(jnp.subtract, o, self, "sub")
    def __mul__(self, o): return _binary(jnp.multiply, self, o, "mul")
    def __rmul__(self, o): return _binary(jnp.multiply, o, self, "mul")
    def __truediv__(self, o): return _binary(jnp.divide, self, o, "div")
    def __rtruediv__(self, o): return _binary(jnp.divide, o, self, "div")
    def __floordiv__(self, o): return _binary(jnp.floor_divide, self, o, "floordiv")
    def __rfloordiv__(self, o): return _binary(jnp.floor_divide, o, self, "floordiv")
    def __mod__(self, o): return _binary(jnp.mod, self, o, "mod")
    def __rmod__(self, o): return _binary(jnp.mod, o, self, "mod")
    def __pow__(self, o): return _binary(jnp.power, self, o, "pow")
    def __rpow__(self, o): return _binary(jnp.power, o, self, "pow")
    def __matmul__(self, o): return _binary(jnp.matmul, self, o, "matmul")
    def __neg__(self): return _unary(jnp.negative, self, "neg")
    def __abs__(self): return _unary(jnp.abs, self, "abs")

    def __iadd__(self, o):
        r = self.__add__(o)
        self._data, self._node = r._data, r._node
        return self

    def __isub__(self, o):
        r = self.__sub__(o)
        self._data, self._node = r._data, r._node
        return self

    def __imul__(self, o):
        r = self.__mul__(o)
        self._data, self._node = r._data, r._node
        return self

    def __itruediv__(self, o):
        r = self.__truediv__(o)
        self._data, self._node = r._data, r._node
        return self

    # -- comparisons (elementwise, parity with mx.nd) ---------------------
    def __eq__(self, o): return _binary(jnp.equal, self, o, "eq")
    def __ne__(self, o): return _binary(jnp.not_equal, self, o, "ne")
    def __lt__(self, o): return _binary(jnp.less, self, o, "lt")
    def __le__(self, o): return _binary(jnp.less_equal, self, o, "le")
    def __gt__(self, o): return _binary(jnp.greater, self, o, "gt")
    def __ge__(self, o): return _binary(jnp.greater_equal, self, o, "ge")

    # -- shape manipulation ----------------------------------------------
    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = tuple(int(s) for s in shape)
        return _apply(lambda a: a.reshape(shape), [self], name="reshape")

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, axes=None):
        return _apply(lambda a: jnp.transpose(a, axes), [self], name="transpose")

    def swapaxes(self, a1, a2):
        return _apply(lambda a: jnp.swapaxes(a, a1, a2), [self], name="swapaxes")

    def flatten(self):
        """MXNet semantics: collapse all but the first axis → (N, -1)."""
        return _apply(lambda a: a.reshape(a.shape[0], -1), [self], name="flatten")

    def ravel(self):
        return _apply(lambda a: a.reshape(-1), [self], name="ravel")

    def expand_dims(self, axis):
        return _apply(lambda a: jnp.expand_dims(a, axis), [self], name="expand_dims")

    def squeeze(self, axis=None):
        return _apply(lambda a: jnp.squeeze(a, axis), [self], name="squeeze")

    def broadcast_to(self, shape):
        return _apply(lambda a: jnp.broadcast_to(a, shape), [self], name="broadcast_to")

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def tile(self, reps):
        return _apply(lambda a: jnp.tile(a, reps), [self], name="tile")

    def repeat(self, repeats, axis=None):
        return _apply(lambda a: jnp.repeat(a, repeats, axis), [self], name="repeat")

    def flip(self, axis):
        return _apply(lambda a: jnp.flip(a, axis), [self], name="flip")

    def split(self, num_outputs, axis=0):
        return split(self, num_outputs, axis)

    def slice_axis(self, axis, begin, end):
        return slice_axis(self, axis, begin, end)

    # -- math methods (delegate to module fns) ----------------------------
    def sum(self, axis=None, keepdims=False): return sum(self, axis, keepdims)
    def mean(self, axis=None, keepdims=False): return mean(self, axis, keepdims)
    def max(self, axis=None, keepdims=False): return max(self, axis, keepdims)
    def min(self, axis=None, keepdims=False): return min(self, axis, keepdims)
    def prod(self, axis=None, keepdims=False): return prod(self, axis, keepdims)
    def argmax(self, axis=None, keepdims=False): return argmax(self, axis, keepdims)
    def argmin(self, axis=None, keepdims=False): return argmin(self, axis, keepdims)
    def norm(self, ord=2, axis=None, keepdims=False): return norm(self, ord, axis, keepdims)
    def var(self, axis=None, keepdims=False): return var(self, axis, keepdims)
    def std(self, axis=None, keepdims=False): return std(self, axis, keepdims)
    def abs(self): return _unary(jnp.abs, self, "abs")
    def exp(self): return _unary(jnp.exp, self, "exp")
    def log(self): return _unary(jnp.log, self, "log")
    def sqrt(self): return _unary(jnp.sqrt, self, "sqrt")
    def square(self): return _unary(jnp.square, self, "square")
    def sign(self): return _unary(jnp.sign, self, "sign")
    def round(self): return _unary(jnp.round, self, "round")
    def floor(self): return _unary(jnp.floor, self, "floor")
    def ceil(self): return _unary(jnp.ceil, self, "ceil")
    def clip(self, a_min=None, a_max=None): return clip(self, a_min, a_max)
    def relu(self): return _unary(jax.nn.relu, self, "relu")
    def sigmoid(self): return _unary(jax.nn.sigmoid, self, "sigmoid")
    def tanh(self): return _unary(jnp.tanh, self, "tanh")
    def softmax(self, axis=-1): return softmax(self, axis)
    def log_softmax(self, axis=-1): return log_softmax(self, axis)
    def dot(self, other, transpose_a=False, transpose_b=False):
        return dot(self, other, transpose_a, transpose_b)
    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return one_hot(self, depth, on_value, off_value)
    def take(self, indices, axis=0):
        return take(self, indices, axis)
    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return topk(self, axis, k, ret_typ, is_ascend)
    def sort(self, axis=-1, is_ascend=True): return sort(self, axis, is_ascend)
    def argsort(self, axis=-1, is_ascend=True): return argsort(self, axis, is_ascend)
    def cumsum(self, axis=None): return _unary(jnp.cumsum, self, "cumsum", axis=axis)

    # -- misc -------------------------------------------------------------
    def __repr__(self):
        if _is_tracer(self._data):
            return f"<NDArray tracer {self.shape} {self._data.dtype}>"
        vals = np.array2string(self.asnumpy(), precision=4, suppress_small=True,
                               threshold=20)
        return f"{vals}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context} {self._data.dtype}>"

    def zeros_like(self): return zeros_like(self)
    def ones_like(self): return ones_like(self)


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _device_of(arr: jax.Array):
    try:
        return next(iter(arr.devices()))
    except Exception:
        return None


def _fix_index(key):
    """Unwrap NDArray indices to raw arrays. Index arrays materialize any
    pending bulk value: they are captured in the indexing op's closure
    (not passed as segment inputs), so a deferred key must be concrete."""
    if isinstance(key, NDArray):
        return _bulk.materialize_one(key._data)
    if isinstance(key, tuple):
        return tuple(_bulk.materialize_one(k._data)
                     if isinstance(k, NDArray) else k for k in key)
    return key


# ===========================================================================
# creation
# ===========================================================================

def array(source, ctx=None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        source = source._data
    dt = normalize_dtype(dtype) if dtype is not None else None
    if dt is None and not isinstance(source, jax.Array):
        a = np.asarray(source)
        # mx defaults: float64 literals → float32; int64 → int32 (x64 is off)
        dt = {np.dtype("float64"): np.float32,
              np.dtype("int64"): np.int32}.get(a.dtype, a.dtype)
        source = a
    return NDArray(jnp.asarray(source, dtype=dt), ctx=ctx or current_context())


def zeros(shape, ctx=None, dtype="float32") -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.zeros(shape, normalize_dtype(dtype)), ctx=ctx or current_context())


def ones(shape, ctx=None, dtype="float32") -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.ones(shape, normalize_dtype(dtype)), ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype="float32") -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.full(shape, val, normalize_dtype(dtype)), ctx=ctx or current_context())


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return zeros(shape, ctx, dtype)


def zeros_like(x: NDArray) -> NDArray:
    return _apply(jnp.zeros_like, [x], name="zeros_like")


def ones_like(x: NDArray) -> NDArray:
    return _apply(jnp.ones_like, [x], name="ones_like")


def full_like(x: NDArray, val) -> NDArray:
    return _apply(lambda a: jnp.full_like(a, val), [x], name="full_like")


def empty_like(x: NDArray) -> NDArray:
    return zeros_like(x)


def mod(lhs, rhs) -> NDArray:
    return lhs % rhs if isinstance(lhs, NDArray) else NDArray(lhs) % rhs


def astype(x: NDArray, dtype, copy=True) -> NDArray:
    return x.astype(dtype, copy=copy)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32") -> NDArray:
    a = jnp.arange(start, stop, step, normalize_dtype(dtype))
    if repeat != 1:
        a = jnp.repeat(a, repeat)
    return NDArray(a, ctx=ctx or current_context())


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32") -> NDArray:
    return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint,
                                dtype=normalize_dtype(dtype)), ctx=ctx or current_context())


def eye(N, M=None, k=0, ctx=None, dtype="float32") -> NDArray:
    return NDArray(jnp.eye(N, M, k, dtype=normalize_dtype(dtype)), ctx=ctx or current_context())


identity = eye


# ===========================================================================
# elementwise / math
# ===========================================================================

def _make_unary(jfn, name):
    def f(x, out=None):
        r = _unary(jfn, _as_nd(x), name)
        if out is not None:
            out._data, out._node = r._data, r._node
            return out
        return r
    f.__name__ = name
    return f


exp = _make_unary(jnp.exp, "exp")
expm1 = _make_unary(jnp.expm1, "expm1")
log = _make_unary(jnp.log, "log")
log2 = _make_unary(jnp.log2, "log2")
log10 = _make_unary(jnp.log10, "log10")
log1p = _make_unary(jnp.log1p, "log1p")
sqrt = _make_unary(jnp.sqrt, "sqrt")
rsqrt = _make_unary(lambda a: 1.0 / jnp.sqrt(a), "rsqrt")
cbrt = _make_unary(jnp.cbrt, "cbrt")
rcbrt = _make_unary(lambda a: 1.0 / jnp.cbrt(a), "rcbrt")
square = _make_unary(jnp.square, "square")
abs = _make_unary(jnp.abs, "abs")
sign = _make_unary(jnp.sign, "sign")
floor = _make_unary(jnp.floor, "floor")
ceil = _make_unary(jnp.ceil, "ceil")
round = _make_unary(jnp.round, "round")
rint = _make_unary(jnp.rint, "rint")
trunc = _make_unary(jnp.trunc, "trunc")
fix = _make_unary(jnp.trunc, "fix")
negative = _make_unary(jnp.negative, "negative")
reciprocal = _make_unary(lambda a: 1.0 / a, "reciprocal")
sin = _make_unary(jnp.sin, "sin")
cos = _make_unary(jnp.cos, "cos")
tan = _make_unary(jnp.tan, "tan")
arcsin = _make_unary(jnp.arcsin, "arcsin")
arccos = _make_unary(jnp.arccos, "arccos")
arctan = _make_unary(jnp.arctan, "arctan")
sinh = _make_unary(jnp.sinh, "sinh")
cosh = _make_unary(jnp.cosh, "cosh")
tanh = _make_unary(jnp.tanh, "tanh")
arcsinh = _make_unary(jnp.arcsinh, "arcsinh")
arccosh = _make_unary(jnp.arccosh, "arccosh")
arctanh = _make_unary(jnp.arctanh, "arctanh")
erf = _make_unary(jax.scipy.special.erf, "erf")
erfinv = _make_unary(jax.scipy.special.erfinv, "erfinv")
gammaln = _make_unary(jax.scipy.special.gammaln, "gammaln")
digamma = _make_unary(jax.scipy.special.digamma, "digamma")
relu = _make_unary(jax.nn.relu, "relu")
sigmoid = _make_unary(jax.nn.sigmoid, "sigmoid")
softsign = _make_unary(jax.nn.soft_sign, "softsign")
logical_not = _make_unary(jnp.logical_not, "logical_not")
isnan = _make_unary(jnp.isnan, "isnan")
isinf = _make_unary(jnp.isinf, "isinf")
isfinite = _make_unary(jnp.isfinite, "isfinite")


def softrelu(x):
    return _unary(jax.nn.softplus, _as_nd(x), "softrelu")


def gelu(x, approximate=True):
    if _symbolic(x):
        return _sym_call("gelu", data=x, approximate=approximate)
    return _unary(lambda a: jax.nn.gelu(a, approximate=approximate), _as_nd(x), "gelu")


def leaky_relu(x, slope=0.25):
    if _symbolic(x):
        return _sym_call("LeakyReLU", data=x, act_type="leaky", slope=slope)
    return _unary(lambda a: jax.nn.leaky_relu(a, slope), _as_nd(x), "leaky_relu")


def elu(x, alpha=1.0):
    if _symbolic(x):
        return _sym_call("LeakyReLU", data=x, act_type="elu", slope=alpha)
    return _unary(lambda a: jax.nn.elu(a, alpha), _as_nd(x), "elu")


def selu(x):
    if _symbolic(x):
        return _sym_call("LeakyReLU", data=x, act_type="selu")
    return _unary(jax.nn.selu, _as_nd(x), "selu")


def silu(x):
    if _symbolic(x):
        return _sym_call("silu", data=x)
    return _unary(jax.nn.silu, _as_nd(x), "silu")


swish = silu


def softmax(x, axis=-1, temperature=None):
    if _symbolic(x):
        if temperature is not None and temperature != 1.0:
            x = x / float(temperature)  # Symbol.__truediv__ -> _div_scalar
        return _sym_call("softmax", data=x, axis=axis)
    if temperature is not None and temperature != 1.0:
        return _unary(lambda a: jax.nn.softmax(a / temperature, axis=axis), x, "softmax")
    return _unary(lambda a: jax.nn.softmax(a, axis=axis), x, "softmax")


def log_softmax(x, axis=-1):
    if _symbolic(x):
        return _sym_call("log_softmax", data=x, axis=axis)
    return _unary(lambda a: jax.nn.log_softmax(a, axis=axis), x, "log_softmax")


def clip(x, a_min=None, a_max=None):
    return _unary(lambda a: jnp.clip(a, a_min, a_max), x, "clip")


def power(x, y): return _binary(jnp.power, x, y, "power")
def add(x, y): return _binary(jnp.add, x, y, "add")
def subtract(x, y): return _binary(jnp.subtract, x, y, "subtract")
def multiply(x, y): return _binary(jnp.multiply, x, y, "multiply")
def divide(x, y): return _binary(jnp.divide, x, y, "divide")
def modulo(x, y): return _binary(jnp.mod, x, y, "modulo")
def maximum(x, y): return _binary(jnp.maximum, x, y, "maximum")
def minimum(x, y): return _binary(jnp.minimum, x, y, "minimum")
def hypot(x, y): return _binary(jnp.hypot, x, y, "hypot")
def arctan2(x, y): return _binary(jnp.arctan2, x, y, "arctan2")
def equal(x, y): return _binary(jnp.equal, x, y, "equal")
def not_equal(x, y): return _binary(jnp.not_equal, x, y, "not_equal")
def greater(x, y): return _binary(jnp.greater, x, y, "greater")
def greater_equal(x, y): return _binary(jnp.greater_equal, x, y, "greater_equal")
def lesser(x, y): return _binary(jnp.less, x, y, "lesser")
def less(x, y): return _binary(jnp.less, x, y, "less")
def lesser_equal(x, y): return _binary(jnp.less_equal, x, y, "lesser_equal")
def less_equal(x, y): return _binary(jnp.less_equal, x, y, "less_equal")
def logical_and(x, y): return _binary(jnp.logical_and, x, y, "logical_and")
def logical_or(x, y): return _binary(jnp.logical_or, x, y, "logical_or")
def logical_xor(x, y): return _binary(jnp.logical_xor, x, y, "logical_xor")

# legacy explicit-broadcast aliases (the rebuild broadcasts implicitly)
broadcast_add = add
broadcast_sub = subtract
broadcast_minus = subtract
broadcast_mul = multiply
broadcast_div = divide
broadcast_mod = modulo
broadcast_power = power
broadcast_maximum = maximum
broadcast_minimum = minimum
broadcast_equal = equal
broadcast_not_equal = not_equal
broadcast_greater = greater
broadcast_greater_equal = greater_equal
broadcast_lesser = lesser
broadcast_lesser_equal = lesser_equal
broadcast_logical_and = logical_and
broadcast_logical_or = logical_or
broadcast_logical_xor = logical_xor
elemwise_add = add
elemwise_sub = subtract
elemwise_mul = multiply
elemwise_div = divide


def where(cond, x, y):
    cond, x, y = _as_nd(cond), _as_nd(x), _as_nd(y)
    return _apply(jnp.where, [cond, x, y], name="where")


# ===========================================================================
# reductions
# ===========================================================================

def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def sum(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.sum(a, axis=_norm_axis(axis), keepdims=keepdims), x, "sum")


def nansum(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.nansum(a, axis=_norm_axis(axis), keepdims=keepdims), x, "nansum")


def nanprod(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.nanprod(a, axis=_norm_axis(axis), keepdims=keepdims), x, "nanprod")


def degrees(x):
    return _unary(jnp.degrees, x, "degrees")


def radians(x):
    return _unary(jnp.radians, x, "radians")


def argmax_channel(x):
    """Parity: mx.nd.argmax_channel — argmax over axis 1, float output."""
    return _unary(lambda a: jnp.argmax(a, axis=1).astype(jnp.float32), x,
                  "argmax_channel")


def mean(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.mean(a, axis=_norm_axis(axis), keepdims=keepdims), x, "mean")


def max(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.max(a, axis=_norm_axis(axis), keepdims=keepdims), x, "max")


def min(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.min(a, axis=_norm_axis(axis), keepdims=keepdims), x, "min")


def prod(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.prod(a, axis=_norm_axis(axis), keepdims=keepdims), x, "prod")


def var(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.var(a, axis=_norm_axis(axis), keepdims=keepdims), x, "var")


def std(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.std(a, axis=_norm_axis(axis), keepdims=keepdims), x, "std")


def argmax(x, axis=None, keepdims=False):
    def f(a):
        r = jnp.argmax(a, axis=axis, keepdims=keepdims).astype(jnp.float32)
        return r
    return _unary(f, x, "argmax")


def argmin(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdims).astype(jnp.float32),
                  x, "argmin")


def norm(x, ord=2, axis=None, keepdims=False):
    def f(a):
        if axis is None:
            # mx.nd.norm: entrywise norm over all elements (not spectral)
            r = jnp.linalg.norm(a.reshape(-1), ord=ord)
            return r.reshape((1,) * a.ndim) if keepdims else r
        return jnp.linalg.norm(a, ord=ord, axis=_norm_axis(axis), keepdims=keepdims)
    return _unary(f, x, "norm")


def all(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.all(a, axis=_norm_axis(axis), keepdims=keepdims), x, "all")


def any(x, axis=None, keepdims=False):
    return _unary(lambda a: jnp.any(a, axis=_norm_axis(axis), keepdims=keepdims), x, "any")


def cumsum(x, axis=None, dtype=None):
    return _unary(lambda a: jnp.cumsum(a, axis=axis, dtype=dtype), x, "cumsum")


# ===========================================================================
# shape manipulation
# ===========================================================================

def reshape(x, shape):
    return x.reshape(shape)


def transpose(x, axes=None):
    return x.transpose(axes)


def swapaxes(x, a1, a2):
    if _symbolic(x):
        return _sym_call("swapaxes", data=x, a1=a1, a2=a2)
    return x.swapaxes(a1, a2)


def expand_dims(x, axis):
    return x.expand_dims(axis)


def squeeze(x, axis=None):
    return x.squeeze(axis)


def flatten(x):
    return x.flatten()


def flip(x, axis):
    return x.flip(axis)


def tile(x, reps):
    return x.tile(reps)


def repeat(x, repeats, axis=None):
    return x.repeat(repeats, axis)


def broadcast_to(x, shape):
    return x.broadcast_to(shape)


def broadcast_like(x, other):
    return x.broadcast_to(other.shape)


def broadcast_axis(x, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)

    def f(a):
        shape = list(a.shape)
        for ax, s in zip(axes, sizes):
            shape[ax] = s
        return jnp.broadcast_to(a, shape)
    return _unary(f, x, "broadcast_axis")


def concat(*args, dim=1, axis=None):
    # MXNet's nd.concat defaults to dim=1 (channel axis) — keep that contract.
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    ax = axis if axis is not None else dim
    for a in args:  # builtins.any is shadowed by nd.any in this module
        if _symbolic(a):
            from ..symbol import Concat as _SymConcat
            from ..gluon.symbolize import to_input
            return _SymConcat(*[to_input(s) for s in args], dim=ax)
    return _apply(lambda *xs: jnp.concatenate(xs, axis=ax), list(args), name="concat")


def concatenate(arrays, axis=0):
    return concat(*arrays, dim=axis)


def stack(*args, axis=0):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return _apply(lambda *xs: jnp.stack(xs, axis=axis), list(args), name="stack")


def add_n(*args):
    """Sum of N arrays (parity: mx.nd.add_n / ElementWiseSum,
    src/operator/tensor/elemwise_sum.cc)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    for a in args:
        if _symbolic(a):
            from ..symbol import add_n as _sym_add_n
            from ..gluon.symbolize import to_input
            return _sym_add_n(*[to_input(s) for s in args])

    def f(*xs):
        total = xs[0]
        for x in xs[1:]:
            total = total + x
        return total

    return _apply(f, list(args), name="add_n")


ElementWiseSum = add_n


def reshape_like(lhs, rhs):
    """Reshape lhs to rhs's shape (parity: mx.nd.reshape_like)."""
    return _apply(lambda a, b: a.reshape(b.shape), [_as_nd(lhs),
                                                    _as_nd(rhs)],
                  name="reshape_like")


def multi_sum_sq(*arrays, num_arrays=None):
    """Per-array sum of squares (parity: mx.nd.multi_sum_sq — the LARS
    helper): one 1-D NDArray of shape (num_arrays,), like the reference."""
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    if num_arrays is not None and num_arrays != len(arrays):
        raise ValueError(f"num_arrays={num_arrays} but got "
                         f"{len(arrays)} arrays")
    return _apply(
        lambda *xs: jnp.stack([jnp.sum(jnp.square(x).astype(jnp.float32))
                               for x in xs]),
        [_as_nd(x) for x in arrays], name="multi_sum_sq")


def khatri_rao(*args):
    """Column-wise Kronecker product (parity: mx.nd.khatri_rao,
    src/operator/contrib/krprod.cc): inputs (r_i, k) -> (prod r_i, k)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])

    def f(*xs):
        out = xs[0]
        for b in xs[1:]:
            out = (out[:, None, :] * b[None, :, :]).reshape(-1, b.shape[1])
        return out

    return _apply(f, list(args), name="khatri_rao")


def split(x, num_outputs, axis=0, squeeze_axis=False):
    if _symbolic(x):
        return _sym_call("SliceChannel", data=x, num_outputs=num_outputs,
                         axis=axis, squeeze_axis=squeeze_axis)
    if num_outputs == 1:
        # parity: mx.nd.split with one output returns the array itself
        return _apply(lambda a: jnp.squeeze(a, axis) if squeeze_axis else a,
                      [x], name="split")

    def f(a):
        parts = jnp.split(a, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    return _apply(f, [x], n_out=num_outputs, name="split")


SliceChannel = split


def slice_axis(x, axis, begin, end):
    def f(a):
        n = a.shape[axis]
        b = begin if begin >= 0 else n + begin
        e = n if end is None else (end if end >= 0 else n + end)
        return lax.slice_in_dim(a, b, e, axis=axis)
    return _unary(f, x, "slice_axis")


def slice(x, begin, end, step=None):
    def f(a):
        idx = tuple(builtins_slice(b, e, s) for b, e, s in
                    zip(begin, end, step or [None] * len(begin)))
        return a[idx]
    return _unary(f, x, "slice")


from builtins import slice as builtins_slice  # noqa: E402


def crop(x, begin=None, end=None, step=None, **kwargs):
    """Legacy alias of nd.slice (parity: mx.nd.crop / src/operator/crop.cc
    deprecation path)."""
    if kwargs:
        raise TypeError("crop: unsupported kwargs %s (the center_crop/"
                        "offset form is not implemented; use nd.slice or "
                        "image.CenterCropAug)" % sorted(kwargs))
    return slice(x, begin, end, step)


def moments(x, axes=None, keepdims=False):
    """Mean and variance in one pass (parity: mx.nd.moments /
    src/operator/nn/moments.cc). Returns (mean, var)."""
    if _symbolic(x):
        return _sym_call("moments", data=x, axes=axes, keepdims=keepdims)
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes

    def f(a):
        m = jnp.mean(a, axis=ax, keepdims=True)
        v = jnp.mean((a - m) ** 2, axis=ax, keepdims=keepdims)
        if keepdims:
            return m, v
        # reuse the computed mean instead of reducing twice
        sq = tuple(range(a.ndim)) if ax is None else \
            (ax if isinstance(ax, tuple) else (ax,))
        return jnp.squeeze(m, axis=sq), v
    return _apply(f, [x], n_out=2, name="moments")


def softmin(x, axis=-1):
    """Parity: mx.nd.softmin — softmax of the negated input."""
    if _symbolic(x):
        return _sym_call("softmin", data=x, axis=axis)
    return _unary(lambda a: jax.nn.softmax(-a, axis=axis), x, "softmin")


def slice_like(x, shape_like, axes=None):
    if _symbolic(x) or _symbolic(shape_like):
        return _sym_call("slice_like", data=x, shape_like=shape_like,
                         axes=tuple(axes) if axes is not None else None)

    def f(a, b):
        idx = []
        for ax in range(a.ndim):
            if axes is None or ax in axes:
                idx.append(builtins_slice(0, b.shape[ax]))
            else:
                idx.append(builtins_slice(None))
        return a[tuple(idx)]
    return _apply(f, [x, shape_like], name="slice_like")


def pad(x, mode="constant", pad_width=None, constant_value=0):
    """MXNet pad: pad_width is a flat tuple (before0, after0, before1, ...)."""
    if _symbolic(x):
        return _sym_call("Pad", data=x, mode=mode,
                         pad_width=tuple(pad_width),
                         constant_value=constant_value)

    def f(a):
        pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(a.ndim)]
        jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
        if jmode == "constant":
            return jnp.pad(a, pw, mode=jmode, constant_values=constant_value)
        return jnp.pad(a, pw, mode=jmode)
    return _unary(f, x, "pad")


def diag(x, k=0):
    return _unary(lambda a: jnp.diag(a, k) if a.ndim <= 2 else jnp.diagonal(a, k, -2, -1),
                  x, "diag")


def tril(x, k=0):
    return _unary(lambda a: jnp.tril(a, k), x, "tril")


def triu(x, k=0):
    return _unary(lambda a: jnp.triu(a, k), x, "triu")


def roll(x, shift, axis=None):
    return _unary(lambda a: jnp.roll(a, shift, axis), x, "roll")


# ===========================================================================
# indexing-ish ops
# ===========================================================================

def take(x, indices, axis=0, mode="clip"):
    indices = _as_nd(indices)
    return _apply(lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=axis, mode=mode),
                  [x, indices], name="take")


def pick(x, index, axis=-1, keepdims=False):
    index = _as_nd(index)

    def f(a, i):
        r = jnp.take_along_axis(a, jnp.expand_dims(i.astype(jnp.int32), axis), axis=axis)
        return r if keepdims else jnp.squeeze(r, axis)
    return _apply(f, [x, index], name="pick")


def gather_nd(x, indices):
    indices = _as_nd(indices)

    def f(a, idx):
        idx = idx.astype(jnp.int32)
        m = idx.shape[0]
        return a[tuple(idx[i] for i in range(m))]
    return _apply(f, [x, indices], name="gather_nd")


def scatter_nd(data, indices, shape):
    """Parity: mx.nd.scatter_nd (src/operator/tensor/indexing_op.cc) —
    inverse of gather_nd; duplicate indices take the last write (the
    reference leaves duplicates undefined)."""
    data = _as_nd(data)
    indices = _as_nd(indices)

    def f(vals, idx):
        idx = idx.astype(jnp.int32)
        m = idx.shape[0]
        out = jnp.zeros(tuple(shape), vals.dtype)
        return out.at[tuple(idx[i] for i in range(m))].set(vals)
    return _apply(f, [data, indices], name="scatter_nd")


def batch_take(a, indices):
    """Parity: mx.nd.batch_take — out[i] = a[i, indices[i]]."""
    indices = _as_nd(indices)

    def f(x, i):
        return jnp.take_along_axis(x, i.astype(jnp.int32)[:, None],
                                   axis=1)[:, 0]
    return _apply(f, [a, indices], name="batch_take")


def reverse(data, axis=0):
    """Parity: mx.nd.reverse — flip along the given axis/axes."""
    axes = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return _apply(lambda x: jnp.flip(x, axis=axes), [data], name="reverse")


flip = reverse


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    indices = _as_nd(indices)

    def f(i):
        oh = jax.nn.one_hot(i.astype(jnp.int32), depth, dtype=normalize_dtype(dtype))
        if on_value != 1.0 or off_value != 0.0:
            oh = oh * (on_value - off_value) + off_value
        return oh
    return _unary(f, indices, "one_hot")


def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False, oor_policy="clip"):
    """Parity: nd.Embedding — lookup rows of `weight` by integer `data`.

    Index handling is the embedding subsystem's ONE policy
    (embedding/lookup.normalize_ids): non-integer carriers are rounded
    (not truncated) to int32, and out-of-range ids follow `oor_policy` —
    ``"clip"`` clamps into ``[0, vocab)``, ``"error"`` raises on concrete
    arrays (clamps inside a trace); occurrences are counted on
    ``embedding/embedding.oor_ids``. Before this, both behaviors were
    whatever the backend's take() did — backend-dependent garbage.

    sparse_grad=True makes the weight's gradient a RowSparseNDArray holding
    only the looked-up rows (parity: Embedding(sparse_grad=True) →
    RowSparse grad, python/mxnet/ndarray/sparse.py). Eager-mode feature;
    inside a traced/hybridized graph it falls back to dense (XLA needs
    static shapes, and the fused step's scatter-add is already optimal)."""
    if _symbolic(data):
        in_dim = input_dim or (weight.shape[0] if hasattr(weight, "shape")
                               else None)
        out_dim = output_dim or (weight.shape[1] if hasattr(weight, "shape")
                                 else None)
        return _sym_call("Embedding", data=data, weight=weight,
                         input_dim=in_dim, output_dim=out_dim)
    from ..embedding import lookup as _emb_lookup
    data = _as_nd(data)
    vocab = int(input_dim if input_dim is not None else weight.shape[0])
    if sparse_grad and not isinstance(data._data, jax.core.Tracer):
        data = _apply(
            lambda i: _emb_lookup.normalize_ids(i, vocab, policy=oor_policy),
            [data], name="normalize_ids")
        return _sparse_embedding(data, weight)
    return _apply(lambda i, w: jnp.take(
                      w, _emb_lookup.normalize_ids(i, vocab,
                                                   policy=oor_policy),
                      axis=0),
                  [data, weight], name="embedding")


def _sparse_embedding(data, weight):
    class _SparseEmbedding(autograd.Function):
        def forward(self, d, w):
            self.save_for_backward(d)
            self._wshape = tuple(w.shape)
            return NDArray(jnp.take(w._data, d._data.astype(jnp.int32), axis=0))

        def backward(self, dy):
            from . import sparse as _sp
            (d,) = self._saved
            ids = np.asarray(d._data).astype(np.int64).ravel()
            uids, pos = np.unique(ids, return_inverse=True)
            dim = dy._data.shape[-1]
            vals = jax.ops.segment_sum(dy._data.reshape(-1, dim),
                                       jnp.asarray(pos),
                                       num_segments=len(uids))
            return (NDArray(jnp.zeros_like(d._data)),
                    _sp.RowSparseNDArray(vals, uids, self._wshape))

    return _SparseEmbedding()(data, weight)


Embedding = embedding


def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    def move(a):
        return jnp.moveaxis(a, axis, -1)

    if ret_typ not in ("indices", "value", "both", "mask"):
        raise ValueError(f"topk ret_typ must be indices|value|both|mask, got {ret_typ!r}")

    def f(a):
        m = move(a)
        vals, idx = lax.top_k(jnp.negative(m) if is_ascend else m, k)
        if is_ascend:
            vals = -vals
        if ret_typ == "mask":
            oh = jax.nn.one_hot(idx, m.shape[-1], dtype=normalize_dtype(dtype))
            return jnp.moveaxis(oh.sum(-2), -1, axis)
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
        if ret_typ == "value":
            return vals
        if ret_typ == "both":
            return (vals, idx.astype(normalize_dtype(dtype)))
        return idx.astype(normalize_dtype(dtype))

    n_out = 2 if ret_typ == "both" else 1
    return _apply(f, [x], n_out=n_out, name="topk")


def sort(x, axis=-1, is_ascend=True):
    def f(a):
        s = jnp.sort(a, axis=axis)
        return s if is_ascend else jnp.flip(s, axis=axis)
    return _unary(f, x, "sort")


def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    def f(a):
        s = jnp.argsort(a, axis=axis)
        if not is_ascend:
            s = jnp.flip(s, axis=axis)
        return s.astype(normalize_dtype(dtype))
    return _unary(f, x, "argsort")


def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    """Parity: nd.SequenceMask — mask positions beyond each sequence length.
    `data` layout: (seq, batch, ...) for axis=0, (batch, seq, ...) for axis=1."""
    if not use_sequence_length or sequence_length is None:
        return data
    sequence_length = _as_nd(sequence_length)

    def f(a, sl):
        seq = a.shape[axis]
        pos = jnp.arange(seq)
        mask = pos[None, :] < sl[:, None].astype(jnp.int32)  # (batch, seq)
        if axis == 0:
            mask = mask.T  # (seq, batch)
        mask = mask.reshape(mask.shape + (1,) * (a.ndim - 2))
        return jnp.where(mask, a, jnp.asarray(value, a.dtype))
    return _apply(f, [data, sequence_length], name="sequence_mask")


SequenceMask = sequence_mask


# ===========================================================================
# linear algebra
# ===========================================================================

def dot(a, b, transpose_a=False, transpose_b=False):
    """MXNet dot: contract last axis of a with first axis of b."""
    if _symbolic(a) or _symbolic(b):
        return _sym_call("dot", lhs=a, rhs=b, transpose_a=transpose_a,
                         transpose_b=transpose_b)
    from ..ops import _raw as _raw_ops
    return _apply(lambda x, y: _raw_ops.dot_mx(x, y, transpose_a,
                                               transpose_b),
                  [a, b], name="dot")


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    def f(x, y):
        if transpose_a:
            x = jnp.swapaxes(x, -1, -2)
        if transpose_b:
            y = jnp.swapaxes(y, -1, -2)
        return jnp.matmul(x, y)
    return _apply(f, [a, b], name="batch_dot")


def matmul(a, b):
    return _binary(jnp.matmul, a, b, "matmul")


def einsum(subscripts, *operands):
    return _apply(lambda *xs: jnp.einsum(subscripts, *xs), list(operands), name="einsum")


def outer(a, b):
    return _apply(jnp.outer, [a, b], name="outer")


# ===========================================================================
# persistence (parity: mx.nd.save / mx.nd.load)
# ===========================================================================

def save(fname, data):
    """Save NDArray | list[NDArray] | dict[str, NDArray]."""
    if isinstance(data, NDArray):
        payload = ("single", np.asarray(data._data))
    elif isinstance(data, (list, tuple)):
        payload = ("list", [np.asarray(x._data) for x in data])
    elif isinstance(data, dict):
        payload = ("dict", {k: np.asarray(v._data) for k, v in data.items()})
    else:
        raise TypeError(f"cannot save {type(data)}")
    with open(fname, "wb") as f:
        pickle.dump(payload, f, protocol=4)


def load(fname):
    with open(fname, "rb") as f:
        kind, payload = pickle.load(f)
    if kind == "single":
        return array(payload)
    if kind == "list":
        return [array(x) for x in payload]
    return {k: array(v) for k, v in payload.items()}


def waitall():
    """Parity: mx.nd.waitall — barrier on all outstanding async work
    (flushes this thread's pending bulk segment first; unconditional so a
    segment left pending after its scope/auto-bulk ended still runs)."""
    _bulk.flush("read")
    (jax.device_put(0.0) + 0).block_until_ready()


def moveaxis(x, source, destination):
    return _unary(lambda a: jnp.moveaxis(a, source, destination), x, "moveaxis")


def cast(x, dtype):
    return x.astype(dtype)


Cast = cast


def stop_gradient(x):
    return _unary(lax.stop_gradient, x, "stop_gradient")


BlockGrad = stop_gradient
block_grad = stop_gradient

from . import random  # noqa: E402  (registers nd.random namespace)
from .random import shuffle  # noqa: E402
from . import sparse  # noqa: E402  (registers nd.sparse namespace)
from . import linalg  # noqa: E402  (registers nd.linalg namespace)


def Custom(*args, op_type=None, **kwargs):
    """mx.nd.Custom (parity: python/mxnet/operator.py eager path): run a
    registered CustomOp on concrete arrays; its backward is recorded on
    the autograd tape."""
    from .. import operator as _operator
    if op_type is None:
        raise ValueError("Custom requires op_type=")
    return _operator.eager_custom(list(args), dict(kwargs, op_type=op_type))


def meshgrid(*arrays, indexing="xy"):
    """Parity: np.meshgrid surface used by reference scripts."""
    arrs = [_as_nd(a) for a in arrays]
    if len(arrs) == 1:
        return [_apply(lambda r: jnp.meshgrid(r, indexing=indexing)[0],
                       arrs, name="meshgrid")]
    outs = _apply(lambda *raws: tuple(jnp.meshgrid(*raws, indexing=indexing)),
                  arrs, n_out=len(arrs), name="meshgrid")
    return list(outs)


def shape_array(x):
    """Parity: mx.nd.shape_array — the shape as a 1-D integer array
    (int32 here: the TPU-native index dtype; the reference uses int64)."""
    return NDArray(jnp.asarray(np.asarray(x.shape, np.int32)))


def size_array(x):
    """Parity: mx.nd.size_array (int32, see shape_array)."""
    return NDArray(jnp.asarray(np.asarray([x.size], np.int32)))


def gamma(x):
    """Parity: mx.nd.gamma — the gamma function Γ(x), including the
    alternating sign on the negative non-integer axis (exp(gammaln) alone
    is |Γ|)."""
    def f(a):
        mag = jnp.exp(jax.scipy.special.gammaln(a))
        neg_sign = jnp.where(jnp.floor(a) % 2 == 0, 1.0, -1.0)
        return jnp.where(a > 0, mag, neg_sign * mag).astype(mag.dtype)
    return _unary(f, x, name="gamma")


def hard_sigmoid(x, alpha=0.2, beta=0.5):
    """Parity: mx.nd.hard_sigmoid."""
    return _unary(lambda a: jnp.clip(alpha * a + beta, 0.0, 1.0), x,
                  name="hard_sigmoid")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return _unary(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                           neginf=neginf), x,
                  name="nan_to_num")


def depth_to_space(x, block_size):
    """Parity: mx.nd.depth_to_space (NCHW, DCR order like the reference)."""
    b = int(block_size)

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, b, b, c // (b * b), h, w)
        a = jnp.transpose(a, (0, 3, 4, 1, 5, 2))
        return a.reshape(n, c // (b * b), h * b, w * b)
    return _unary(f, x, name="depth_to_space")


def space_to_depth(x, block_size):
    """Parity: mx.nd.space_to_depth (inverse of depth_to_space)."""
    b = int(block_size)

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // b, b, w // b, b)
        a = jnp.transpose(a, (0, 3, 5, 1, 2, 4))
        return a.reshape(n, c * b * b, h // b, w // b)
    return _unary(f, x, name="space_to_depth")


def ravel_multi_index(data, shape):
    """Parity: mx.nd.ravel_multi_index — data (M, N) column-per-point."""
    def f(a):
        idx = a.astype(jnp.int32)
        strides = np.cumprod([1] + list(shape[::-1]))[::-1][1:]
        strides = jnp.asarray(np.asarray(strides, np.int32))
        return (idx * strides[:, None]).sum(axis=0)
    return _unary(f, _as_nd(data), name="ravel_multi_index")


def unravel_index(data, shape):
    """Parity: mx.nd.unravel_index — returns (M, N) column-per-point."""
    def f(a):
        outs = jnp.unravel_index(a.astype(jnp.int32), shape)
        return jnp.stack(outs, axis=0)
    return _unary(f, _as_nd(data), name="unravel_index")


def hsplit(x, num_outputs):
    return split(x, num_outputs, axis=1)


def vsplit(x, num_outputs):
    return split(x, num_outputs, axis=0)


Pad = pad
