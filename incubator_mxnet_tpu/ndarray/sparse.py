"""Sparse NDArray (parity: python/mxnet/ndarray/sparse.py +
src/operator/tensor/cast_storage-inl.h).

Two storage types, as in the reference:

* ``RowSparseNDArray`` — (indices, data) where ``indices`` are the ids of
  the non-zero ROWS (sorted, unique) and ``data`` stacks those rows. The
  workhorse for sparse embedding gradients and ``kv.row_sparse_pull``.
* ``CSRNDArray`` — classic (data, indices, indptr) compressed rows, for
  sparse input features and ``sparse.dot``.

TPU-first design notes: XLA has no dynamic sparse layouts, so every
*operation* here is a static-shape computation over the materialized
(nnz,…) buffers — ``take``/``segment_sum`` on the MXU-friendly dense
carriers, jit-compatible once nnz is fixed. Only *construction* from a
dense array (``cast_storage``) inspects values on the host: that mirrors
the reference, where cast_storage is likewise a data-dependent kernel and
never sits in a jitted hot loop.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import NDArray, _as_nd

__all__ = [
    "BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
    "row_sparse_array", "csr_matrix", "cast_storage", "retain", "dot",
    "zeros", "array",
]


class BaseSparseNDArray:
    stype = "undefined"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._shape))

    def asnumpy(self):
        return np.asarray(self.todense()._data)

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self.todense(), stype)

    def astype(self, dtype):
        raise NotImplementedError

    def __repr__(self):
        return (f"<{type(self).__name__} {self._shape} "
                f"dtype={self.dtype} nnz={self.nnz}>")


class RowSparseNDArray(BaseSparseNDArray):
    """Rows `indices` of an abstract dense (N, ...) array, stacked in `data`
    of shape (nnz_rows, ...). Parity: mx.nd.sparse.RowSparseNDArray."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self._data = jnp.asarray(data)
        self.indices = jnp.asarray(indices, jnp.int32)
        self._shape = tuple(int(s) for s in shape)
        if self._data.ndim != len(self._shape):
            raise ValueError(
                f"row_sparse data ndim {self._data.ndim} must match "
                f"shape ndim {len(self._shape)}")

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def nnz(self):
        return int(self.indices.shape[0])

    def todense(self) -> NDArray:
        dense = jnp.zeros(self._shape, self._data.dtype)
        if self.nnz:
            dense = dense.at[self.indices].set(self._data)
        return NDArray(dense)

    def astype(self, dtype):
        return RowSparseNDArray(self._data.astype(dtype), self.indices,
                                self._shape)

    def copy(self):
        return RowSparseNDArray(self._data, self.indices, self._shape)

    def retain(self, row_ids):
        """Keep only rows whose id is in `row_ids` (parity:
        sparse.retain)."""
        ids = _row_ids_np(row_ids)
        mine = np.asarray(self.indices)
        keep = np.isin(mine, ids)
        sel = np.nonzero(keep)[0]
        return RowSparseNDArray(jnp.take(self._data, jnp.asarray(sel), axis=0),
                                mine[sel], self._shape)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            if other._shape != self._shape:
                raise ValueError("shape mismatch in row_sparse add")
            ids = np.concatenate([np.asarray(self.indices),
                                  np.asarray(other.indices)])
            uids, pos = np.unique(ids, return_inverse=True)
            vals = jnp.concatenate([self._data, other._data], axis=0)
            merged = jax.ops.segment_sum(vals, jnp.asarray(pos),
                                         num_segments=len(uids))
            return RowSparseNDArray(merged, uids, self._shape)
        if isinstance(other, NDArray):
            return self.todense() + other
        return NotImplemented

    __radd__ = __add__

    def __mul__(self, scalar):
        return RowSparseNDArray(self._data * scalar, self.indices, self._shape)

    __rmul__ = __mul__


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (parity: mx.nd.sparse.CSRNDArray)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self._data = jnp.asarray(data)
        self.indices = jnp.asarray(indices, jnp.int32)
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) != 2:
            raise ValueError("CSR requires a 2-D shape")

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def nnz(self):
        return int(self._data.shape[0])

    def _row_of_nnz(self):
        counts = np.diff(np.asarray(self.indptr))
        return jnp.asarray(np.repeat(np.arange(self._shape[0]), counts))

    def todense(self) -> NDArray:
        dense = jnp.zeros(self._shape, self._data.dtype)
        if self.nnz:
            rows = self._row_of_nnz()
            dense = dense.at[rows, self.indices].set(self._data)
        return NDArray(dense)

    def astype(self, dtype):
        return CSRNDArray(self._data.astype(dtype), self.indices,
                          self.indptr, self._shape)

    def copy(self):
        return CSRNDArray(self._data, self.indices, self.indptr, self._shape)


# ---------------------------------------------------------------------------
# construction / conversion
# ---------------------------------------------------------------------------

def _row_ids_np(row_ids):
    if isinstance(row_ids, NDArray):
        return np.asarray(row_ids._data).astype(np.int64).ravel()
    return np.asarray(row_ids).astype(np.int64).ravel()


def row_sparse_array(arg, shape=None, dtype=None) -> RowSparseNDArray:
    """row_sparse_array((data, indices), shape) or from a dense source."""
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        data = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        if dtype is not None:
            data = data.astype(dtype)
        indices = _row_ids_np(indices)
        order = np.argsort(indices)
        if not np.all(order == np.arange(len(order))):
            indices = indices[order]
            data = jnp.take(data, jnp.asarray(order), axis=0)
        if shape is None:
            raise ValueError("shape required for (data, indices) form")
        return RowSparseNDArray(data, indices, shape)
    if isinstance(arg, RowSparseNDArray):
        return arg
    dense = arg if isinstance(arg, NDArray) else NDArray(jnp.asarray(arg))
    if dtype is not None:
        dense = dense.astype(dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg, shape=None, dtype=None) -> CSRNDArray:
    """csr_matrix((data, indices, indptr), shape) or from a dense source."""
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        data = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        if dtype is not None:
            data = data.astype(dtype)
        if shape is None:
            raise ValueError("shape required for (data, indices, indptr) form")
        return CSRNDArray(data, indices, indptr, shape)
    if isinstance(arg, CSRNDArray):
        return arg
    dense = arg if isinstance(arg, NDArray) else NDArray(jnp.asarray(arg))
    if dtype is not None:
        dense = dense.astype(dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr, stype):
    """Parity: mx.nd.sparse.cast_storage / src/operator/tensor/cast_storage.
    Dense→sparse inspects values on the host (data-dependent nnz, like the
    reference kernel); sparse→dense is a device scatter."""
    if isinstance(arr, BaseSparseNDArray):
        if stype == "default":
            return arr.todense()
        return arr.tostype(stype)
    arr = _as_nd(arr)
    if stype == "default":
        return arr
    host = np.asarray(arr._data)
    if stype == "row_sparse":
        nz = np.nonzero(host.reshape(host.shape[0], -1).any(axis=1))[0]
        return RowSparseNDArray(jnp.take(arr._data, jnp.asarray(nz), axis=0),
                                nz, host.shape)
    if stype == "csr":
        if host.ndim != 2:
            raise ValueError("csr cast requires 2-D input")
        rows, cols = np.nonzero(host)
        indptr = np.zeros(host.shape[0] + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        indptr = np.cumsum(indptr)
        return CSRNDArray(host[rows, cols], cols, indptr, host.shape)
    raise ValueError(f"unknown storage type {stype!r}")


def retain(rsp: RowSparseNDArray, row_ids):
    return rsp.retain(row_ids)


def dot(lhs, rhs, transpose_a=False) -> NDArray:
    """sparse.dot: csr @ dense (and csr.T @ dense), the reference's two
    supported layouts. Static-nnz segment-sum → jit/MXU friendly."""
    if not isinstance(lhs, CSRNDArray):
        raise TypeError("sparse.dot expects a CSRNDArray lhs")
    rhs = _as_nd(rhs)
    rows = lhs._row_of_nnz()
    gathered = jnp.take(rhs._data, lhs.indices, axis=0)  # (nnz, K)
    contrib = lhs._data[:, None] * gathered
    if transpose_a:
        out = jax.ops.segment_sum(contrib, lhs.indices,
                                  num_segments=lhs._shape[1])
    else:
        out = jax.ops.segment_sum(contrib, rows,
                                  num_segments=lhs._shape[0])
    return NDArray(out)


def zeros(stype, shape, dtype="float32"):
    if stype == "row_sparse":
        tail = tuple(shape[1:])
        return RowSparseNDArray(jnp.zeros((0,) + tail, dtype),
                                np.zeros((0,), np.int64), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), np.zeros((0,), np.int64),
                          np.zeros(shape[0] + 1, np.int64), shape)
    raise ValueError(f"unknown storage type {stype!r}")


def array(source, stype="row_sparse", dtype=None):
    if stype == "row_sparse":
        return row_sparse_array(source, dtype=dtype)
    if stype == "csr":
        return csr_matrix(source, dtype=dtype)
    raise ValueError(f"unknown storage type {stype!r}")
