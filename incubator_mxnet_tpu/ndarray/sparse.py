"""Sparse NDArray (parity: python/mxnet/ndarray/sparse.py +
src/operator/tensor/cast_storage-inl.h).

Two storage types, as in the reference:

* ``RowSparseNDArray`` — (indices, data) where ``indices`` are the ids of
  the non-zero ROWS (sorted, unique) and ``data`` stacks those rows. The
  workhorse for sparse embedding gradients and ``kv.row_sparse_pull``.
* ``CSRNDArray`` — classic (data, indices, indptr) compressed rows, for
  sparse input features and ``sparse.dot``.

TPU-first design notes: XLA has no dynamic sparse layouts, so every
*operation* here is a static-shape computation over the materialized
(nnz,…) buffers — ``take``/``segment_sum`` on the MXU-friendly dense
carriers, jit-compatible once nnz is fixed. Only *construction* from a
dense array (``cast_storage``) inspects values on the host: that mirrors
the reference, where cast_storage is likewise a data-dependent kernel and
never sits in a jitted hot loop.
"""
from __future__ import annotations

import warnings

import numpy as np

import jax
import jax.numpy as jnp

from . import NDArray, _as_nd

__all__ = [
    "BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
    "row_sparse_array", "csr_matrix", "cast_storage", "retain", "dot",
    "zeros", "array", "add", "subtract", "multiply", "divide",
    "elemwise_add", "elemwise_sub", "elemwise_mul",
]


class StorageFallbackWarning(UserWarning):
    """An operation on sparse inputs fell back to dense compute (parity:
    the reference's FComputeFallback log warning,
    src/operator/operator_common.h LogStorageFallback)."""


_FALLBACK_WARNED = set()


def _stype_of(x):
    return x.stype if isinstance(x, BaseSparseNDArray) else "default"


def _warn_fallback(op, *operands):
    key = (op,) + tuple(_stype_of(o) for o in operands)
    if key not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(key)
        warnings.warn(
            "sparse storage fallback: %s(%s) has no sparse kernel and is "
            "computed via dense temporaries" % (op, ", ".join(key[1:])),
            StorageFallbackWarning, stacklevel=3)


def _to_dense_nd(x):
    return x.todense() if isinstance(x, BaseSparseNDArray) else _as_nd(x)


class BaseSparseNDArray:
    stype = "undefined"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._shape))

    def asnumpy(self):
        return np.asarray(self.todense()._data)

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self.todense(), stype)

    def astype(self, dtype):
        raise NotImplementedError

    def __repr__(self):
        return (f"<{type(self).__name__} {self._shape} "
                f"dtype={self.dtype} nnz={self.nnz}>")

    # arithmetic routes through the storage-aware module functions below
    def __add__(self, other):
        return add(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        return subtract(self, other)

    def __rsub__(self, other):
        return subtract(other, self)

    def __mul__(self, other):
        return multiply(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return divide(self, other)

    def __rtruediv__(self, other):
        return divide(other, self)


class RowSparseNDArray(BaseSparseNDArray):
    """Rows `indices` of an abstract dense (N, ...) array, stacked in `data`
    of shape (nnz_rows, ...). Parity: mx.nd.sparse.RowSparseNDArray."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        self._data = jnp.asarray(data)
        self.indices = jnp.asarray(indices, jnp.int32)
        self._shape = tuple(int(s) for s in shape)
        if self._data.ndim != len(self._shape):
            raise ValueError(
                f"row_sparse data ndim {self._data.ndim} must match "
                f"shape ndim {len(self._shape)}")

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def nnz(self):
        return int(self.indices.shape[0])

    def todense(self) -> NDArray:
        dense = jnp.zeros(self._shape, self._data.dtype)
        if self.nnz:
            dense = dense.at[self.indices].set(self._data)
        return NDArray(dense)

    def astype(self, dtype):
        return RowSparseNDArray(self._data.astype(dtype), self.indices,
                                self._shape)

    def copy(self):
        return RowSparseNDArray(self._data, self.indices, self._shape)

    def retain(self, row_ids):
        """Keep only rows whose id is in `row_ids` (parity:
        sparse.retain)."""
        ids = _row_ids_np(row_ids)
        mine = np.asarray(self.indices)
        keep = np.isin(mine, ids)
        sel = np.nonzero(keep)[0]
        return RowSparseNDArray(jnp.take(self._data, jnp.asarray(sel), axis=0),
                                mine[sel], self._shape)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (parity: mx.nd.sparse.CSRNDArray)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self._data = jnp.asarray(data)
        self.indices = jnp.asarray(indices, jnp.int32)
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) != 2:
            raise ValueError("CSR requires a 2-D shape")

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def nnz(self):
        return int(self._data.shape[0])

    def _row_of_nnz(self):
        counts = np.diff(np.asarray(self.indptr))
        return jnp.asarray(np.repeat(np.arange(self._shape[0]), counts))

    def todense(self) -> NDArray:
        dense = jnp.zeros(self._shape, self._data.dtype)
        if self.nnz:
            rows = self._row_of_nnz()
            dense = dense.at[rows, self.indices].set(self._data)
        return NDArray(dense)

    def astype(self, dtype):
        return CSRNDArray(self._data.astype(dtype), self.indices,
                          self.indptr, self._shape)

    def copy(self):
        return CSRNDArray(self._data, self.indices, self.indptr, self._shape)


# ---------------------------------------------------------------------------
# construction / conversion
# ---------------------------------------------------------------------------

def _row_ids_np(row_ids):
    if isinstance(row_ids, NDArray):
        return np.asarray(row_ids._data).astype(np.int64).ravel()
    return np.asarray(row_ids).astype(np.int64).ravel()


def row_sparse_array(arg, shape=None, dtype=None) -> RowSparseNDArray:
    """row_sparse_array((data, indices), shape) or from a dense source."""
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        data = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        if dtype is not None:
            data = data.astype(dtype)
        indices = _row_ids_np(indices)
        order = np.argsort(indices)
        if not np.all(order == np.arange(len(order))):
            indices = indices[order]
            data = jnp.take(data, jnp.asarray(order), axis=0)
        if shape is None:
            raise ValueError("shape required for (data, indices) form")
        return RowSparseNDArray(data, indices, shape)
    if isinstance(arg, RowSparseNDArray):
        return arg
    dense = arg if isinstance(arg, NDArray) else NDArray(jnp.asarray(arg))
    if dtype is not None:
        dense = dense.astype(dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg, shape=None, dtype=None) -> CSRNDArray:
    """csr_matrix((data, indices, indptr), shape) or from a dense source."""
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        data = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        if dtype is not None:
            data = data.astype(dtype)
        if shape is None:
            raise ValueError("shape required for (data, indices, indptr) form")
        return CSRNDArray(data, indices, indptr, shape)
    if isinstance(arg, CSRNDArray):
        return arg
    dense = arg if isinstance(arg, NDArray) else NDArray(jnp.asarray(arg))
    if dtype is not None:
        dense = dense.astype(dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr, stype):
    """Parity: mx.nd.sparse.cast_storage / src/operator/tensor/cast_storage.
    Dense→sparse inspects values on the host (data-dependent nnz, like the
    reference kernel); sparse→dense is a device scatter."""
    if isinstance(arr, BaseSparseNDArray):
        if stype == "default":
            return arr.todense()
        return arr.tostype(stype)
    arr = _as_nd(arr)
    if stype == "default":
        return arr
    host = np.asarray(arr._data)
    if stype == "row_sparse":
        nz = np.nonzero(host.reshape(host.shape[0], -1).any(axis=1))[0]
        return RowSparseNDArray(jnp.take(arr._data, jnp.asarray(nz), axis=0),
                                nz, host.shape)
    if stype == "csr":
        if host.ndim != 2:
            raise ValueError("csr cast requires 2-D input")
        rows, cols = np.nonzero(host)
        indptr = np.zeros(host.shape[0] + 1, np.int64)
        np.add.at(indptr, rows + 1, 1)
        indptr = np.cumsum(indptr)
        return CSRNDArray(host[rows, cols], cols, indptr, host.shape)
    raise ValueError(f"unknown storage type {stype!r}")


def retain(rsp: RowSparseNDArray, row_ids):
    return rsp.retain(row_ids)


# ---------------------------------------------------------------------------
# elementwise algebra (parity: python/mxnet/ndarray/sparse.py elemwise_add/
# sub/mul and the arithmetic operators on sparse arrays). Sparse-sparse
# kernels keep the result sparse: index merging happens on the host (data-
# dependent nnz, like cast_storage), value arithmetic on device. Mixed
# sparse/dense combinations fall back to dense with a StorageFallbackWarning
# — the reference's LogStorageFallback behavior.
# ---------------------------------------------------------------------------

def _csr_keys(csr):
    # host-only: indptr/indices are the layout metadata; no device traffic
    counts = np.diff(np.asarray(csr.indptr))
    rows = np.repeat(np.arange(csr._shape[0], dtype=np.int64), counts)
    cols = np.asarray(csr.indices, np.int64)
    return rows * csr._shape[1] + cols


def _csr_from_keys(keys, values, shape):
    rows = keys // shape[1]
    cols = keys % shape[1]
    indptr = np.zeros(shape[0] + 1, np.int64)
    np.add.at(indptr, rows + 1, 1)
    return CSRNDArray(values, cols, np.cumsum(indptr), shape)


def _csr_union(a, b, negate_b=False):
    ka, kb = _csr_keys(a), _csr_keys(b)
    uk, inv = np.unique(np.concatenate([ka, kb]), return_inverse=True)
    vb = -b._data if negate_b else b._data
    vals = jax.ops.segment_sum(jnp.concatenate([a._data, vb]),
                               jnp.asarray(inv), num_segments=len(uk))
    return _csr_from_keys(uk, vals, a._shape)


def _csr_intersect_mul(a, b):
    ka, kb = _csr_keys(a), _csr_keys(b)
    common, ia, ib = np.intersect1d(ka, kb, return_indices=True)
    vals = (jnp.take(a._data, jnp.asarray(ia))
            * jnp.take(b._data, jnp.asarray(ib)))
    return _csr_from_keys(common, vals, a._shape)


def _rsp_union(a, b, negate_b=False):
    ids = np.concatenate([np.asarray(a.indices, np.int64),
                          np.asarray(b.indices, np.int64)])
    uids, inv = np.unique(ids, return_inverse=True)
    vb = -b._data if negate_b else b._data
    vals = jax.ops.segment_sum(jnp.concatenate([a._data, vb], axis=0),
                               jnp.asarray(inv), num_segments=len(uids))
    return RowSparseNDArray(vals, uids, a._shape)


def _rsp_intersect_mul(a, b):
    common, ia, ib = np.intersect1d(np.asarray(a.indices, np.int64),
                                    np.asarray(b.indices, np.int64),
                                    return_indices=True)
    vals = (jnp.take(a._data, jnp.asarray(ia), axis=0)
            * jnp.take(b._data, jnp.asarray(ib), axis=0))
    return RowSparseNDArray(vals, common, a._shape)


def _check_same_shape(op, lhs, rhs):
    if tuple(lhs.shape) != tuple(rhs.shape):
        raise ValueError("%s: shape mismatch %s vs %s"
                         % (op, lhs.shape, rhs.shape))


def _is_scalar(x):
    if np.isscalar(x):
        return True
    if isinstance(x, NDArray):
        return x.shape == ()
    return (isinstance(x, (np.ndarray, jnp.ndarray))
            and getattr(x, "ndim", 1) == 0)


def _scalar_raw(x):
    """Value usable in device arithmetic (keeps 0-d NDArrays on device)."""
    return x._data if isinstance(x, NDArray) else x


def add(lhs, rhs):
    """Storage-aware add: csr+csr -> csr, rsp+rsp -> rsp, anything mixed
    with dense -> dense (with fallback warning)."""
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        _check_same_shape("add", lhs, rhs)
        return _csr_union(lhs, rhs)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        _check_same_shape("add", lhs, rhs)
        return _rsp_union(lhs, rhs)
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs,
                                                        BaseSparseNDArray):
        _warn_fallback("elemwise_add", lhs, rhs)
        return _to_dense_nd(lhs) + _to_dense_nd(rhs)
    return _as_nd(lhs) + _as_nd(rhs)


def subtract(lhs, rhs):
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        _check_same_shape("subtract", lhs, rhs)
        return _csr_union(lhs, rhs, negate_b=True)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        _check_same_shape("subtract", lhs, rhs)
        return _rsp_union(lhs, rhs, negate_b=True)
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs,
                                                        BaseSparseNDArray):
        _warn_fallback("elemwise_sub", lhs, rhs)
        return _to_dense_nd(lhs) - _to_dense_nd(rhs)
    return _as_nd(lhs) - _as_nd(rhs)


def multiply(lhs, rhs):
    """Storage-aware multiply. Sparse*scalar and sparse*sparse stay sparse
    (intersection of patterns); sparse*dense keeps the SPARSE pattern
    (zeros absorb), matching the reference's elemwise_mul(csr, default) ->
    csr kernel."""
    if _is_scalar(rhs):
        lhs, rhs = rhs, lhs
    if _is_scalar(lhs):
        s = _scalar_raw(lhs)
        if isinstance(rhs, CSRNDArray):
            return CSRNDArray(rhs._data * s, rhs.indices, rhs.indptr,
                              rhs._shape)
        if isinstance(rhs, RowSparseNDArray):
            return RowSparseNDArray(rhs._data * s, rhs.indices,
                                    rhs._shape)
        return _as_nd(rhs) * s
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        _check_same_shape("multiply", lhs, rhs)
        return _csr_intersect_mul(lhs, rhs)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        _check_same_shape("multiply", lhs, rhs)
        return _rsp_intersect_mul(lhs, rhs)
    # sparse * dense: gather dense values at the sparse pattern
    for a, b in ((lhs, rhs), (rhs, lhs)):
        if isinstance(a, CSRNDArray) and isinstance(b, NDArray):
            _check_same_shape("multiply", a, b)
            rows = a._row_of_nnz()
            dvals = b._data[rows, a.indices]
            return CSRNDArray(a._data * dvals, a.indices, a.indptr,
                              a._shape)
        if isinstance(a, RowSparseNDArray) and isinstance(b, NDArray):
            _check_same_shape("multiply", a, b)
            dvals = jnp.take(b._data, a.indices, axis=0)
            return RowSparseNDArray(a._data * dvals, a.indices, a._shape)
    return _as_nd(lhs) * _as_nd(rhs)


def divide(lhs, rhs):
    if _is_scalar(rhs):
        # direct division: array semantics for /0 (inf/nan, no Python
        # ZeroDivisionError) and full precision for large divisors
        s = _scalar_raw(rhs)
        if isinstance(lhs, CSRNDArray):
            return CSRNDArray(lhs._data / s, lhs.indices, lhs.indptr,
                              lhs._shape)
        if isinstance(lhs, RowSparseNDArray):
            return RowSparseNDArray(lhs._data / s, lhs.indices, lhs._shape)
        return _as_nd(lhs) / s
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs,
                                                        BaseSparseNDArray):
        # no sparse division kernel in the reference either (0/0 hazards)
        _warn_fallback("elemwise_div", lhs, rhs)
        return _to_dense_nd(lhs) / _to_dense_nd(rhs)
    return _as_nd(lhs) / _as_nd(rhs)


elemwise_add = add
elemwise_sub = subtract
elemwise_mul = multiply


def dot(lhs, rhs, transpose_a=False, transpose_b=False) -> NDArray:
    """sparse.dot (parity: mx.nd.sparse.dot / src/operator/tensor/dot-inl.h).

    Supported layouts, all static-nnz segment-sums (jit/MXU friendly):
      dot(csr, dense)       dot(csr.T, dense)      [the reference's core two]
      dot(csr, row_sparse)  dot(csr.T, row_sparse) [rhs rows materialized]
      dot(dense, csr)   = (csr.T @ dense.T).T      [transpose identity]
      dot(dense, csr.T) = (csr  @ dense.T).T
    """
    if isinstance(lhs, NDArray) and isinstance(rhs, CSRNDArray):
        if transpose_a:
            raise NotImplementedError("dot(dense.T, csr) is unsupported "
                                      "(as in the reference)")
        out = dot(rhs, NDArray(jnp.swapaxes(lhs._data, -1, -2)),
                  transpose_a=not transpose_b)
        return NDArray(jnp.swapaxes(out._data, -1, -2))
    if not isinstance(lhs, CSRNDArray):
        raise TypeError("sparse.dot expects a CSRNDArray operand")
    if transpose_b:
        raise NotImplementedError("dot(csr, rhs.T) is unsupported (as in "
                                  "the reference)")
    if isinstance(rhs, RowSparseNDArray):
        rhs = rhs.todense()  # device scatter; pattern is lost in the output
    elif isinstance(rhs, CSRNDArray):
        raise NotImplementedError("dot(csr, csr) is unsupported (as in the "
                                  "reference); densify one operand")
    rhs = _as_nd(rhs)
    rows = lhs._row_of_nnz()
    if transpose_a:
        # (A.T @ Y)[c] = sum_r A[r, c] * Y[r]: gather Y by nnz row ids,
        # scatter-add into the column segments
        gathered = jnp.take(rhs._data, rows, axis=0)          # (nnz, K)
        out = jax.ops.segment_sum(lhs._data[:, None] * gathered,
                                  lhs.indices,
                                  num_segments=lhs._shape[1])
    else:
        gathered = jnp.take(rhs._data, lhs.indices, axis=0)   # (nnz, K)
        out = jax.ops.segment_sum(lhs._data[:, None] * gathered, rows,
                                  num_segments=lhs._shape[0])
    return NDArray(out)


def zeros(stype, shape, dtype="float32"):
    if stype == "row_sparse":
        tail = tuple(shape[1:])
        return RowSparseNDArray(jnp.zeros((0,) + tail, dtype),
                                np.zeros((0,), np.int64), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), np.zeros((0,), np.int64),
                          np.zeros(shape[0] + 1, np.int64), shape)
    raise ValueError(f"unknown storage type {stype!r}")


def array(source, stype="row_sparse", dtype=None):
    if stype == "row_sparse":
        return row_sparse_array(source, dtype=dtype)
    if stype == "csr":
        return csr_matrix(source, dtype=dtype)
    raise ValueError(f"unknown storage type {stype!r}")
