"""Stateful random sampling (parity: mx.nd.random + mx.random.seed).

MXNet keeps per-device RNG state; here a process-global PRNG key is split on
every draw, so eager sampling is stateful like the reference while each draw
itself is a pure jax op. Inside jitted code (hybridized blocks), layers that
need randomness (Dropout) thread keys explicitly instead — this module is the
eager/imperative surface.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..base import normalize_dtype
from ..context import current_context
from . import NDArray

# Process-global key (parity: mx.random.seed seeds every consumer, including
# worker threads); a lock keeps split() race-free across threads.
_lock = threading.Lock()
_global_key = None

# While tracing a hybridized block, randomness must derive from a traced key
# (a concrete key would bake one dropout mask into the compiled executable).
# HybridBlock pushes the per-call key here; _key() then splits it functionally.
# Thread-local: another thread's eager sampling must not see this trace's key.
_trace_keys = threading.local()


def _tk_stack():
    if not hasattr(_trace_keys, "stack"):
        _trace_keys.stack = []
    return _trace_keys.stack


class _TraceKeyScope:
    def __init__(self, raw_key):
        self._raw = raw_key

    def __enter__(self):
        _tk_stack().append(self._raw)
        return self

    def __exit__(self, *exc):
        _tk_stack().pop()
        return False


def _ensure_global_key():
    """Materialize (and return) the process-global key WITHOUT consuming
    from it — unlike _key(), this ignores any active trace-key context,
    so checkpoint code can always reach the real global state."""
    global _global_key
    with _lock:
        if _global_key is None:
            _global_key = jax.random.PRNGKey(
                np.random.SeedSequence().entropy % (2**63))
        return _global_key


def _key():
    stack = _tk_stack()
    if stack:
        nxt, sub = jax.random.split(stack[-1])
        stack[-1] = nxt
        return sub
    global _global_key
    _ensure_global_key()
    with _lock:
        _global_key, sub = jax.random.split(_global_key)
    return sub


def seed(seed_state, ctx="all"):
    """Seed every RNG the framework draws from: the jax key chain AND the
    python/numpy global generators the host-side augmenters use (random
    crop/flip/jitter order) — one call makes data augmentation and device
    randomness reproducible together."""
    global _global_key
    import random as _pyrandom
    import numpy as _np
    with _lock:
        _global_key = jax.random.PRNGKey(int(seed_state))
    _pyrandom.seed(int(seed_state))
    _np.random.seed(int(seed_state) % (2 ** 32))


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def _wrap(raw, ctx):
    return NDArray(raw, ctx=ctx or current_context())


def _fill_out(out, r):
    """Overwrite `out` in place: keep its dtype, detach any stale tape node."""
    out._data = r.astype(out._data.dtype)
    out._node = None
    return out


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None):
    if out is not None and shape is None:
        shape = out.shape
    r = jax.random.uniform(_key(), _shape(shape), normalize_dtype(dtype),
                           minval=low, maxval=high)
    if out is not None:
        return _fill_out(out, r)
    return _wrap(r, ctx)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    if out is not None and shape is None:
        shape = out.shape
    r = loc + scale * jax.random.normal(_key(), _shape(shape), normalize_dtype(dtype))
    if out is not None:
        return _fill_out(out, r)
    return _wrap(r, ctx)


randn = lambda *shape, **kw: normal(shape=shape, **kw)  # noqa: E731


def randint(low, high=None, shape=None, dtype="int32", ctx=None):
    if high is None:
        low, high = 0, low
    r = jax.random.randint(_key(), _shape(shape), low, high, normalize_dtype(dtype))
    return _wrap(r, ctx)


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None):
    r = jax.random.bernoulli(_key(), prob, _shape(shape)).astype(normalize_dtype(dtype))
    return _wrap(r, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None):
    r = jax.random.gamma(_key(), alpha, _shape(shape), normalize_dtype(dtype)) * beta
    return _wrap(r, ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None):
    r = jax.random.exponential(_key(), _shape(shape), normalize_dtype(dtype)) * scale
    return _wrap(r, ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None):
    r = jax.random.poisson(_key(), lam, _shape(shape)).astype(normalize_dtype(dtype))
    return _wrap(r, ctx)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None):
    g = jax.random.gamma(_key(), k, _shape(shape)) * (1 - p) / p
    r = jax.random.poisson(_key(), g).astype(normalize_dtype(dtype))
    return _wrap(r, ctx)


def multinomial(data, shape=1, get_prob=False, dtype="int32"):
    """Sample category indices from (batched) probability rows. With
    get_prob=True also return log-prob of each sample (parity: used for
    REINFORCE-style estimators)."""
    n = shape if isinstance(shape, int) else int(np.prod(shape))
    logp = jnp.log(jnp.clip(data._data, 1e-20, None))
    if logp.ndim == 1:
        idx = jax.random.categorical(_key(), logp, shape=(n,))
        sample_logp = jnp.take(logp, idx)
        if n == 1:
            idx, sample_logp = idx[0], sample_logp[0]
    else:
        idx = jax.random.categorical(_key(), logp[:, None, :].repeat(n, 1), axis=-1)
        sample_logp = jnp.take_along_axis(logp, idx, axis=-1)
        if n == 1:
            idx, sample_logp = idx[:, 0], sample_logp[:, 0]
    out = NDArray(idx.astype(normalize_dtype(dtype)))
    if get_prob:
        return out, NDArray(sample_logp)
    return out


categorical = multinomial


def shuffle(data):
    perm = jax.random.permutation(_key(), data._data.shape[0])
    return NDArray(jnp.take(data._data, perm, axis=0))


def permutation(n):
    return NDArray(jax.random.permutation(_key(), int(n)).astype(jnp.int32))


def truncated_normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None):
    r = loc + scale * jax.random.truncated_normal(_key(), -2.0, 2.0, _shape(shape),
                                                  normalize_dtype(dtype))
    return _wrap(r, ctx)


# ---------------------------------------------------------------------------
# sample_* family: per-element distribution parameters (parity:
# mx.nd.sample_uniform/... — src/operator/random/sample_op.cc). Each
# parameter array contributes one output row of `shape` draws.
# ---------------------------------------------------------------------------

def _param_raw(p, dt):
    from . import NDArray
    raw = p._data if isinstance(p, NDArray) else jnp.asarray(p)
    return raw.astype(dt)


def _bcast(p, extra):
    """Parameter array -> shape broadcastable against (p.shape + extra)."""
    return p.reshape(p.shape + (1,) * len(extra))


def _extra(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def sample_uniform(low, high, shape=None, dtype="float32", ctx=None):
    dt = normalize_dtype(dtype)
    low, high = _param_raw(low, dt), _param_raw(high, dt)
    extra = _extra(shape)
    r = jax.random.uniform(_key(), low.shape + extra, dt)
    return _wrap(_bcast(low, extra) + r * _bcast(high - low, extra), ctx)


def sample_normal(mu, sigma, shape=None, dtype="float32", ctx=None):
    dt = normalize_dtype(dtype)
    mu, sigma = _param_raw(mu, dt), _param_raw(sigma, dt)
    extra = _extra(shape)
    r = jax.random.normal(_key(), mu.shape + extra, dt)
    return _wrap(_bcast(mu, extra) + r * _bcast(sigma, extra), ctx)


def sample_exponential(lam, shape=None, dtype="float32", ctx=None):
    dt = normalize_dtype(dtype)
    lam = _param_raw(lam, dt)
    extra = _extra(shape)
    r = jax.random.exponential(_key(), lam.shape + extra, dt)
    return _wrap(r / _bcast(lam, extra), ctx)


def sample_poisson(lam, shape=None, dtype="float32", ctx=None):
    lam = _param_raw(lam, jnp.float32)
    extra = _extra(shape)
    r = jax.random.poisson(_key(), _bcast(lam, extra), lam.shape + extra)
    return _wrap(r.astype(normalize_dtype(dtype)), ctx)


def sample_gamma(alpha, beta, shape=None, dtype="float32", ctx=None):
    dt = normalize_dtype(dtype)
    alpha, beta = _param_raw(alpha, dt), _param_raw(beta, dt)
    extra = _extra(shape)
    r = jax.random.gamma(_key(), _bcast(alpha, extra),
                         alpha.shape + extra, dt)
    return _wrap(r * _bcast(beta, extra), ctx)


def _mirror_samples_into_nd():
    """mx.nd.sample_uniform etc. — the reference exposes the family at
    the nd top level as well as nd.random."""
    import sys
    nd_mod = sys.modules["incubator_mxnet_tpu.ndarray"]
    for n in ("sample_uniform", "sample_normal", "sample_exponential",
              "sample_poisson", "sample_gamma"):
        setattr(nd_mod, n, globals()[n])


_mirror_samples_into_nd()
