"""Multi-host bootstrap (the rebuild of the reference's launcher + ps-lite
topology plumbing: tools/launch.py, dmlc tracker env, kvstore rank/size).

The reference starts schedulers/servers/workers over ssh and wires them
with DMLC_* env vars. TPU-native: every host runs the SAME SPMD program;
`jax.distributed.initialize` forms the cluster (coordinator + N processes),
after which `jax.devices()` spans all hosts and one `Mesh` over it gives
collectives that ride ICI within a pod slice and DCN across slices. KVStore
`rank`/`num_workers` and `dist_*` modes read this state.

Usage (one command per host, reference-launcher style):
    import incubator_mxnet_tpu as mx
    mx.distributed.init(coordinator_address="host0:1234",
                        num_processes=4, process_id=HOST_ID)
    mesh = mx.distributed.global_mesh({"dp": -1})
    # ... FusedTrainStep(net, loss, opt, mesh=mesh) as single-host ...

On TPU pods with the standard runtime, `init()` with no arguments
auto-discovers everything from the pod metadata (jax's default).
"""
from __future__ import annotations

import jax

__all__ = ["init", "shutdown", "rank", "num_workers", "local_devices",
           "global_devices", "global_mesh", "barrier", "is_initialized"]

_state = {"initialized": False}


def init(coordinator_address=None, num_processes=None, process_id=None,
         local_device_ids=None, initialization_timeout=None):
    """Form the multi-host cluster (parity: the reference launcher's
    scheduler rendezvous). No-op when already initialized or single-host
    with no coordinator given.

    Arguments default from the MXTPU_COORDINATOR / MXTPU_NUM_PROCESSES /
    MXTPU_PROCESS_ID environment (set by tools/launch.py, the analogue of
    the reference launcher's DMLC_* variables), so an unmodified training
    script that calls ``mx.distributed.init()`` works under the
    launcher.

    ``initialization_timeout`` (seconds; env MXTPU_INIT_TIMEOUT) bounds
    the rendezvous wait — widen it on loaded machines where sibling
    processes start staggered (CI under full-suite load), shrink it in
    fail-fast launchers."""
    if _state["initialized"]:
        return
    import os
    if (coordinator_address is None and num_processes is None
            and process_id is None):
        # env applies only as a COMPLETE set — a partial/leaked variable
        # (e.g. a stray MXTPU_NUM_PROCESSES) must not reroute a plain
        # single-host init() into a hard-crashing explicit rendezvous
        from .autotune.knobs import env_str
        env_vals = [env_str("MXTPU_COORDINATOR", ""),
                    env_str("MXTPU_NUM_PROCESSES", ""),
                    env_str("MXTPU_PROCESS_ID", "")]
        if all(env_vals):
            coordinator_address = env_vals[0]
            num_processes = int(env_vals[1])
            process_id = int(env_vals[2])
    if initialization_timeout is None:
        from .autotune.knobs import env_int
        initialization_timeout = env_int("MXTPU_INIT_TIMEOUT", None)
    timeout_kw = ({} if initialization_timeout is None
                  else {"initialization_timeout": int(initialization_timeout)})
    if coordinator_address is not None:
        # Cross-process computations on the CPU backend (loopback test
        # clusters, CPU fleets) need a collectives implementation; jax
        # does not default one on this version, and without it every
        # process_allgather dies with "Multiprocess computations aren't
        # implemented on the CPU backend". Must be set BEFORE the first
        # backend materialization; harmless for TPU (per-backend knob).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older/newer jax: keep going
            pass
    if coordinator_address is None and num_processes is None:
        # single-host or TPU-pod auto-discovery; jax treats absent args as
        # "use the runtime's own metadata" and works standalone too
        try:
            jax.distributed.initialize(**timeout_kw)
        except Exception as e:  # noqa: BLE001
            # plain single-process runs land here by design; on a real pod
            # a swallowed rendezvous error would strand the OTHER hosts in
            # initialize() — so always leave a trace of why we degraded
            import logging
            logging.getLogger(__name__).warning(
                "distributed.init auto-discovery failed (%r); continuing "
                "single-process — if this host is part of a pod, pass "
                "coordinator_address/num_processes/process_id explicitly",
                e)
            return
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            **timeout_kw)
    _state["initialized"] = True


def shutdown():
    if _state["initialized"]:
        # stop any dist_async server threads FIRST: a grpc poll in flight
        # while the coordination client is destroyed aborts the process
        # (C++ exception in a detached thread)
        from .kvstore import async_ps
        async_ps.stop_all()
        jax.distributed.shutdown()
        _state["initialized"] = False


def is_initialized() -> bool:
    return _state["initialized"]


def rank() -> int:
    """This process's index (parity: kv.rank / DMLC_RANK)."""
    return jax.process_index()


def num_workers() -> int:
    """Total processes (parity: kv.num_workers / DMLC_NUM_WORKER)."""
    return jax.process_count()


def local_devices():
    return jax.local_devices()


def global_devices():
    return jax.devices()


def global_mesh(axes=None):
    """Mesh over ALL hosts' devices (ICI inside a slice, DCN across) —
    the multi-host analogue of make_mesh. Put the fastest-varying axis
    (tp/sp) innermost so its collectives stay on ICI."""
    from .parallel import make_mesh
    return make_mesh(axes or {"dp": -1}, devices=jax.devices())


def barrier(name="mxtpu_barrier"):
    """Block until every process reaches this point (parity: kv.barrier
    across workers)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)
