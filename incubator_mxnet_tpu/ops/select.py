"""Kernel-selection layer: ONE place that decides, per call site, whether a
hand-written Pallas kernel (ops/pallas/) replaces the plain XLA
formulation of a hot op.

Every framework path that can hit a Pallas kernel — eager ops, the
`hybridize()` CachedOp trace, and the FusedTrainStep/TrainLoop whole-loop
trace — routes its decision through these predicates, so the
qualification rules (platform, dtype, shape alignment) live in one table
instead of being re-derived inline at each call site, and every decision
is observable:

* counters ``pallas.selected.<kernel>`` / ``pallas.rejected.<kernel>``
  (domain ``ops``) count decisions — once per TRACE (the CachedOp build
  traces a signature twice: the eval_shape structure probe, then the
  first jit dispatch), in eager mode once per call — directional
  indicators, not exact compile counts;
* :class:`capture` collects the decisions made while tracing a
  hybridized block, and `HybridBlock._build_cache` attaches them to the
  compile's flight-recorder record, so "which kernels did my model
  actually get" is answerable from a flight dump.

Escape hatches (checked by ``pallas.enabled()``):

* ``MXTPU_PALLAS=0``  — master off switch: plain XLA everywhere;
* ``MXTPU_PALLAS=force`` / ``MXTPU_FORCE_PALLAS=1`` — select kernels
  off-TPU too (interpret mode; what the CPU parity tests use);
* ``MXTPU_NO_PALLAS=1`` — legacy spelling of the off switch.

Selection table (docs/trainloop.md renders this):

===============  =========================================================
kernel           qualifies when
===============  =========================================================
flash_attention  pallas enabled; no additive mask; no attention-weight
                 dropout in training mode (the kernel keeps scores in
                 VMEM and applies no dropout)
layer_norm       pallas enabled; normalized axis is the LAST axis;
                 1-D gamma; on real TPU the width is 128-lane aligned
scale_shift_act  pallas enabled; channels-last input (the BatchNorm+ReLU
                 epilogue: one HBM pass for normalize+affine+act); on
                 real TPU channel count 128-lane aligned
conv_bn_relu     pallas enabled; inference-style BN (moving stats);
                 NHWC; 1x1/stride-1/no-pad conv runs as one fused
                 matmul+epilogue kernel, any other geometry keeps the
                 XLA conv and fuses only the epilogue
===============  =========================================================
"""
from __future__ import annotations

import threading

from .. import profiler as _prof

__all__ = ["flash_attention", "layer_norm", "scale_shift_act",
           "conv_bn_relu", "capture", "quiet", "selection_table"]

_tls = threading.local()


class capture:
    """Collect the selection decisions made on this thread inside the
    scope (used by HybridBlock._build_cache to attach the traced block's
    kernel choices to its compile record). Nestable; each scope sees only
    its own decisions."""

    def __enter__(self):
        self._prev = getattr(_tls, "log", None)
        _tls.log = []
        return _tls.log

    def __exit__(self, *exc):
        _tls.log = self._prev
        return False


class quiet:
    """Suppress the selection counters on this thread inside the scope.
    perfscope's cost capture re-lowers an already-traced program purely
    to read XLA's cost analysis; without this, every analyzed compile
    would double-count pallas.selected.*/rejected.*."""

    def __enter__(self):
        self._prev = getattr(_tls, "quiet", False)
        _tls.quiet = True
        return self

    def __exit__(self, *exc):
        _tls.quiet = self._prev
        return False


def _decide(kernel: str, ok: bool, reason: str) -> bool:
    if not getattr(_tls, "quiet", False):
        _prof.counter(
            ("pallas.selected." if ok else "pallas.rejected.") + kernel,
            "ops").increment()
    log = getattr(_tls, "log", None)
    if log is not None:
        log.append({"kernel": kernel, "selected": bool(ok),
                    "reason": reason})
    return ok


def _enabled():
    from . import pallas as _pallas
    return _pallas.enabled()


def _on_tpu():
    from . import pallas as _pallas
    return _pallas.is_tpu()


def flash_attention(mask, dropout_active: bool) -> bool:
    """Qualify the pallas flash-attention kernel for a multihead-attention
    call (O(L) memory, scores stay in VMEM)."""
    if not _enabled():
        return False
    if mask is not None:
        return _decide("flash_attention", False, "explicit mask")
    if dropout_active:
        return _decide("flash_attention", False, "attention dropout")
    return _decide("flash_attention", True, "ok")


def layer_norm(x, gamma, axis) -> bool:
    """Qualify the fused pallas layernorm (one HBM pass, f32 stats)."""
    if not _enabled():
        return False
    if axis not in (-1, x.ndim - 1) or gamma.ndim != 1:
        return _decide("layer_norm", False, "non-last-axis")
    if _on_tpu() and x.shape[-1] % 128:
        return _decide("layer_norm", False,
                       f"width {x.shape[-1]} not 128-lane aligned")
    return _decide("layer_norm", True, "ok")


# activations the fused epilogue kernel implements; anything else keeps
# the XLA chain (which supports the full _ACTIVATIONS table)
_EPILOGUE_ACTS = (None, "relu", "relu6")


def scale_shift_act(x, channel_axis, act=None) -> bool:
    """Qualify the fused scale+shift+activation epilogue (the
    BatchNorm[+ReLU] tail as one HBM pass) — channels-last layouts only;
    the per-channel scale/shift broadcast along the last axis maps onto
    lanes."""
    if not _enabled():
        return False
    if act not in _EPILOGUE_ACTS:
        return _decide("scale_shift_act", False, f"act {act!r}")
    if channel_axis % x.ndim != x.ndim - 1:
        return _decide("scale_shift_act", False, "channels not last")
    if _on_tpu() and x.shape[-1] % 128:
        return _decide("scale_shift_act", False,
                       f"channels {x.shape[-1]} not 128-lane aligned")
    return _decide("scale_shift_act", True, "ok")


def conv_bn_relu(x, weight, stride, pad, dilate, num_group,
                 layout, training: bool, act="relu") -> bool:
    """Qualify the fused conv+BN+relu path (inference hot path: the conv
    epilogue applies the folded BN scale/shift + relu in one pass; 1x1
    convs run entirely as a fused pallas matmul)."""
    if not _enabled():
        return False
    if act not in _EPILOGUE_ACTS:
        return _decide("conv_bn_relu", False, f"act {act!r}")
    if training:
        # training-mode BN normalizes with CURRENT batch stats of the conv
        # output — a second pass by construction; the scale_shift_act
        # epilogue covers that case separately
        return _decide("conv_bn_relu", False, "training-mode batch stats")
    if layout != "NHWC":
        return _decide("conv_bn_relu", False, f"layout {layout}")
    if num_group != 1:
        return _decide("conv_bn_relu", False, "grouped conv")
    if dilate is not None and any(d != 1 for d in dilate):
        return _decide("conv_bn_relu", False, "dilated conv")
    if _on_tpu() and (x.shape[-1] % 128 or weight.shape[-1] % 128):
        return _decide("conv_bn_relu", False,
                       "channels not 128-lane aligned")
    return _decide("conv_bn_relu", True, "ok")


def selection_table():
    """The qualification rules as data (docs/tests): kernel -> rule."""
    return {
        "flash_attention": "no mask, no attention-weight dropout",
        "layer_norm": "last-axis, 1-D gamma; TPU: width % 128 == 0",
        "scale_shift_act": "channels-last; TPU: channels % 128 == 0",
        "conv_bn_relu": ("inference BN, NHWC, ungrouped/undilated; "
                         "TPU: in/out channels % 128 == 0; 1x1/s1 fully "
                         "fused, other geometries fuse the epilogue"),
    }
