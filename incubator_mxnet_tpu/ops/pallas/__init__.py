"""Pallas TPU kernels for the hot ops (reference analogue: the hand-written
CUDA/cuDNN kernels under src/operator/contrib/ and src/operator/nn/).

On TPU these run as real Mosaic kernels; off-TPU they run with
``interpret=True`` (tests) or are bypassed in favor of the XLA path.

Before the first real-hardware dispatch the kernels must pass a one-time
on-device self-test (:func:`kernels_ok`): tiny-shape forward+backward of
both kernels checked against the plain XLA formulation. Any compile
failure, runtime error, or numeric mismatch permanently flips dispatch to
the XLA path for the process (with a warning) instead of letting a Mosaic
tiling bug take down a long training/bench run mid-compile.
"""
from .flash_attention import flash_attention
from .layer_norm import layer_norm
from .conv_bn_relu import conv_bn_relu, scale_shift_act, fold_bn

import os
import sys
import time
import warnings

import jax

__all__ = ["flash_attention", "layer_norm", "conv_bn_relu",
           "scale_shift_act", "fold_bn", "enabled", "kernels_ok",
           "is_tpu"]

# tri-state: None = not yet tested, True/False = verdict for this process
_KERNELS_OK = None

# exception types the self-test must NOT swallow (external watchdogs:
# bench.py registers its SIGALRM deadline so a hang is reported as a
# timeout, not misdiagnosed as a kernel numerics failure)
_SELFTEST_PASSTHROUGH = ()


def register_selftest_passthrough(*exc_types):
    """Let callers' deadline exceptions propagate out of the self-test."""
    global _SELFTEST_PASSTHROUGH
    _SELFTEST_PASSTHROUGH = _SELFTEST_PASSTHROUGH + tuple(exc_types)


def enabled() -> bool:
    """Use pallas kernels for framework ops? On by default on TPU (gated by
    the one-time on-device self-test). MXTPU_PALLAS is the master switch:
    ``0`` forces the plain XLA path everywhere (the escape hatch);
    ``1`` is explicit-on (TPU keeps the self-test gate, off-TPU runs
    interpret-mode kernels); ``force`` selects kernels everywhere with
    no self-test gate (what the CPU parity tests use). MXTPU_NO_PALLAS=1
    / MXTPU_FORCE_PALLAS=1 are the legacy spellings and keep working.

    The three spellings resolve through the ONE knob home
    (``autotune.knobs.resolve("pallas")``, same off > force > on > auto
    order this function always had) — which also gives this switch the
    cached-tuning-winner layer: before, a ``pallas`` winner installed by
    ``MXTPU_AUTOTUNE=1`` configured every knob EXCEPT this one, because
    this function read the raw env below the cache. Per-call-site
    qualification (shape/dtype/layout) lives in ops/select.py on top of
    this switch."""
    from ...autotune import knobs as _knobs
    mode = _knobs.resolve("pallas")[0]
    if mode == "off":
        return False
    if mode == "force":
        return True
    if mode == "on":
        # explicit on: TPU keeps the self-test gate; off-TPU this means
        # interpret-mode kernels (the MXTPU_*=1 spelling must not no-op)
        return kernels_ok() if is_tpu() else True
    return is_tpu() and kernels_ok()          # auto


def is_tpu() -> bool:
    """True when the attached device is a TPU, however the platform
    registers itself — the canonical 'tpu' backend OR a plugin name (the
    axon relay reports platform 'axon' with TPU device_kind). The single
    definition of "on TPU" for kernel dispatch, interpret-mode selection,
    and runtime feature flags."""
    if jax.default_backend() == "tpu":
        return True
    try:
        return any("tpu" in d.device_kind.lower() for d in jax.devices())
    except Exception:  # noqa: BLE001  (no backend reachable)
        return False


def kernels_ok() -> bool:
    """One-time on-device validation of the Mosaic kernels.

    The pallas kernels are numerically verified in interpret mode by the
    test suite, but Mosaic lowering on real hardware has failure modes
    interpret mode can't see (tiling/layout constraints, VMEM limits).
    First call on a TPU runs both kernels forward+backward on tiny shapes
    and compares against the XLA formulation; any exception or mismatch
    disables the pallas fast path for the rest of the process and warns,
    so a kernel bug degrades perf instead of crashing the run.

    Off-TPU this returns True without running anything (interpret mode is
    covered by tests/test_pallas.py). MXTPU_PALLAS_SELFTEST=0 skips the
    check (trust the kernels; saves two tiny compiles at startup).
    """
    global _KERNELS_OK
    if _KERNELS_OK is None:
        from ...autotune.knobs import env_flag
        skip = not env_flag("MXTPU_PALLAS_SELFTEST", True)
        if skip or not is_tpu():
            _KERNELS_OK = True
        else:
            _KERNELS_OK = _selftest()
    return _KERNELS_OK


def _selftest() -> bool:
    import numpy as np
    import jax.numpy as jnp

    t0 = time.time()
    try:
        rng = np.random.RandomState(0)

        # -- fused layer norm, fwd + bwd ---------------------------------
        x = jnp.asarray(rng.randn(16, 256).astype(np.float32))
        g = jnp.asarray(rng.rand(256).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(256).astype(np.float32))

        def ln_ref(x, g, b):
            m = jnp.mean(x, -1, keepdims=True)
            v = jnp.var(x, -1, keepdims=True)
            return (x - m) * jax.lax.rsqrt(v + 1e-5) * g + b

        def check(fn_got, fn_ref, args, what, atol, grad_names):
            got, vjp_g = jax.vjp(fn_got, *args)
            ref, vjp_r = jax.vjp(fn_ref, *args)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       atol=atol, rtol=atol,
                                       err_msg=f"{what} forward")
            ct = jnp.ones_like(ref)
            for gg, gr, nm in zip(vjp_g(ct), vjp_r(ct), grad_names):
                np.testing.assert_allclose(np.asarray(gg), np.asarray(gr),
                                           atol=atol * 4, rtol=atol * 4,
                                           err_msg=f"{what} grad {nm}")

        check(lambda x, g, b: layer_norm(x, g, b, 1e-5), ln_ref,
              (x, g, b), "layer_norm", 2e-3, ("x", "gamma", "beta"))

        # -- flash attention, fwd + bwd ----------------------------------
        q = jnp.asarray(rng.randn(1, 2, 256, 64).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(1, 2, 256, 64).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(1, 2, 256, 64).astype(np.float32))

        def attn_ref(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (q.shape[-1] ** 0.5)
            return jnp.einsum("bhqk,bhkd->bhqd",
                              jax.nn.softmax(s, axis=-1), v)

        check(lambda q, k, v: flash_attention(q, k, v), attn_ref,
              (q, k, v), "flash_attention", 5e-3, ("q", "k", "v"))
        check(lambda q, k, v: flash_attention(q, k, v, causal=True),
              lambda q, k, v: _causal_ref(q, k, v),
              (q, k, v), "flash_attention(causal)", 5e-3, ("q", "k", "v"))

        print(f"pallas: on-device kernel self-test PASSED "
              f"({time.time() - t0:.1f}s)", file=sys.stderr, flush=True)
        return True
    except Exception as e:  # noqa: BLE001 — any failure means fall back
        if isinstance(e, _SELFTEST_PASSTHROUGH):
            raise
        warnings.warn(
            f"pallas kernels failed the on-device self-test after "
            f"{time.time() - t0:.1f}s — falling back to the XLA path for "
            f"this process ({type(e).__name__}: {str(e)[:300]})",
            RuntimeWarning, stacklevel=2)
        return False


def _causal_ref(q, k, v):
    import jax.numpy as jnp
    lq, lk = q.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (q.shape[-1] ** 0.5)
    tri = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
    s = jnp.where(tri, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


def _reset_selftest_for_tests():
    """Test hook: clear the cached self-test verdict."""
    global _KERNELS_OK
    _KERNELS_OK = None
