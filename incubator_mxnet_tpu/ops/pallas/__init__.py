"""Pallas TPU kernels for the hot ops (reference analogue: the hand-written
CUDA/cuDNN kernels under src/operator/contrib/ and src/operator/nn/).

On TPU these run as real Mosaic kernels; off-TPU they run with
``interpret=True`` (tests) or are bypassed in favor of the XLA path.
"""
from .flash_attention import flash_attention
from .layer_norm import layer_norm

import os

import jax

__all__ = ["flash_attention", "layer_norm", "enabled"]


def enabled() -> bool:
    """Use pallas kernels for framework ops? On by default on TPU; set
    MXTPU_FORCE_PALLAS=1 to exercise interpret-mode kernels off-TPU, or
    MXTPU_NO_PALLAS=1 to force the plain XLA path everywhere."""
    def _truthy(name):
        return os.environ.get(name, "").strip().lower() not in ("", "0", "false")

    if _truthy("MXTPU_NO_PALLAS"):
        return False
    if _truthy("MXTPU_FORCE_PALLAS"):
        return True
    return is_tpu()


def is_tpu() -> bool:
    """True when the attached device is a TPU, however the platform
    registers itself — the canonical 'tpu' backend OR a plugin name (the
    axon relay reports platform 'axon' with TPU device_kind). The single
    definition of "on TPU" for kernel dispatch, interpret-mode selection,
    and runtime feature flags."""
    if jax.default_backend() == "tpu":
        return True
    try:
        return any("tpu" in d.device_kind.lower() for d in jax.devices())
    except Exception:  # noqa: BLE001  (no backend reachable)
        return False
