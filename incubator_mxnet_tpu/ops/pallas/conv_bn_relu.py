"""Fused conv+BN+relu pallas kernels (reference analogue: the cuDNN
fused ConvBiasActivation / CUDNN_BATCHNORM_OPS paths the MXNet fork
leaned on for ResNet throughput).

Two kernels:

* :func:`scale_shift_act` — the BatchNorm tail ``act(x * scale + shift)``
  as ONE HBM pass (per-channel scale/shift broadcast along lanes). This
  is what training-mode BatchNormReLU fuses through after the batch-stat
  reduction, and what the general-geometry conv path uses as its
  epilogue.
* :func:`conv_bn_relu` — inference-style conv+BN+act. A 1x1/stride-1/
  no-pad NHWC conv IS a matmul over flattened pixels, so it runs as a
  single blocked pallas matmul whose final k-block applies the folded BN
  scale/shift and the activation before the one output write (the conv
  output never round-trips HBM unfused). Any other geometry keeps XLA's
  conv (MXU-tuned) and fuses only the epilogue.

Backward: scale_shift_act has a cheap closed-form VJP (the pre-activation
recompute is elementwise). conv_bn_relu's VJP re-derives through the XLA
reference formulation (one extra forward — remat-style; the fused path
targets inference/serving where no backward runs).

Off-TPU the kernels run with ``interpret=True`` (parity tests); shapes
are padded to tile boundaries and sliced back.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["scale_shift_act", "conv_bn_relu", "fold_bn"]


def _vspec(shape, index_map):
    if _VMEM is None:
        return pl.BlockSpec(shape, index_map)
    return pl.BlockSpec(shape, index_map, memory_space=_VMEM)


def _apply_act(y, act):
    if act is None:
        return y
    if act == "relu":
        return jnp.maximum(y, 0.0)
    if act == "relu6":
        return jnp.clip(y, 0.0, 6.0)
    raise ValueError(f"scale_shift_act: unsupported act {act!r} "
                     "(relu, relu6 or None)")


# ---------------------------------------------------------------------------
# fused scale+shift+activation epilogue
# ---------------------------------------------------------------------------

def _ssa_kernel(x_ref, s_ref, b_ref, o_ref, *, act):
    y = x_ref[:].astype(jnp.float32) * s_ref[:] + b_ref[:]
    o_ref[:] = _apply_act(y, act).astype(o_ref.dtype)


def _ssa_fwd_impl(x2, scale, shift, act, interpret, block_r):
    rows, d = x2.shape
    s2 = scale.reshape(1, d).astype(jnp.float32)
    b2 = shift.reshape(1, d).astype(jnp.float32)
    return pl.pallas_call(
        functools.partial(_ssa_kernel, act=act),
        grid=(rows // block_r,),
        in_specs=[_vspec((block_r, d), lambda i: (i, 0)),
                  _vspec((1, d), lambda i: (0, 0)),
                  _vspec((1, d), lambda i: (0, 0))],
        out_specs=_vspec((block_r, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x2.dtype),
        interpret=interpret,
    )(x2, s2, b2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ssa(x2, scale, shift, act, interpret, block_r):
    return _ssa_fwd_impl(x2, scale, shift, act, interpret, block_r)


def _ssa_fwd(x2, scale, shift, act, interpret, block_r):
    return (_ssa_fwd_impl(x2, scale, shift, act, interpret, block_r),
            (x2, scale, shift))


def _ssa_bwd(act, interpret, block_r, res, dy):
    x2, scale, shift = res
    xf = x2.astype(jnp.float32)
    g = dy.astype(jnp.float32)
    if act is not None:
        # recompute the pre-activation (elementwise — cheap) for the mask
        pre = xf * scale.astype(jnp.float32) + shift.astype(jnp.float32)
        if act == "relu":
            mask = pre > 0
        else:                       # relu6
            mask = (pre > 0) & (pre < 6.0)
        g = jnp.where(mask, g, 0.0)
    dx = (g * scale.astype(jnp.float32)).astype(x2.dtype)
    dscale = jnp.sum(g * xf, axis=0).astype(scale.dtype)
    dshift = jnp.sum(g, axis=0).astype(shift.dtype)
    return dx, dscale, dshift


_ssa.defvjp(_ssa_fwd, _ssa_bwd)


def scale_shift_act(x, scale, shift, act="relu", block_rows=256,
                    interpret=None):
    """``act(x * scale + shift)`` over the LAST axis of x in one HBM pass;
    scale/shift shape (C,). Differentiable (closed-form VJP)."""
    if interpret is None:
        from . import is_tpu
        interpret = not is_tpu()
    d = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, d)
    rp = (rows + 7) // 8 * 8
    if rp != rows:
        x2 = jnp.pad(x2, ((0, rp - rows), (0, 0)))
    cap = max(8, (1 << 19) // d // 8 * 8)
    block_r = min(block_rows, cap, rp) // 8 * 8
    while block_r > 8 and rp % block_r:
        block_r -= 8
    out = _ssa(x2, scale, shift, act, bool(interpret), int(block_r))
    return out[:rows].reshape(x.shape)


# ---------------------------------------------------------------------------
# fused 1x1-conv (matmul) + BN epilogue
# ---------------------------------------------------------------------------

def _mm_kernel(x_ref, w_ref, s_ref, b_ref, o_ref, acc_ref, *, nk, act):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(x_ref[:].astype(jnp.float32),
                          w_ref[:].astype(jnp.float32),
                          preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == nk - 1)
    def _epilogue():
        y = acc_ref[:] * s_ref[:] + b_ref[:]
        o_ref[:] = _apply_act(y, act).astype(o_ref.dtype)


def _pick_block(n, pref=128, align=8):
    if n % pref == 0:
        return pref
    b = min(n, pref) // align * align
    while b > align and n % b:
        b -= align
    return b if b and n % b == 0 else n


def _mm_epilogue(x2, w2, scale, shift, act, interpret):
    """(M, K) @ (K, N) with fused per-column scale/shift/act on the final
    accumulation block. f32 accumulation in VMEM scratch. The row block
    is always sublane-aligned (multiple of 8; rows are padded to it) —
    M itself never constrains alignment. Channel dims are the caller's
    contract: on real TPU the selection layer admits only 128-lane-
    aligned Cin/Cout."""
    m, k = x2.shape
    n = w2.shape[1]
    bm = min(128, (max(m, 1) + 7) // 8 * 8)     # 8-aligned, rows padded
    mp = (m + bm - 1) // bm * bm
    if mp != m:
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    bn = _pick_block(n, 128)
    bk = _pick_block(k, 128)
    nk = k // bk
    s2 = scale.reshape(1, n).astype(jnp.float32)
    b2 = shift.reshape(1, n).astype(jnp.float32)
    if pltpu is None:  # pragma: no cover — no pallas TPU support built in
        raise NotImplementedError("pallas TPU backend unavailable")
    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]
    out = pl.pallas_call(
        functools.partial(_mm_kernel, nk=nk, act=act),
        grid=(mp // bm, n // bn, nk),
        in_specs=[_vspec((bm, bk), lambda i, j, kk: (i, kk)),
                  _vspec((bk, bn), lambda i, j, kk: (kk, j)),
                  _vspec((1, bn), lambda i, j, kk: (0, j)),
                  _vspec((1, bn), lambda i, j, kk: (0, j))],
        out_specs=_vspec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, n), x2.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(x2, w2, s2, b2)
    return out[:m]


# ---------------------------------------------------------------------------
# conv + BN + act
# ---------------------------------------------------------------------------

def fold_bn(gamma, beta, mean, var, eps):
    """BN(moving stats) as an affine epilogue: scale = gamma*rsqrt(var+eps),
    shift = beta - mean*scale (f32 — matches the XLA path's f32 stats)."""
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    return scale, shift


def _conv_ref(x, w, scale, shift, stride, pad, act):
    """XLA reference formulation — the VJP re-derivation target and the
    parity oracle for tests."""
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y = y.astype(jnp.float32) * scale + shift
    return _apply_act(y, act).astype(x.dtype)


def _cbr_fwd_impl(x, w, scale, shift, stride, pad, act, interpret):
    kh, kw = w.shape[0], w.shape[1]
    one_by_one = (kh == 1 and kw == 1 and tuple(stride) == (1, 1)
                  and tuple(pad) == (0, 0))
    if one_by_one:
        n, h, wd, cin = x.shape
        cout = w.shape[-1]
        x2 = x.reshape(n * h * wd, cin)
        w2 = w.reshape(cin, cout)
        out = _mm_epilogue(x2, w2, scale, shift, act, interpret)
        return out.reshape(n, h, wd, cout)
    # general geometry: XLA's conv (MXU-tuned), pallas fuses the epilogue
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return scale_shift_act(y, scale, shift, act=act, interpret=interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _cbr(x, w, scale, shift, stride, pad, act, interpret):
    return _cbr_fwd_impl(x, w, scale, shift, stride, pad, act, interpret)


def _cbr_fwd(x, w, scale, shift, stride, pad, act, interpret):
    return (_cbr_fwd_impl(x, w, scale, shift, stride, pad, act, interpret),
            (x, w, scale, shift))


def _cbr_bwd(stride, pad, act, interpret, res, dy):
    x, w, scale, shift = res
    _, vjp = jax.vjp(
        lambda xx, ww, ss, bb: _conv_ref(xx, ww, ss, bb, stride, pad, act),
        x, w, scale, shift)
    return vjp(dy)


_cbr.defvjp(_cbr_fwd, _cbr_bwd)


def conv_bn_relu(x, weight, gamma, beta, mean, var, *, eps=1e-5,
                 stride=(1, 1), pad=(0, 0), act="relu", interpret=None):
    """Fused NHWC conv + BatchNorm(moving stats) + activation.

    x (N,H,W,Cin); weight HWIO. 1x1/stride-1/no-pad runs as ONE pallas
    matmul+epilogue kernel; other geometries run XLA's conv with the
    pallas scale/shift/act epilogue. Numerics match
    ``act(bn(conv(x)))`` computed the XLA way to f32 accumulation
    tolerance (the epilogue applies BN AFTER the conv sum, same order as
    the unfused path — weights are not pre-folded)."""
    if interpret is None:
        from . import is_tpu
        interpret = not is_tpu()
    scale, shift = fold_bn(gamma, beta, mean, var, eps)
    return _cbr(x, weight, scale, shift, tuple(stride), tuple(pad), act,
                bool(interpret))
