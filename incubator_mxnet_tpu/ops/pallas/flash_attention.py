"""Flash attention pallas kernels (TPU fast path for multihead attention).

Replaces the reference's interleaved_matmul_selfatt_* / cuDNN attention
(src/operator/contrib/transformer.cc) with a FlashAttention-2 style tiled
kernel: online softmax over K/V blocks, O(L) memory, scores never hit HBM.
Forward saves the per-row logsumexp; backward recomputes scores blockwise in
two kernels (dq; dk/dv).

Layout notes (TPU tiling wants the last two block dims ∈ {(8k, 128m), full}):
- q/k/v/o are (batch*heads, seq, head_dim) with head_dim padded to 128 lanes;
- lse/delta ride as (batch*heads, 1, seq) with full-seq blocks, written via
  dynamic slices (the (1, block_q) layout is not tileable);
- the online-softmax m/l scratch is (block_q, 128) lanes-broadcast.

Off-TPU the same kernels run with interpret=True (tests/conftest sets CPU).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG = -1e30
_LANES = 128


def _ru(x, m):
    return (x + m - 1) // m * m


def _vspec(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                scale, causal, block_q, block_k, kv_len, num_kv, offset):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = ((ki * block_k < (qi + 1) * block_q + offset) if causal
           else (ki >= 0))

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row + offset >= col)
        s = jnp.where(mask, s, _NEG)

        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)

    if causal:
        last = jnp.clip(((qi + 1) * block_q - 1 + offset) // block_k,
                        0, num_kv - 1)
    else:
        last = num_kv - 1

    @pl.when(ki == last)
    def _():
        l = l_scr[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse = (m_scr[:, 0:1] + jnp.log(l)).reshape(1, block_q)
        lse_ref[0, 0:1, pl.ds(pl.multiple_of(qi * block_q, block_q),
                              block_q)] = lse


def _fwd(q, k, v, cfg):
    scale, causal, bq, bk, kv_len, offset, interpret = cfg
    bh, lq, d = q.shape
    lk = k.shape[1]
    num_q, num_kv = lq // bq, lk // bk
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=bq, block_k=bk, kv_len=kv_len,
                             num_kv=num_kv, offset=offset)
    return pl.pallas_call(
        kern,
        grid=(bh, num_q, num_kv),
        in_specs=[_vspec((1, bq, d), lambda b, i, j: (b, i, 0)),
                  _vspec((1, bk, d), lambda b, i, j: (b, j, 0)),
                  _vspec((1, bk, d), lambda b, i, j: (b, j, 0))],
        out_specs=[_vspec((1, bq, d), lambda b, i, j: (b, i, 0)),
                   _vspec((1, 1, lq), lambda b, i, j: (b, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, 1, lq), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _row(ref, start, size):
    """Read (1, size) slice of a (1, 1, L) block as (size, 1)."""
    return ref[0, 0:1, pl.ds(pl.multiple_of(start, size),
                             size)].reshape(size, 1)


def _masked_p(q, k, lse_col, scale, causal, qi, ki, block_q, block_k, kv_len,
              offset):
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse_col)
    col = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = col < kv_len
    if causal:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(mask, row + offset >= col)
    return jnp.where(mask, p, 0.0)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, dq_scr, *,
               scale, causal, block_q, block_k, kv_len, num_kv, offset):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = ((ki * block_k < (qi + 1) * block_q + offset) if causal
           else (ki >= 0))

    @pl.when(run)
    def _():
        k, v, do = k_ref[0], v_ref[0], do_ref[0]
        lse = _row(lse_ref, qi * block_q, block_q)
        dl = _row(dl_ref, qi * block_q, block_q)
        p = _masked_p(q_ref[0], k, lse, scale, causal, qi, ki,
                      block_q, block_k, kv_len, offset)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dl) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    if causal:
        last = jnp.clip(((qi + 1) * block_q - 1 + offset) // block_k,
                        0, num_kv - 1)
    else:
        last = num_kv - 1

    @pl.when(ki == last)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, scale, causal, block_q, block_k, kv_len,
                num_q, offset):
    ki, qi = pl.program_id(1), pl.program_id(2)
    if causal:
        first = jnp.clip((ki * block_k - offset) // block_q, 0, num_q - 1)
    else:
        first = 0

    @pl.when(qi == first)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = ((ki * block_k < (qi + 1) * block_q + offset) if causal
           else (qi >= 0))

    @pl.when(run)
    def _():
        q, v, do = q_ref[0], v_ref[0], do_ref[0]
        lse = _row(lse_ref, qi * block_q, block_q)
        dl = _row(dl_ref, qi * block_q, block_q)
        p = _masked_p(q, k_ref[0], lse, scale, causal, qi, ki,
                      block_q, block_k, kv_len, offset)
        pt = p.astype(do.dtype)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - dl) * scale).astype(q.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(cfg, res, dout):
    scale, causal, bq, bk, kv_len, offset, interpret = cfg
    q, k, v, out, lse = res
    do, _ = dout
    bh, lq, d = q.shape
    lk = k.shape[1]
    num_q, num_kv = lq // bq, lk // bk
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, lq)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, block_q=bq,
                          block_k=bk, kv_len=kv_len, num_kv=num_kv,
                          offset=offset),
        grid=(bh, num_q, num_kv),
        in_specs=[_vspec((1, bq, d), lambda b, i, j: (b, i, 0)),
                  _vspec((1, bk, d), lambda b, i, j: (b, j, 0)),
                  _vspec((1, bk, d), lambda b, i, j: (b, j, 0)),
                  _vspec((1, bq, d), lambda b, i, j: (b, i, 0)),
                  _vspec((1, 1, lq), lambda b, i, j: (b, 0, 0)),
                  _vspec((1, 1, lq), lambda b, i, j: (b, 0, 0))],
        out_specs=_vspec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, block_q=bq,
                          block_k=bk, kv_len=kv_len, num_q=num_q,
                          offset=offset),
        grid=(bh, num_kv, num_q),
        in_specs=[_vspec((1, bq, d), lambda b, j, i: (b, i, 0)),
                  _vspec((1, bk, d), lambda b, j, i: (b, j, 0)),
                  _vspec((1, bk, d), lambda b, j, i: (b, j, 0)),
                  _vspec((1, bq, d), lambda b, j, i: (b, i, 0)),
                  _vspec((1, 1, lq), lambda b, j, i: (b, 0, 0)),
                  _vspec((1, 1, lq), lambda b, j, i: (b, 0, 0))],
        out_specs=[_vspec((1, bk, d), lambda b, j, i: (b, j, 0)),
                   _vspec((1, bk, d), lambda b, j, i: (b, j, 0))],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, cfg):
    out, lse = _fwd(q, k, v, cfg)
    return out, lse


def _flash_fwd(q, k, v, cfg):
    out, lse = _fwd(q, k, v, cfg)
    return (out, lse), (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def flash_attention(q, k, v, *, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=None):
    """Tiled attention on (B, H, L, D) tensors; returns (B, H, Lq, D).

    Differentiable (custom VJP with blockwise recompute). Padding of L and D
    to block multiples is handled here; padded KV positions are masked inside
    the kernel, padded Q rows are sliced off (their grads vanish since the
    incoming cotangent there is zero).
    """
    if interpret is None:
        from . import is_tpu
        interpret = not is_tpu()
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)

    if interpret:
        block_q = min(block_q, _ru(lq, 16))
        block_k = min(block_k, _ru(lk, 16))
    else:
        # Mosaic needs the lse dynamic-slice lane index provably 128-aligned,
        # so q/k blocks are 128-multiples on hardware (lq/lk get padded up).
        block_q = _ru(min(block_q, _ru(lq, _LANES)), _LANES)
        block_k = _ru(min(block_k, _ru(lk, _LANES)), _LANES)
    lqp, lkp = _ru(lq, block_q), _ru(lk, block_k)
    dp = d if interpret else _ru(d, _LANES)

    def prep(x, lp):
        x = x.reshape(b * h, x.shape[2], d)
        return jnp.pad(x, ((0, 0), (0, lp - x.shape[1]), (0, dp - d)))

    q3, k3, v3 = prep(q, lqp), prep(k, lkp), prep(v, lkp)
    if causal and lq > lk:
        raise ValueError("flash_attention: causal with more queries than keys "
                         "is undefined (use an explicit mask)")
    cfg = (scale, bool(causal), block_q, block_k, lk, lk - lq,
           bool(interpret))
    out, _ = _flash(q3, k3, v3, cfg)
    return out[:, :lq, :d].reshape(b, h, lq, d)
