"""Fused LayerNorm pallas kernel (one HBM pass: stats + normalize + affine).

The XLA path (_raw.layer_norm) already fuses decently; this kernel guarantees
the single-pass schedule on TPU and keeps the reduction in fp32 regardless of
input dtype. Backward uses the closed-form layernorm VJP in XLA (cheap, and
XLA fuses it into the surrounding backward).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

__all__ = ["layer_norm"]


def _vspec(shape, index_map):
    if _VMEM is None:
        return pl.BlockSpec(shape, index_map)
    return pl.BlockSpec(shape, index_map, memory_space=_VMEM)


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * g_ref[:] + b_ref[:]).astype(o_ref.dtype)


def _ln_fwd_impl(x2, gamma, beta, eps, interpret, block_r):
    rows, d = x2.shape
    g2 = gamma.reshape(1, d).astype(jnp.float32)
    b2 = beta.reshape(1, d).astype(jnp.float32)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(rows // block_r,),
        in_specs=[_vspec((block_r, d), lambda i: (i, 0)),
                  _vspec((1, d), lambda i: (0, 0)),
                  _vspec((1, d), lambda i: (0, 0))],
        out_specs=_vspec((block_r, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x2.dtype),
        interpret=interpret,
    )(x2, g2, b2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x2, gamma, beta, eps, interpret, block_r):
    return _ln_fwd_impl(x2, gamma, beta, eps, interpret, block_r)


def _ln_fwd(x2, gamma, beta, eps, interpret, block_r):
    return _ln_fwd_impl(x2, gamma, beta, eps, interpret, block_r), (x2, gamma)


def _ln_bwd(eps, interpret, block_r, res, dy):
    x2, gamma = res
    x = x2.astype(jnp.float32)
    g = dy.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    dgamma = jnp.sum(g * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(g, axis=0).astype(gamma.dtype)
    gg = g * gamma.astype(jnp.float32)
    n = x.shape[-1]
    dx = (gg - jnp.mean(gg, axis=-1, keepdims=True)
          - xhat * jnp.mean(gg * xhat, axis=-1, keepdims=True)) * rstd
    return dx.astype(x2.dtype), dgamma, dbeta


_ln.defvjp(_ln_fwd, _ln_bwd)


def layer_norm(x, gamma, beta, eps=1e-5, block_rows=256, interpret=None):
    """Fused layernorm over the LAST axis of x; gamma/beta shape (D,)."""
    if interpret is None:
        from . import is_tpu
        interpret = not is_tpu()
    d = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, d)
    # TPU tiling wants sublane blocks of 8; pad the row dim rather than
    # blowing VMEM with one full-array block (padded rows are sliced off).
    rp = (rows + 7) // 8 * 8
    if rp != rows:
        x2 = jnp.pad(x2, ((0, rp - rows), (0, 0)))
    # keep blocks well under VMEM (in+out, double-buffered): ~512k f32 = 2MB
    cap = max(8, (1 << 19) // d // 8 * 8)
    block_r = min(block_rows, cap, rp) // 8 * 8
    while block_r > 8 and rp % block_r:
        block_r -= 8
    out = _ln(x2, gamma, beta, float(eps), bool(interpret), int(block_r))
    return out[:rows].reshape(x.shape)
