"""Pure-jax NN kernels (reference parity: src/operator/nn/*).

These are the XLA-native replacements for the reference's mshadow/cuDNN
kernels: conv/pool lower to lax convolution/reduce_window (MXU/VPU on TPU),
norms are fused elementwise chains XLA consolidates into single kernels.
All functions are pure (state in, state out) so they compose with jit/grad/
shard_map. Layouts: MXNet's default NCHW is supported everywhere, NHWC is
offered because it is the faster layout on TPU (channels-last feeds the MXU
without relayout); model zoo defaults to NHWC on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------------------
# dense / linear
# ---------------------------------------------------------------------------

def dense(x, weight, bias=None, flatten=True):
    """FullyConnected (reference src/operator/nn/fully_connected.cc):
    weight layout (out_units, in_units); flatten=True collapses trailing dims."""
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------

def _conv_dn(ndim, layout):
    if layout == "NCHW" or (layout is None and ndim == 4):
        return ("NCHW", "OIHW", "NCHW")
    if layout == "NHWC":
        return ("NHWC", "HWIO", "NHWC")
    if layout == "NCW" or (layout is None and ndim == 3):
        return ("NCH", "OIH", "NCH")  # 1D as H
    if layout == "NWC":
        return ("NHC", "HIO", "NHC")
    if layout == "NCDHW" or (layout is None and ndim == 5):
        return ("NCDHW", "OIDHW", "NCDHW")
    if layout == "NDHWC":
        return ("NDHWC", "DHWIO", "NDHWC")
    raise ValueError(f"unsupported conv layout {layout}")


def conv(x, weight, bias=None, kernel=None, stride=None, pad=None, dilate=None,
         num_group=1, layout="NCHW"):
    """Convolution (reference src/operator/nn/convolution.cc). `weight` is
    OIHW-ordered for NCHW (out, in/group, *k); HWIO for NHWC."""
    nsp = x.ndim - 2
    stride = stride or (1,) * nsp
    pad = pad or (0,) * nsp
    dilate = dilate or (1,) * nsp
    dn = _conv_dn(x.ndim, layout)
    y = lax.conv_general_dilated(
        x, weight,
        window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        rhs_dilation=tuple(dilate),
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if bias is not None:
        if layout.endswith("C") and layout[0] == "N" and "C" != layout[1]:
            y = y + bias  # channels-last broadcasts directly
        else:
            y = y + bias.reshape((1, -1) + (1,) * nsp)
    return y


def conv_transpose(x, weight, bias=None, stride=None, pad=None, dilate=None,
                   adj=None, num_group=1, layout="NCHW"):
    """Deconvolution (reference src/operator/nn/deconvolution.cc): gradient of
    conv w.r.t. input, implemented as lax.conv_transpose with IOHW weights."""
    nsp = x.ndim - 2
    stride = tuple(stride or (1,) * nsp)
    pad = tuple(pad or (0,) * nsp)
    dilate = tuple(dilate or (1,) * nsp)
    adj = tuple(adj or (0,) * nsp)
    if layout == "NCHW":
        dn = ("NCHW", "IOHW", "NCHW")
        kshape = weight.shape[2:]
    elif layout == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        kshape = weight.shape[:-2]
    else:
        raise ValueError(f"unsupported deconv layout {layout}")
    # MXNet output size: (in-1)*s - 2p + dilate*(k-1) + 1 + adj
    pads = []
    for i in range(nsp):
        k_eff = dilate[i] * (kshape[i] - 1) + 1
        lo = k_eff - 1 - pad[i]
        hi = k_eff - 1 - pad[i] + adj[i]
        pads.append((lo, hi))
    if num_group != 1:
        xs = jnp.split(x, num_group, axis=1 if layout == "NCHW" else -1)
        ws = jnp.split(weight, num_group, axis=0 if layout == "NCHW" else -2)
        ys = [lax.conv_transpose(xi, wi, stride, pads, rhs_dilation=dilate,
                                 dimension_numbers=dn)
              for xi, wi in zip(xs, ws)]
        y = jnp.concatenate(ys, axis=1 if layout == "NCHW" else -1)
    else:
        y = lax.conv_transpose(x, weight, stride, pads, rhs_dilation=dilate,
                               dimension_numbers=dn)
    if bias is not None:
        y = y + (bias if layout == "NHWC" else bias.reshape((1, -1) + (1,) * nsp))
    return y


def grid_generator(data, transform_type="affine", target_shape=None):
    """GridGenerator (reference src/operator/grid_generator.cc): sampling
    grid in [-1,1] normalized coords, (N, 2, H, W) with channel 0 = x.
    affine: data (N,6) row-major 2x3; warp: data = flow (N,2,H,W) added to
    the identity grid in pixel units."""
    if transform_type == "affine":
        h, w = target_shape
        n = data.shape[0]
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        theta = data.reshape(n, 2, 3)
        out = theta @ base                                        # (N,2,HW)
        return out.reshape(n, 2, h, w)
    if transform_type == "warp":
        n, _, h, w = data.shape
        ys = jnp.arange(h, dtype=data.dtype)
        xs = jnp.arange(w, dtype=data.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        x = (data[:, 0] + gx) * (2.0 / jnp.maximum(w - 1, 1)) - 1.0
        y = (data[:, 1] + gy) * (2.0 / jnp.maximum(h - 1, 1)) - 1.0
        return jnp.stack([x, y], axis=1)
    raise ValueError(f"unknown transform_type {transform_type!r}")


def bilinear_sampler(data, grid):
    """BilinearSampler (reference src/operator/bilinear_sampler.cc): sample
    NCHW `data` at normalized grid (N,2,Ho,Wo); zero padding outside.
    One vectorized gather + 4-tap blend — XLA fuses it; no scalar loops."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0     # (N,Ho,Wo) in pixel coords
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def tap(yi, xi):
        inb = ((xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1))
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        # gather per batch: data (N,C,H,W) at (N,Ho,Wo) points
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)
        vals = jnp.take_along_axis(flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
        vals = vals.reshape(n, c, *xi.shape[1:])
        return vals * inb[:, None].astype(data.dtype)

    v00 = tap(y0, x0)
    v01 = tap(y0, x0 + 1)
    v10 = tap(y0 + 1, x0)
    v11 = tap(y0 + 1, x0 + 1)
    wx = wx[:, None].astype(data.dtype)
    wy = wy[:, None].astype(data.dtype)
    return ((1 - wy) * ((1 - wx) * v00 + wx * v01)
            + wy * ((1 - wx) * v10 + wx * v11))


def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Correlation (reference src/operator/correlation.cc, FlowNet):
    zero-centered displacement grid (radius max_displacement//stride2 in
    stride2 multiples), k x k patch sum normalized by k*k*C, centers
    cropped by border = max_displacement + (k-1)//2 from the pad_size-padded
    map, subsampled by stride1. The displacement loop is static, so it
    unrolls into one fused XLA computation (no dynamic shapes)."""
    import math
    n, c, h, w = data1.shape
    k = int(kernel_size)
    d = int(max_displacement)
    d2r = d // max(1, stride2)
    offsets = [stride2 * i for i in range(-d2r, d2r + 1)]
    border = d + (k - 1) // 2
    h2, w2 = h + 2 * pad_size, w + 2 * pad_size
    out_h = int(math.ceil((h2 - 2 * border) / float(stride1)))
    out_w = int(math.ceil((w2 - 2 * border) / float(stride1)))
    if out_h <= 0 or out_w <= 0:
        raise ValueError(
            f"correlation output would be empty: input {h}x{w}, pad "
            f"{pad_size}, border {border}")
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    # extra d margin on data2 so every shifted slice stays in bounds
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad_size + d, pad_size + d),
                         (pad_size + d, pad_size + d)))
    norm = float(k * k * c)
    outs = []
    for dy in offsets:
        for dx in offsets:
            shifted = jax.lax.dynamic_slice(
                p2, (0, 0, d + dy, d + dx), (n, c, h2, w2))
            prod = ((p1 * shifted) if is_multiply
                    else jnp.abs(p1 - shifted)).sum(axis=1)  # (N,H2,W2)
            if k > 1:
                prod = jax.lax.reduce_window(
                    prod, 0.0, jax.lax.add, (1, k, k), (1, 1, 1), "SAME")
            outs.append(prod / norm)
    out = jnp.stack(outs, axis=1)        # (N, D2, H2, W2)
    out = out[:, :, border:border + (out_h - 1) * stride1 + 1:stride1,
              border:border + (out_w - 1) * stride1 + 1:stride1]
    return out


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    """SequenceMask (reference src/operator/sequence_mask.cc): positions at
    or beyond each sequence's length (along time `axis`) become `value`."""
    if not use_sequence_length or sequence_length is None:
        return data
    t = data.shape[axis]
    steps = jnp.arange(t)
    ln = sequence_length.astype(jnp.int32)      # (N,)
    if axis == 0:
        mask = steps[:, None] < ln[None, :]     # (T, N)
    else:
        mask = steps[None, :] < ln[:, None]     # (N, T)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0):
    """SequenceLast: the last valid element along `axis` per sequence."""
    t = data.shape[axis]
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, t - 1, axis=axis)
    ln = jnp.clip(sequence_length.astype(jnp.int32) - 1, 0, t - 1)  # (N,)
    moved = jnp.moveaxis(data, axis, 0)          # (T, N, ...)
    idx = ln.reshape((1, -1) + (1,) * (moved.ndim - 2))
    idx = jnp.broadcast_to(idx, (1,) + moved.shape[1:])
    return jnp.take_along_axis(moved, idx, axis=0)[0]


def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0):
    """SequenceReverse: reverse the first len_n steps of each sequence,
    leaving padding in place."""
    t = data.shape[axis]
    moved = jnp.moveaxis(data, axis, 0)          # (T, N, ...)
    if not use_sequence_length or sequence_length is None:
        return jnp.moveaxis(moved[::-1], 0, axis)
    ln = sequence_length.astype(jnp.int32)       # (N,)
    steps = jnp.arange(t)[:, None]               # (T,1)
    src = jnp.where(steps < ln[None, :], ln[None, :] - 1 - steps, steps)
    src = src.reshape(src.shape + (1,) * (moved.ndim - 2))
    src = jnp.broadcast_to(src, moved.shape)
    out = jnp.take_along_axis(moved, src, axis=0)
    return jnp.moveaxis(out, 0, axis)


def bilinear_kernel_1d(k, dtype=jnp.float32):
    """The reference's bilinear deconv filter row (same formula as
    mx.init.Bilinear / src/operator/nn/upsampling-inl.h)."""
    import math
    f = math.ceil(k / 2.0)
    c = (2 * f - 1 - f % 2) / (2.0 * f)
    x = jnp.arange(k, dtype=dtype)
    return 1 - jnp.abs(x / f - c)


def upsampling(x, scale=2, sample_type="nearest", layout="NCHW"):
    """UpSampling (reference src/operator/nn/upsampling.cc). `nearest` is a
    repeat; `bilinear` is the reference's fixed-weight Deconvolution
    (kernel 2s-s%2, stride s, pad ceil((s-1)/2)) realised as ONE depthwise
    lhs-dilated conv — a single XLA conv the TPU tiles onto the MXU, no
    per-channel loop."""
    import math
    s = int(scale)
    if sample_type == "nearest":
        if layout == "NCHW":
            return jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
        return jnp.repeat(jnp.repeat(x, s, axis=1), s, axis=2)
    if sample_type != "bilinear":
        raise ValueError(f"unknown UpSampling sample_type {sample_type!r}")
    k = 2 * s - s % 2
    pad_deconv = int(math.ceil((s - 1) / 2.0))
    p = k - 1 - pad_deconv  # deconv pad → lhs-dilated conv pad
    w1 = bilinear_kernel_1d(k, x.dtype)
    w2 = jnp.outer(w1, w1)
    if layout == "NCHW":
        ch = x.shape[1]
        kernel = jnp.broadcast_to(w2, (ch, 1, k, k))
        dn = ("NCHW", "OIHW", "NCHW")
    elif layout == "NHWC":
        ch = x.shape[3]
        kernel = jnp.broadcast_to(w2[:, :, None, None], (k, k, 1, ch))
        dn = ("NHWC", "HWIO", "NHWC")
    else:
        raise ValueError(f"unsupported UpSampling layout {layout}")
    return lax.conv_general_dilated(
        x, kernel.astype(x.dtype), (1, 1), [(p, p), (p, p)],
        lhs_dilation=(s, s), feature_group_count=ch, dimension_numbers=dn)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _maxpool_ncs(x, kernel, stride, pad, hi_extra=None):
    """Max pool on (N, C, *spatial) via dilated patches (jit-differentiable)."""
    import numpy as _np
    nsp = x.ndim - 2
    hi_extra = hi_extra or [0] * nsp
    if any(pad) or any(hi_extra):
        neg = jnp.asarray(jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating)
                          else jnp.iinfo(x.dtype).min, x.dtype)
        pw = [(0, 0), (0, 0)] + [(p, p + h) for p, h in zip(pad, hi_extra)]
        x = jnp.pad(x, pw, constant_values=neg)
    patches = lax.conv_general_dilated_patches(x, tuple(kernel), tuple(stride), "VALID")
    c = x.shape[1]
    k = int(_np.prod(kernel))
    out_sp = patches.shape[2:]
    return patches.reshape((x.shape[0], c, k) + out_sp).max(axis=2)


def pooling(x, pool_type="max", kernel=(2, 2), stride=None, pad=None,
            global_pool=False, count_include_pad=True, layout="NCHW",
            ceil_mode=False):
    """Pooling (reference src/operator/nn/pooling.cc) via lax.reduce_window."""
    nsp = x.ndim - 2
    channels_last = layout.endswith("C") and len(layout) == x.ndim and layout[1] != "C"
    sp_axes = tuple(range(1, 1 + nsp)) if channels_last else tuple(range(2, 2 + nsp))
    if global_pool:
        if pool_type == "max":
            return jnp.max(x, axis=sp_axes, keepdims=True)
        if pool_type in ("avg", "sum"):
            r = jnp.sum(x, axis=sp_axes, keepdims=True)
            if pool_type == "avg":
                cnt = 1
                for a in sp_axes:
                    cnt *= x.shape[a]
                r = r / cnt
            return r
        raise ValueError(pool_type)
    stride = tuple(stride or kernel)
    pad = tuple(pad or (0,) * nsp)
    # ceil_mode: extend the high-side padding so the last partial window is
    # kept (MXNet ceil((in + 2p - k)/s) + 1 output size).
    hi_extra = [0] * nsp
    if ceil_mode:
        for i, a in enumerate(sp_axes):
            size = x.shape[a] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            if rem:
                hi_extra[i] = stride[i] - rem
    window = [1] * x.ndim
    strides = [1] * x.ndim
    pads = [(0, 0)] * x.ndim
    for i, a in enumerate(sp_axes):
        window[a] = kernel[i]
        strides[a] = stride[i]
        pads[a] = (pad[i], pad[i] + hi_extra[i])
    if pool_type == "max":
        # Patch-extraction + max: reduce_window(max) has no linearization
        # rule under jit in this jax, and patches feed the same XLA fusion.
        if channels_last:
            perm = (0, x.ndim - 1) + tuple(range(1, x.ndim - 1))
            xc = jnp.transpose(x, perm)
            y = _maxpool_ncs(xc, kernel, stride, pad, hi_extra)
            back = (0,) + tuple(range(2, x.ndim)) + (1,)
            return jnp.transpose(y, back)
        return _maxpool_ncs(x, kernel, stride, pad, hi_extra)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, jnp.asarray(0, x.dtype), lax.add,
                              window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            cnt = 1
            for i in range(nsp):
                cnt *= kernel[i]
            return s / cnt
        ones = jnp.ones(x.shape, x.dtype)
        cnt = lax.reduce_window(ones, jnp.asarray(0, x.dtype), lax.add,
                                window, strides, pads)
        return s / cnt
    raise ValueError(f"unsupported pool_type {pool_type}")


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def batch_norm(x, gamma, beta, moving_mean, moving_var, *, axis=1, eps=1e-5,
               momentum=0.9, training=True, use_global_stats=False,
               fix_gamma=False, act=None):
    """BatchNorm (reference src/operator/nn/batch_norm.cc). Returns
    (y, new_moving_mean, new_moving_var); caller threads state.

    ``act`` fuses a trailing activation (BatchNormReLU): on qualifying
    channels-last shapes the normalize+affine+act tail runs as ONE pallas
    HBM pass (ops/pallas/conv_bn_relu.scale_shift_act) — the stats
    reduction (training mode) stays XLA; otherwise the activation rides
    the XLA chain."""
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    red = tuple(i for i in range(x.ndim) if i != (axis % x.ndim))
    bshape = [1] * x.ndim
    bshape[axis % x.ndim] = x.shape[axis % x.ndim]
    if training and not use_global_stats:
        mean = jnp.mean(x, axis=red)
        var = jnp.var(x, axis=red)
        new_mm = momentum * moving_mean + (1 - momentum) * mean
        new_mv = momentum * moving_var + (1 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    from . import select as _sel
    if act is not None and _sel.scale_shift_act(x, axis, act=act):
        from . import pallas as _pallas
        scale, shift = _pallas.fold_bn(gamma, beta, mean, var, eps)
        return (_pallas.scale_shift_act(x, scale, shift, act=act),
                new_mm, new_mv)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(x.dtype)
    y = (x - mean.reshape(bshape).astype(x.dtype)) * inv.reshape(bshape)
    y = y * gamma.reshape(bshape).astype(x.dtype) + beta.reshape(bshape).astype(x.dtype)
    if act is not None:
        y = activation(y, act)
    return y, new_mm, new_mv


def conv_bn_relu(x, weight, gamma, beta, moving_mean, moving_var, *,
                 eps=1e-5, stride=None, pad=None, dilate=None, num_group=1,
                 layout="NHWC", act="relu", training=False):
    """Fused conv+BN+activation (inference hot path). Qualifying calls
    (ops/select.py: inference BN, NHWC, ungrouped) run the pallas fused
    kernel — 1x1 convs as one matmul+epilogue program, other geometries
    as XLA conv + fused epilogue; everything else falls back to the
    unfused conv → batch_norm(act=...) chain with identical semantics.
    Returns y only (moving stats are unchanged by inference BN; training
    callers get the updated stats from the fallback chain via
    batch_norm)."""
    nsp = x.ndim - 2
    stride = tuple(stride or (1,) * nsp)
    pad = tuple(pad or (0,) * nsp)
    from . import select as _sel
    if (not training and nsp == 2
            and _sel.conv_bn_relu(x, weight, stride, pad, dilate, num_group,
                                  layout, training, act=act)):
        from . import pallas as _pallas
        return _pallas.conv_bn_relu(x, weight, gamma, beta, moving_mean,
                                    moving_var, eps=eps, stride=stride,
                                    pad=pad, act=act)
    y = conv(x, weight, None, stride=stride, pad=pad, dilate=dilate,
             num_group=num_group, layout=layout)
    caxis = -1 if layout.endswith("C") and layout[1] != "C" else 1
    y, _, _ = batch_norm(y, gamma, beta, moving_mean, moving_var,
                         axis=caxis, eps=eps, training=training, act=act)
    return y


def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    """LayerNorm (reference src/operator/nn/layer_norm.cc). Stats in f32 for
    bf16 stability, one fused XLA chain. Qualifying shapes dispatch to the
    fused pallas kernel through the selection layer (ops/select.py)."""
    from . import select as _sel
    if _sel.layer_norm(x, gamma, axis):
        from . import pallas as _pallas
        return _pallas.layer_norm(x, gamma, beta, eps)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis % x.ndim] = x.shape[axis % x.ndim]
    y = y * gamma.astype(jnp.float32).reshape(shape) + beta.astype(jnp.float32).reshape(shape)
    return y.astype(x.dtype)


def instance_norm(x, gamma, beta, eps=1e-5):
    """InstanceNorm: normalize over spatial dims per (N, C)."""
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return y * gamma.reshape(shape) + beta.reshape(shape)


def group_norm(x, gamma, beta, num_groups, eps=1e-5):
    """GroupNorm over channel groups (NCHW)."""
    n, c = x.shape[0], x.shape[1]
    g = num_groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    red = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.var(xg, axis=red, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return y * gamma.reshape(shape) + beta.reshape(shape)


def l2_normalization(x, eps=1e-10, mode="instance"):
    """L2Normalization (reference src/operator/l2_normalization.cc)."""
    if mode == "instance":
        red = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + eps)
    elif mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
    elif mode == "spatial":
        red = tuple(range(2, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + eps)
    else:
        raise ValueError(mode)
    return x / n


# ---------------------------------------------------------------------------
# regularization / activations
# ---------------------------------------------------------------------------

def dropout(x, key, rate=0.5, training=True, axes=()):
    """Dropout; `axes` = broadcast axes (one shared mask along them, parity
    with mx.nd.Dropout axes= for spatial/channel dropout)."""
    if not training or rate == 0.0:
        return x
    keep = 1.0 - rate
    mshape = list(x.shape)
    for a in axes:
        mshape[a] = 1
    mask = jax.random.bernoulli(key, keep, tuple(mshape))
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "gelu": jax.nn.gelu,
    "gelu_tanh": lambda a: jax.nn.gelu(a, approximate=True),
    "erf_gelu": lambda a: jax.nn.gelu(a, approximate=False),
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "mish": lambda a: a * jnp.tanh(jax.nn.softplus(a)),
    "relu6": lambda a: jnp.clip(a, 0, 6),
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "hard_swish": jax.nn.hard_swish,
    "leaky": lambda a: jax.nn.leaky_relu(a, 0.25),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "log_softmax": jax.nn.log_softmax,
    "softmax": jax.nn.softmax,
}


def activation(x, act_type):
    try:
        return _ACTIVATIONS[act_type](x)
    except KeyError:
        raise ValueError(f"unknown activation {act_type!r}; "
                         f"known: {sorted(_ACTIVATIONS)}") from None


# ---------------------------------------------------------------------------
# losses / classification heads
# ---------------------------------------------------------------------------

def softmax_cross_entropy(logits, labels, axis=-1, sparse_label=True):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if sparse_label:
        lab = labels.astype(jnp.int32)
        return -jnp.take_along_axis(logp, jnp.expand_dims(lab, axis), axis=axis).squeeze(axis)
    return -jnp.sum(labels * logp, axis=axis)


def smooth_l1(x, scalar=1.0):
    """smooth_l1 (reference: used by SSD loc loss)."""
    s2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * jnp.square(x), absx - 0.5 / s2)


# ---------------------------------------------------------------------------
# attention (XLA path; pallas kernel in ops/pallas/ for the TPU fast path)
# ---------------------------------------------------------------------------

def multihead_attention(q, k, v, num_heads, mask=None, dropout_rate=0.0,
                        key=None, training=False, scale=None, causal=False):
    """Batched MHA on (B, L, D) inputs already projected; splits heads,
    scaled-dot-product, merges heads. Reference: src/operator/contrib/
    transformer.cc (interleaved_matmul_*).

    Fast path: qualifying calls (no custom mask/dropout — see
    ops/select.py) dispatch to the pallas flash-attention kernel
    (ops/pallas/) — O(L) memory, scores stay in VMEM."""
    from . import select as _sel

    b, lq, d = q.shape
    lk = k.shape[1]
    hd = d // num_heads
    scale = scale if scale is not None else 1.0 / (hd ** 0.5)

    def split(x, l):
        return x.reshape(b, l, num_heads, hd).transpose(0, 2, 1, 3)

    if _sel.flash_attention(mask, dropout_rate > 0.0 and training):
        from . import pallas as _pallas
        out = _pallas.flash_attention(split(q, lq), split(k, lk), split(v, lk),
                                      causal=causal, scale=scale)
        return out.transpose(0, 2, 1, 3).reshape(b, lq, d)

    qh, kh, vh = split(q, lq), split(k, lk), split(v, lk)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        if lq > lk:
            raise ValueError("causal attention with more queries than keys is "
                             "undefined (use an explicit mask)")
        tri = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
        mask = tri if mask is None else jnp.logical_and(mask, tri)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.asarray(-1e9, scores.dtype))
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and training and key is not None:
        w = dropout(w, key, dropout_rate, training)
    out = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
    return out.transpose(0, 2, 1, 3).reshape(b, lq, d)


# ---------------------------------------------------------------------------
# vision extras (reference: src/operator/roi_pooling.cc, im2col.h)
# ---------------------------------------------------------------------------

def roi_pooling(x, rois, pooled_size, spatial_scale):
    """ROI max pooling, NCHW. x: (N,C,H,W); rois: (R,5) [batch_idx, x0, y0,
    x1, y1] in image coords. Static-shape TPU formulation: one mask-matmul
    per pooled cell over the full H,W grid is replaced by a gather-free
    max over a masked grid — vectorized over rois via vmap."""
    n, c, h, w = x.shape
    ph, pw = pooled_size
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x0, y0, x1, y1 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        x0, y0 = jnp.round(x0), jnp.round(y0)
        x1, y1 = jnp.round(x1), jnp.round(y1)
        rw = jnp.maximum(x1 - x0 + 1.0, 1.0)
        rh = jnp.maximum(y1 - y0 + 1.0, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        img = x[b]                                          # (C,H,W)

        def cell(i, j):
            hs = jnp.floor(y0 + i * bin_h)
            he = jnp.ceil(y0 + (i + 1) * bin_h)
            ws_ = jnp.floor(x0 + j * bin_w)
            we = jnp.ceil(x0 + (j + 1) * bin_w)
            mask = ((ys >= hs) & (ys < he))[:, None] & \
                   ((xs >= ws_) & (xs < we))[None, :]
            empty = ~mask.any()
            val = jnp.max(jnp.where(mask[None], img, -jnp.inf), axis=(1, 2))
            return jnp.where(empty, 0.0, val)

        ii, jj = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        cells = jax.vmap(jax.vmap(cell))(ii, jj)            # (ph,pw,C)
        return cells.transpose(2, 0, 1)                     # (C,ph,pw)

    return jax.vmap(one_roi)(rois.astype(jnp.float32))      # (R,C,ph,pw)


def im2col(x, kernel, stride=None, dilate=None, pad=None):
    """Unfold NCHW patches to columns (reference im2col.h):
    (N, C, H, W) -> (N, C*kh*kw, L) with L = out_h*out_w."""
    kh, kw = kernel
    stride = stride or (1, 1)
    dilate = dilate or (1, 1)
    pad = pad or (0, 0)
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    out_h = (h + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
    out_w = (w + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1
    # extract_patches via gather of strided indices (static shapes)
    i0 = jnp.arange(out_h) * stride[0]
    j0 = jnp.arange(out_w) * stride[1]
    ki = jnp.arange(kh) * dilate[0]
    kj = jnp.arange(kw) * dilate[1]
    rows = i0[:, None] + ki[None, :]                         # (out_h, kh)
    cols = j0[:, None] + kj[None, :]                         # (out_w, kw)
    # (N, C, out_h, kh, W') -> (N, C, out_h, kh, out_w, kw)
    patches = xp[:, :, rows][:, :, :, :, cols]
    patches = patches.transpose(0, 1, 3, 5, 2, 4)            # N,C,kh,kw,oh,ow
    return patches.reshape(n, c * kh * kw, out_h * out_w)


# ---------------------------------------------------------------------------
# contrib vision ops (reference src/operator/contrib/: roi_align.cc,
# bilinear_resize.cc, adaptive_avg_pooling.cc)
# ---------------------------------------------------------------------------

def _interp_matrix(out_len, in_len):
    """(out_len, in_len) bilinear row-sampling matrix, align-corners
    semantics (the reference BilinearResize2D kernel). Interpolation as a
    dense matmul keeps the op on the MXU instead of gather units."""
    if in_len == 1:
        return jnp.ones((out_len, 1), jnp.float32)
    pos = jnp.linspace(0.0, in_len - 1.0, out_len)
    i0 = jnp.floor(pos).astype(jnp.int32)
    i1 = jnp.minimum(i0 + 1, in_len - 1)
    f = (pos - i0).astype(jnp.float32)
    rows = jnp.arange(out_len)
    a = jnp.zeros((out_len, in_len), jnp.float32)
    return a.at[rows, i0].add(1.0 - f).at[rows, i1].add(f)


def dot_mx(x, y, transpose_a=False, transpose_b=False):
    """MXNet dot semantics on raw arrays: contract last axis of x with
    first axis of y; transpose_a swaps x's last two axes, transpose_b
    swaps y's first two. The ONE implementation behind nd.dot and the
    symbol 'dot' op."""
    if transpose_a:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_b:
        y = jnp.swapaxes(y, 0, 1) if y.ndim > 1 else y
    if x.ndim == 1 and y.ndim == 1:
        return jnp.dot(x, y)
    return jnp.tensordot(x, y, axes=1)


def validate_resize_sizes(height, width, op="BilinearResize2D"):
    """Shared nd/symbol-path validation: explicit positive integer sizes
    (python ints or numpy integer scalars; bool rejected). Returns them as
    python ints."""
    import operator as _op
    try:
        if isinstance(height, bool) or isinstance(width, bool):
            raise TypeError
        height, width = _op.index(height), _op.index(width)
        if height <= 0 or width <= 0:
            raise TypeError
    except TypeError:
        raise ValueError(f"{op} requires explicit positive integer height= "
                         f"and width= (got height={height!r}, "
                         f"width={width!r})")
    return height, width


def _fractional_compute_dtype(x):
    """Fractional-weight ops (resize/avg-pool/roi sampling) must not cast
    weights in [0,1] to an integer input dtype — that truncates them to 0
    and silently zeroes the output. Integer inputs compute in f32 and the
    caller rounds back."""
    return x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32


def _cast_back(y, dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return y
    return jnp.round(y).astype(dtype)


def bilinear_resize(x, height, width):
    """BilinearResize2D, NCHW (reference contrib op). out = A_h @ x @ A_w.T
    per channel — two MXU contractions, no dynamic gathers. Integer images
    (e.g. uint8) compute in f32 and round back."""
    cd = _fractional_compute_dtype(x)
    a_h = _interp_matrix(height, x.shape[2]).astype(cd)
    a_w = _interp_matrix(width, x.shape[3]).astype(cd)
    y = jnp.einsum("ij,ncjk,lk->ncil", a_h, x.astype(cd), a_w)
    return _cast_back(y, x.dtype)


def adaptive_avg_pool(x, output_size):
    """AdaptiveAvgPooling2D, NCHW (reference contrib op). Torch-style
    bins: cell i averages rows [floor(i*H/oh), ceil((i+1)*H/oh)). The
    (static) bin structure becomes averaging matrices -> MXU einsum."""
    import numpy as _np
    oh, ow = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))

    def avg_matrix(out_len, in_len):
        m = _np.zeros((out_len, in_len), _np.float32)
        for i in range(out_len):
            s = (i * in_len) // out_len
            e = -(-((i + 1) * in_len) // out_len)  # ceil div
            m[i, s:e] = 1.0 / (e - s)
        return jnp.asarray(m)

    cd = _fractional_compute_dtype(x)
    a_h = avg_matrix(oh, x.shape[2]).astype(cd)
    a_w = avg_matrix(ow, x.shape[3]).astype(cd)
    y = jnp.einsum("ij,ncjk,lk->ncil", a_h, x.astype(cd), a_w)
    return _cast_back(y, x.dtype)


def roi_align(x, rois, pooled_size, spatial_scale, sample_ratio=-1):
    """ROIAlign, NCHW (reference src/operator/contrib/roi_align.cc —
    the Mask R-CNN op: no coordinate rounding, bilinear sample points
    averaged per cell). x (N,C,H,W); rois (R,5) [batch_idx, x0, y0,
    x1, y1] image coords. sample_ratio<=0 uses 2 samples per bin axis
    (static shapes; the reference's adaptive ceil(bin) is data-dependent
    and would defeat jit)."""
    n, c, h, w = x.shape
    ph, pw = pooled_size
    s = sample_ratio if sample_ratio and sample_ratio > 0 else 2
    out_dtype = x.dtype
    x = x.astype(_fractional_compute_dtype(x))

    ky = (jnp.arange(ph)[:, None] + (jnp.arange(s)[None, :] + 0.5) / s)  # (ph,s)
    kx = (jnp.arange(pw)[:, None] + (jnp.arange(s)[None, :] + 0.5) / s)  # (pw,s)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x0, y0, x1, y1 = (roi[1] * spatial_scale, roi[2] * spatial_scale,
                          roi[3] * spatial_scale, roi[4] * spatial_scale)
        rh = jnp.maximum(y1 - y0, 1.0)
        rw = jnp.maximum(x1 - x0, 1.0)
        ys = (y0 + ky * (rh / ph)).reshape(-1)                # (ph*s,)
        xs = (x0 + kx * (rw / pw)).reshape(-1)                # (pw*s,)
        # reference border rule (roi_align.cc): samples beyond one pixel
        # outside the image contribute ZERO; the [-1, H] band clamps to
        # the edge for the bilinear corners
        vy = ((ys >= -1.0) & (ys <= h)).astype(x.dtype)
        vx = ((xs >= -1.0) & (xs <= w)).astype(x.dtype)
        ys = jnp.clip(ys, 0.0, h - 1.0)
        xs = jnp.clip(xs, 0.0, w - 1.0)
        yi0 = jnp.floor(ys).astype(jnp.int32)
        xi0 = jnp.floor(xs).astype(jnp.int32)
        yi1 = jnp.minimum(yi0 + 1, h - 1)
        xi1 = jnp.minimum(xi0 + 1, w - 1)
        fy = (ys - yi0).astype(x.dtype)
        fx = (xs - xi0).astype(x.dtype)
        img = x[b]                                            # (C,H,W)
        # separable bilinear: gather rows then columns
        gy0 = jnp.take(img, yi0, axis=1)                      # (C,PY,W)
        gy1 = jnp.take(img, yi1, axis=1)
        gy = gy0 * (1 - fy)[None, :, None] + gy1 * fy[None, :, None]
        g00 = jnp.take(gy, xi0, axis=2)                       # (C,PY,PX)
        g01 = jnp.take(gy, xi1, axis=2)
        vals = g00 * (1 - fx)[None, None, :] + g01 * fx[None, None, :]
        vals = vals * (vy[None, :, None] * vx[None, None, :])
        vals = vals.reshape(c, ph, s, pw, s)
        return vals.mean(axis=(2, 4))                         # (C,ph,pw)

    return _cast_back(jax.vmap(one_roi)(rois.astype(jnp.float32)),
                      out_dtype)
