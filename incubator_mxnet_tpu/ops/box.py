"""Bounding-box ops (parity: reference src/operator/contrib/
{bounding_box,multibox_prior,multibox_target,multibox_detection}.cc —
the SSD op family, mx.nd.contrib.*).

TPU-first rebuild: every op is static-shape and vectorized (one-hot matmuls,
pairwise-IoU matrices, lax.scan for the sequential NMS dependency) — no
dynamic box counts, so everything jits and batches. Coordinates are corner
format (xmin, ymin, xmax, ymax), normalized, matching the reference default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ndarray import _apply

__all__ = ["box_iou", "box_nms", "MultiBoxPrior", "MultiBoxTarget",
           "MultiBoxDetection"]

_VAR = (0.1, 0.1, 0.2, 0.2)  # reference multibox center/size variances


# ---------------------------------------------------------------------------
# raw (jnp-level) kernels
# ---------------------------------------------------------------------------

def _iou_corner(a, b):
    """Pairwise IoU. a: (..., M, 4), b: (..., N, 4) -> (..., M, N)."""
    ax0, ay0, ax1, ay1 = jnp.split(a, 4, axis=-1)          # (..., M, 1)
    bx0, by0, bx1, by1 = (t[..., None, :, 0] for t in jnp.split(b, 4, axis=-1))
    ix0 = jnp.maximum(ax0, bx0)
    iy0 = jnp.maximum(ay0, by0)
    ix1 = jnp.minimum(ax1, bx1)
    iy1 = jnp.minimum(ay1, by1)
    inter = jnp.clip(ix1 - ix0, 0) * jnp.clip(iy1 - iy0, 0)
    area_a = jnp.clip(ax1 - ax0, 0) * jnp.clip(ay1 - ay0, 0)
    area_b = jnp.clip(bx1 - bx0, 0) * jnp.clip(by1 - by0, 0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


def _center_to_corner(x):
    cx, cy, w, h = jnp.split(x, 4, axis=-1)
    return jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)


def _corner_to_center(x):
    x0, y0, x1, y1 = jnp.split(x, 4, axis=-1)
    return jnp.concatenate([(x0 + x1) / 2, (y0 + y1) / 2, x1 - x0, y1 - y0], -1)


def _multibox_prior(h, w, sizes, ratios, steps, offsets, dtype=jnp.float32):
    """Anchors for one (H, W) feature map -> (H*W*(S+R-1), 4) corner coords.

    Per pixel: [s1,r1], [s2,r1], ..., [sn,r1], [s1,r2], ..., [s1,rm]
    (reference layout: all sizes with first ratio, then first size with the
    remaining ratios)."""
    sizes = jnp.asarray(sizes, dtype)
    ratios = jnp.asarray(ratios, dtype)
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=dtype) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=dtype) + offsets[1]) * step_x
    # anchor shapes; widths carry the feature-map aspect (h/w) so anchors
    # stay square in pixel space on non-square maps (reference kernel
    # multiplies width by in_h/in_w)
    r0 = jnp.sqrt(ratios[0])
    ws = jnp.concatenate([sizes * r0, sizes[0] * jnp.sqrt(ratios[1:])]) * (
        jnp.asarray(h, dtype) / jnp.asarray(w, dtype))
    hs = jnp.concatenate([sizes / r0, sizes[0] / jnp.sqrt(ratios[1:])])
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")               # (H, W)
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    out = jnp.stack([cxg - ws / 2, cyg - hs / 2, cxg + ws / 2, cyg + hs / 2],
                    axis=-1)                                      # (H, W, K, 4)
    return out.reshape(-1, 4)


def _encode_boxes(gt_corner, anchors_corner, variances=_VAR):
    """Corner GT + corner anchors -> variance-scaled center offsets."""
    g = _corner_to_center(gt_corner)
    a = _corner_to_center(anchors_corner)
    tx = (g[..., 0] - a[..., 0]) / jnp.maximum(a[..., 2], 1e-12) / variances[0]
    ty = (g[..., 1] - a[..., 1]) / jnp.maximum(a[..., 3], 1e-12) / variances[1]
    tw = jnp.log(jnp.maximum(g[..., 2] / jnp.maximum(a[..., 2], 1e-12),
                             1e-12)) / variances[2]
    th = jnp.log(jnp.maximum(g[..., 3] / jnp.maximum(a[..., 3], 1e-12),
                             1e-12)) / variances[3]
    return jnp.stack([tx, ty, tw, th], axis=-1)


def _decode_boxes(pred, anchors_corner, clip=True, variances=_VAR):
    """Variance-scaled offsets -> corner boxes."""
    a = _corner_to_center(anchors_corner)
    cx = pred[..., 0] * variances[0] * a[..., 2] + a[..., 0]
    cy = pred[..., 1] * variances[1] * a[..., 3] + a[..., 1]
    w = jnp.exp(jnp.clip(pred[..., 2] * variances[2], -10, 10)) * a[..., 2]
    h = jnp.exp(jnp.clip(pred[..., 3] * variances[3], -10, 10)) * a[..., 3]
    out = _center_to_corner(jnp.stack([cx, cy, w, h], axis=-1))
    return jnp.clip(out, 0.0, 1.0) if clip else out


def _multibox_target(anchors, labels, cls_preds, overlap_threshold,
                     negative_mining_ratio, negative_mining_thresh,
                     ignore_label=-1, minimum_negative_samples=0,
                     variances=_VAR):
    """Single image. anchors (A,4); labels (M,5) [cls x0 y0 x1 y1], cls=-1
    pad; cls_preds (C+1, A). Returns (box_target (A,4), box_mask (A,4),
    cls_target (A,) int32 [0=background, c+1=class c])."""
    A = anchors.shape[0]
    valid = labels[:, 0] >= 0                                   # (M,)
    iou = _iou_corner(anchors, labels[:, 1:5])                  # (A, M)
    iou = jnp.where(valid[None, :], iou, -1.0)
    # per-anchor best gt
    best_gt = jnp.argmax(iou, axis=1)                           # (A,)
    best_iou = jnp.take_along_axis(iou, best_gt[:, None], 1)[:, 0]
    matched = best_iou >= overlap_threshold
    # bipartite: each VALID gt claims its best still-unclaimed anchor, in gt
    # order (exclusive, like the reference's sequential matcher); zero-IoU
    # gts claim nothing
    def claim(carry, m):
        claimed, forced, gt_of = carry
        col = jnp.where(claimed, -2.0, iou[:, m])
        a_best = jnp.argmax(col)
        ok = valid[m] & (col[a_best] > 0)
        claimed = claimed.at[a_best].set(claimed[a_best] | ok)
        forced = forced.at[a_best].set(forced[a_best] | ok)
        gt_of = gt_of.at[a_best].set(jnp.where(ok, m, gt_of[a_best]))
        return (claimed, forced, gt_of), None

    M = labels.shape[0]
    (_, forced, gt_of_forced), _ = lax.scan(
        claim, (jnp.zeros((A,), bool), jnp.zeros((A,), bool),
                jnp.zeros((A,), jnp.int32)),
        jnp.arange(M, dtype=jnp.int32))
    assign_gt = jnp.where(forced, gt_of_forced, best_gt)
    positive = jnp.logical_or(matched & (best_iou > 0), forced)
    gt_boxes = labels[assign_gt, 1:5]                           # (A, 4)
    gt_cls = labels[assign_gt, 0].astype(jnp.int32)
    box_target = jnp.where(positive[:, None],
                           _encode_boxes(gt_boxes, anchors, variances), 0.0)
    box_mask = jnp.broadcast_to(positive[:, None], (A, 4)).astype(jnp.float32)
    cls_target = jnp.where(positive, gt_cls + 1, 0)
    if negative_mining_ratio > 0 and cls_preds is not None:
        # hard negatives: among anchors with max-IoU < negative_mining_thresh
        # (reference semantics), rank by background-error score
        probs = jax.nn.softmax(cls_preds, axis=0)               # (C+1, A)
        neg_score = 1.0 - probs[0]                              # bg error
        eligible = (~positive) & (best_iou < negative_mining_thresh)
        neg_score = jnp.where(eligible, neg_score, -1.0)
        n_pos = positive.sum()
        n_neg = jnp.clip((n_pos * negative_mining_ratio).astype(jnp.int32),
                         minimum_negative_samples, A)
        order = jnp.argsort(-neg_score)                          # desc
        rank = jnp.zeros((A,), jnp.int32).at[order].set(
            jnp.arange(A, dtype=jnp.int32))
        keep_neg = (rank < n_neg) & (neg_score > -1.0)
        # ignore_label marks anchors excluded from the cls loss
        cls_target = jnp.where(positive, cls_target,
                               jnp.where(keep_neg, 0, ignore_label))
    return box_target, box_mask, cls_target


def _nms_mask(boxes, scores, ids, iou_threshold, valid, force_suppress):
    """Greedy NMS keep-mask over score-sorted boxes via lax.scan.
    boxes (K,4), scores (K,), ids (K,) — already sorted desc by score."""
    K = boxes.shape[0]
    iou = _iou_corner(boxes, boxes)                             # (K, K)
    same_cls = (ids[:, None] == ids[None, :]) | force_suppress
    suppress_pair = (iou > iou_threshold) & same_cls            # i suppresses j

    def step(alive, i):
        keep_i = alive[i] & valid[i]
        alive = alive & ~(keep_i & suppress_pair[i] &
                          (jnp.arange(K) > i))
        return alive, keep_i

    alive0 = jnp.ones((K,), bool)
    _, keep = lax.scan(step, alive0, jnp.arange(K))
    return keep & valid


def _box_nms(data, overlap_thresh, valid_thresh, topk, coord_start,
             score_index, id_index, force_suppress, background_id,
             in_format="corner", out_format=None):
    """data (B, K, E) rows [.. id? score coords ..] -> same shape, suppressed
    rows set to -1, kept rows score-sorted first (reference box_nms
    semantics). Only the top-`topk` candidates enter the O(T^2) suppression
    matrix — the rest are below them in score and returned as -1.
    out_format != in_format converts surviving rows' coordinate columns
    (shared raw body for nd.contrib/sym.contrib box_nms)."""
    scores = data[..., score_index]
    ids = (data[..., id_index].astype(jnp.int32) if id_index >= 0
           else jnp.zeros(scores.shape, jnp.int32))
    boxes = lax.dynamic_slice_in_dim(data, coord_start, 4, axis=-1)
    if in_format == "center":
        boxes = _center_to_corner(boxes)
    valid = scores > valid_thresh
    if id_index >= 0 and background_id >= 0:
        valid &= ids != background_id
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf), axis=1)
    K = data.shape[1]
    T = min(topk, K) if topk > 0 else K

    def per_image(d, b, s, i, v, o):
        ot = o[:T]
        db, bb, sb, ib, vb = d[ot], b[ot], s[ot], i[ot], v[ot]
        keep = _nms_mask(bb, sb, ib, overlap_thresh, vb, force_suppress)
        out_top = jnp.where(keep[:, None], db, -jnp.ones_like(db))
        if T == K:
            return out_top
        pad = -jnp.ones((K - T, d.shape[-1]), d.dtype)
        return jnp.concatenate([out_top, pad], axis=0)

    out = jax.vmap(per_image)(data, boxes, scores, ids, valid, order)
    out_format = out_format or in_format
    if out_format != in_format:
        conv = (_corner_to_center if out_format == "center"
                else _center_to_corner)
        coords = out[..., coord_start:coord_start + 4]
        alive = (coords != -1.0).any(axis=-1, keepdims=True)
        out = jnp.concatenate(
            [out[..., :coord_start], jnp.where(alive, conv(coords), coords),
             out[..., coord_start + 4:]], axis=-1)
    return out


# ---------------------------------------------------------------------------
# recordable NDArray-level ops
# ---------------------------------------------------------------------------

def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference: mx.nd.contrib.box_iou)."""
    def f(a, b):
        if format == "center":
            a, b = _center_to_corner(a), _center_to_corner(b)
        return _iou_corner(a, b)
    return _apply(f, [lhs, rhs], name="box_iou")


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Non-maximum suppression (reference: mx.nd.contrib.box_nms).
    Suppressed/invalid rows become all -1; rows are returned score-sorted.
    out_format != in_format converts surviving rows' coordinate columns
    (corner <-> center), leaving suppressed all-(-1) rows untouched."""
    _validate_nms_formats(in_format, out_format)

    def f(d):
        one = d.ndim == 2
        db = d[None] if one else d
        out = _box_nms(db, overlap_thresh, valid_thresh, topk, coord_start,
                       score_index, id_index, force_suppress, background_id,
                       in_format, out_format)
        return out[0] if one else out
    return _apply(f, [data], name="box_nms")


def _validate_nms_formats(in_format, out_format):
    for fmt in (in_format, out_format):
        if fmt not in ("corner", "center"):
            raise ValueError(f"box_nms: unknown format {fmt!r}")


def _multibox_prior_raw(x, sizes, ratios, steps, offsets, clip, layout):
    """Shared raw body for nd.contrib/sym.contrib MultiBoxPrior."""
    h, w = (x.shape[2], x.shape[3]) if layout == "NCHW" else \
           (x.shape[1], x.shape[2])
    out = _multibox_prior(h, w, sizes, ratios, steps, offsets,
                          x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                          else jnp.float32)[None]
    return jnp.clip(out, 0.0, 1.0) if clip else out


def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5), layout="NCHW"):
    """Anchor generation (reference: mx.nd.contrib.MultiBoxPrior; argument
    order matches the reference op — clip before steps). data: feature
    map; returns (1, H*W*K, 4) corner anchors; clip=True clamps anchors
    to [0, 1]."""
    return _apply(lambda x: _multibox_prior_raw(
        x, sizes, ratios, steps, offsets, clip, layout),
        [data], name="MultiBoxPrior")


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1, negative_mining_ratio=-1,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=_VAR):
    """Anchor-GT matching + target encoding (reference:
    mx.nd.contrib.MultiBoxTarget). anchor (1,A,4); label (B,M,5);
    cls_pred (B,C+1,A). Returns (box_target (B,A*4), box_mask (B,A*4),
    cls_target (B,A))."""
    return _apply(lambda anc, lab, cp: _multibox_target_raw(
        anc, lab, cp, overlap_threshold, negative_mining_ratio,
        negative_mining_thresh, ignore_label, minimum_negative_samples,
        variances),
        [anchor, label, cls_pred], n_out=3, name="MultiBoxTarget")


def _multibox_target_raw(anc, lab, cp, overlap_threshold,
                         negative_mining_ratio, negative_mining_thresh,
                         ignore_label, minimum_negative_samples,
                         variances=_VAR):
    """Shared raw body for nd.contrib/sym.contrib MultiBoxTarget."""
    def one(lab_i, cp_i):
        bt, bm, ct = _multibox_target(anc[0], lab_i, cp_i,
                                      overlap_threshold,
                                      negative_mining_ratio,
                                      negative_mining_thresh,
                                      ignore_label,
                                      minimum_negative_samples,
                                      variances)
        return bt.reshape(-1), bm.reshape(-1), ct
    return tuple(jax.vmap(one)(lab, cp))


def MultiBoxDetection(cls_prob, loc_pred, anchor, threshold=0.01,
                      clip=True, nms_threshold=0.5, force_suppress=False,
                      variances=_VAR, nms_topk=-1):
    """Decode + per-class NMS (reference: mx.nd.contrib.MultiBoxDetection).
    cls_prob (B,C+1,A); loc_pred (B,A*4); anchor (1,A,4).
    Returns (B, A, 6) rows [class_id, score, x0, y0, x1, y1]; suppressed
    rows have class_id = -1."""
    return _apply(lambda cp, lp, anc: _multibox_detection_raw(
        cp, lp, anc, threshold, clip, nms_threshold, force_suppress,
        nms_topk, variances),
        [cls_prob, loc_pred, anchor], name="MultiBoxDetection")


def _multibox_detection_raw(cp, lp, anc, threshold, clip, nms_threshold,
                            force_suppress, nms_topk, variances=_VAR):
    """Shared raw body for nd.contrib/sym.contrib MultiBoxDetection."""
    b = cp.shape[0]
    a = anc.shape[1]
    boxes = _decode_boxes(lp.reshape(b, a, 4), anc, clip,
                          variances)                         # (B,A,4)
    # best non-background class per anchor
    cls_id = jnp.argmax(cp[:, 1:, :], axis=1)                # (B,A)
    score = jnp.max(cp[:, 1:, :], axis=1)
    keep = score > threshold
    rows = jnp.concatenate([
        jnp.where(keep, cls_id, -1).astype(boxes.dtype)[..., None],
        jnp.where(keep, score, -1.0)[..., None], boxes], axis=-1)
    return _box_nms(rows, nms_threshold, threshold, nms_topk,
                    coord_start=2, score_index=1, id_index=0,
                    force_suppress=force_suppress, background_id=-1)
