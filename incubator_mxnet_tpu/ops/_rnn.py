"""Recurrent kernels on lax.scan (parity: src/operator/rnn.cc, the fused
RNN op cuDNN path).

Design: the whole sequence × all layers runs inside ONE traced computation —
`lax.scan` over time per (layer, direction) — so XLA compiles a single fused
loop whose body is two MXU matmuls + elementwise gates. This replaces the
reference's cuDNN RNN kernels; there is no per-timestep Python dispatch.

Gate orders match the reference (rnn-inl.h):
  LSTM: i, f, g, o        GRU: r, z, n (reset, update, newmem)
Weights per (layer, direction): i2h_w (G*H, I), h2h_w (G*H, H),
i2h_b (G*H,), h2h_b (G*H,) — exactly the reference's parameter packing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def packed_param_size(mode, num_layers, bidirectional, input_size, hidden):
    """Length of the flat packed parameter vector (reference rnn-inl.h
    layout: all i2h/h2h weights in (layer, dir) order, then all biases).
    Single source of truth for FusedRNNCell.param_size and the RNN op's
    shape-inference hint."""
    G = GATES[mode]
    D = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        il = input_size if layer == 0 else D * hidden
        size += D * (G * hidden * il + G * hidden * hidden)
    size += num_layers * D * 2 * G * hidden
    return size


def rnn_cell_step(mode, x, states, wi, wh, bi, bh):
    """One timestep. states: tuple of arrays (N, H). Returns (out, states)."""
    if mode in ("rnn_relu", "rnn_tanh"):
        (h,) = states
        pre = x @ wi.T + bi + h @ wh.T + bh
        h2 = jax.nn.relu(pre) if mode == "rnn_relu" else jnp.tanh(pre)
        return h2, (h2,)
    if mode == "lstm":
        h, c = states
        pre = x @ wi.T + bi + h @ wh.T + bh
        i, f, g, o = jnp.split(pre, 4, axis=-1)
        c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
        return h2, (h2, c2)
    if mode == "gru":
        (h,) = states
        xi = x @ wi.T + bi
        hh = h @ wh.T + bh
        xr, xz, xn = jnp.split(xi, 3, axis=-1)
        hr, hz, hn = jnp.split(hh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h2 = (1 - z) * n + z * h
        return h2, (h2,)
    raise ValueError(f"unknown rnn mode {mode}")


def _scan_direction(mode, x_tnc, h0, wi, wh, bi, bh, reverse=False,
                    valid_len=None):
    """Scan one direction over time. x_tnc (T, N, I); h0 tuple of (N, H).
    valid_len (N,) masks steps t >= valid_len: state holds, output zeroed."""
    T = x_tnc.shape[0]

    def step(carry, inp):
        states = carry
        x_t, t = inp
        out, new_states = rnn_cell_step(mode, x_t, states, wi, wh, bi, bh)
        if valid_len is not None:
            # ts is scanned WITH x, so t is the true time index in both
            # directions (reverse=True consumes pairs back-to-front).
            keep = (t < valid_len)[:, None]
            new_states = tuple(jnp.where(keep, ns, s)
                               for ns, s in zip(new_states, states))
            out = jnp.where(keep, out, jnp.zeros_like(out))
        return new_states, out

    ts = jnp.arange(T)
    final, outs = lax.scan(step, h0, (x_tnc, ts), reverse=reverse)
    return outs, final


def rnn_forward(x, states, layer_params, mode, bidirectional=False,
                dropout=0.0, dropout_key=None, training=False,
                valid_len=None):
    """Fused multi-layer (bi)RNN (parity: the RNN op's cuDNN fused path).

    x: (T, N, I). states: list of (L*D, N, H) arrays — [h] or [h, c].
    layer_params: list over L*D of (wi, wh, bi, bh); layout [l0_fwd, l0_bwd,
    l1_fwd, ...] like the reference. Returns (out (T, N, H*D), new_states).
    """
    D = 2 if bidirectional else 1
    L = len(layer_params) // D
    n_state = len(states)
    new_states = [[] for _ in range(n_state)]
    h = x
    for layer in range(L):
        outs_dir = []
        for d in range(D):
            idx = layer * D + d
            wi, wh, bi, bh = layer_params[idx]
            h0 = tuple(s[idx] for s in states)
            outs, final = _scan_direction(mode, h, h0, wi, wh, bi, bh,
                                          reverse=(d == 1),
                                          valid_len=valid_len)
            outs_dir.append(outs)
            for k in range(n_state):
                new_states[k].append(final[k])
        h = outs_dir[0] if D == 1 else jnp.concatenate(outs_dir, axis=-1)
        if dropout > 0.0 and training and layer < L - 1 and dropout_key is not None:
            dropout_key, sub = jax.random.split(dropout_key)
            keep = 1.0 - dropout
            mask = jax.random.bernoulli(sub, keep, h.shape)
            h = jnp.where(mask, h / keep, jnp.zeros_like(h))
    out_states = [jnp.stack(s, axis=0) for s in new_states]
    return h, out_states
