"""NDArray-level operator namespace (parity: mx.nd.Convolution etc.).

Thin recordable wrappers over ops/_raw.py. Gluon layers call these in eager
mode; under hybridize the same code runs with tracers and compiles into one
XLA computation. `from incubator_mxnet_tpu import ops` or use the mirrored
names on `mx.nd`.
"""
from __future__ import annotations

from .. import autograd
from ..ndarray import NDArray, _apply, _as_nd
from ..ndarray import random as ndrandom
from . import _raw

from .box import (box_iou, box_nms, MultiBoxPrior, MultiBoxTarget,
                  MultiBoxDetection)

__all__ = ["FullyConnected", "Convolution", "Deconvolution", "Pooling",
           "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "Activation",
           "Dropout", "L2Normalization", "softmax_cross_entropy", "smooth_l1",
           "UpSampling", "multihead_attention", "box_iou", "box_nms",
           "MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection",
           "ROIPooling", "im2col", "SliceChannel",
           "SequenceMask", "SequenceLast", "SequenceReverse",
           "GridGenerator", "BilinearSampler", "SpatialTransformer",
           "Correlation"]


def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):
    if no_bias or bias is None:
        return _apply(lambda x, w: _raw.dense(x, w, None, flatten),
                      [data, weight], name="FullyConnected")
    return _apply(lambda x, w, b: _raw.dense(x, w, b, flatten),
                  [data, weight, bias], name="FullyConnected")


def Convolution(data, weight, bias=None, kernel=None, stride=None, pad=None,
                dilate=None, num_filter=None, num_group=1, no_bias=False,
                layout="NCHW"):
    kw = dict(kernel=kernel, stride=stride, pad=pad, dilate=dilate,
              num_group=num_group, layout=layout)
    if no_bias or bias is None:
        return _apply(lambda x, w: _raw.conv(x, w, None, **kw),
                      [data, weight], name="Convolution")
    return _apply(lambda x, w, b: _raw.conv(x, w, b, **kw),
                  [data, weight, bias], name="Convolution")


def Deconvolution(data, weight, bias=None, kernel=None, stride=None, pad=None,
                  dilate=None, adj=None, num_filter=None, num_group=1,
                  no_bias=False, layout="NCHW"):
    kw = dict(stride=stride, pad=pad, dilate=dilate, adj=adj,
              num_group=num_group, layout=layout)
    if no_bias or bias is None:
        return _apply(lambda x, w: _raw.conv_transpose(x, w, None, **kw),
                      [data, weight], name="Deconvolution")
    return _apply(lambda x, w, b: _raw.conv_transpose(x, w, b, **kw),
                  [data, weight, bias], name="Deconvolution")


def Pooling(data, pool_type="max", kernel=(2, 2), stride=None, pad=None,
            global_pool=False, count_include_pad=True, layout="NCHW",
            ceil_mode=False):
    return _apply(lambda x: _raw.pooling(x, pool_type, kernel, stride, pad,
                                         global_pool, count_include_pad, layout,
                                         ceil_mode),
                  [data], name="Pooling")


def BatchNorm(data, gamma, beta, moving_mean, moving_var, *, axis=1, eps=1e-5,
              momentum=0.9, fix_gamma=False, use_global_stats=False,
              output_mean_var=False):
    """Eager BatchNorm. In training mode (autograd.is_training) uses batch
    stats and updates moving_mean/var NDArrays in place (outside the tape),
    like the reference's in-place aux update. Single pass: y and new moving
    stats come from one recorded op."""
    training = autograd.is_training()

    def fwd(x, g, b, mm, mv):
        return _raw.batch_norm(x, g, b, mm, mv, axis=axis, eps=eps,
                               momentum=momentum, training=training,
                               use_global_stats=use_global_stats,
                               fix_gamma=fix_gamma)

    out, nm, nv = _apply(fwd, [data, gamma, beta, moving_mean, moving_var],
                         n_out=3, name="BatchNorm")
    if training and not use_global_stats:
        moving_mean._data = nm._data
        moving_var._data = nv._data
    return out


def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5):
    return _apply(lambda x, g, b: _raw.layer_norm(x, g, b, axis, eps),
                  [data, gamma, beta], name="LayerNorm")


def InstanceNorm(data, gamma, beta, eps=1e-5):
    return _apply(lambda x, g, b: _raw.instance_norm(x, g, b, eps),
                  [data, gamma, beta], name="InstanceNorm")


def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5):
    return _apply(lambda x, g, b: _raw.group_norm(x, g, b, num_groups, eps),
                  [data, gamma, beta], name="GroupNorm")


def Activation(data, act_type="relu"):
    return _apply(lambda x: _raw.activation(x, act_type), [data], name="Activation")


def Dropout(data, p=0.5, mode="training", axes=()):
    training = autograd.is_training() or mode == "always"
    if not training or p == 0.0:
        return data
    key = ndrandom._key()
    return _apply(lambda x: _raw.dropout(x, key, p, True, axes), [data],
                  name="Dropout")


def L2Normalization(data, eps=1e-10, mode="instance"):
    return _apply(lambda x: _raw.l2_normalization(x, eps, mode), [data],
                  name="L2Normalization")


def softmax_cross_entropy(data, label, axis=-1, sparse_label=True):
    label = _as_nd(label)
    return _apply(lambda x, l: _raw.softmax_cross_entropy(x, l, axis, sparse_label),
                  [data, label], name="softmax_cross_entropy")


def smooth_l1(data, scalar=1.0):
    return _apply(lambda x: _raw.smooth_l1(x, scalar), [data], name="smooth_l1")


def UpSampling(data, scale=2, sample_type="nearest", num_filter=None,
               layout="NCHW"):
    """Parity: mx.nd.UpSampling (src/operator/nn/upsampling.cc); `bilinear`
    is the reference's fixed-weight Deconvolution path (num_filter accepted
    for API parity; channels are inferred)."""
    return _apply(lambda x: _raw.upsampling(x, scale, sample_type, layout),
                  [data], name="UpSampling")


def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """ROI max pooling (reference: mx.nd.ROIPooling). data NCHW; rois (R,5)
    rows [batch_idx, x0, y0, x1, y1] image coords."""
    return _apply(lambda x, r: _raw.roi_pooling(x, r, pooled_size,
                                                spatial_scale),
                  [data, _as_nd(rois)], name="ROIPooling")


def im2col(data, kernel, stride=None, dilate=None, pad=None):
    """Patch unfolding (reference: mx.nd.im2col)."""
    return _apply(lambda x: _raw.im2col(x, kernel, stride, dilate, pad),
                  [data], name="im2col")


def SliceChannel(data, num_outputs, axis=1, squeeze_axis=False):
    """Parity alias: mx.nd.SliceChannel == split."""
    from .. import ndarray as nd
    return nd.split(data, num_outputs, axis=axis, squeeze_axis=squeeze_axis)


def multihead_attention(q, k, v, num_heads, mask=None, dropout_rate=0.0,
                        scale=None, causal=False):
    training = autograd.is_training()
    key = ndrandom._key() if (dropout_rate > 0.0 and training) else None
    inputs = [q, k, v] + ([mask] if mask is not None else [])

    def f(qq, kk, vv, *rest):
        m = rest[0] if rest else None
        return _raw.multihead_attention(qq, kk, vv, num_heads, m, dropout_rate,
                                        key, training, scale, causal)
    return _apply(f, inputs, name="multihead_attention")


def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):
    """Parity: mx.nd.SequenceMask (src/operator/sequence_mask.cc)."""
    if sequence_length is None:
        return _apply(lambda x: _raw.sequence_mask(x, None, False, value,
                                                   axis),
                      [data], name="SequenceMask")
    sequence_length = _as_nd(sequence_length)
    return _apply(lambda x, ln: _raw.sequence_mask(x, ln,
                                                   use_sequence_length,
                                                   value, axis),
                  [data, sequence_length], name="SequenceMask")


def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0):
    """Parity: mx.nd.SequenceLast (src/operator/sequence_last.cc)."""
    if sequence_length is None:
        return _apply(lambda x: _raw.sequence_last(x, None, False, axis),
                      [data], name="SequenceLast")
    sequence_length = _as_nd(sequence_length)
    return _apply(lambda x, ln: _raw.sequence_last(x, ln,
                                                   use_sequence_length, axis),
                  [data, sequence_length], name="SequenceLast")


def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0):
    """Parity: mx.nd.SequenceReverse (src/operator/sequence_reverse.cc)."""
    if sequence_length is None:
        return _apply(lambda x: _raw.sequence_reverse(x, None, False, axis),
                      [data], name="SequenceReverse")
    sequence_length = _as_nd(sequence_length)
    return _apply(lambda x, ln: _raw.sequence_reverse(
        x, ln, use_sequence_length, axis),
        [data, sequence_length], name="SequenceReverse")


def GridGenerator(data, transform_type="affine", target_shape=None):
    """Parity: mx.nd.GridGenerator (src/operator/grid_generator.cc)."""
    return _apply(lambda d: _raw.grid_generator(d, transform_type,
                                                target_shape),
                  [data], name="GridGenerator")


def BilinearSampler(data, grid):
    """Parity: mx.nd.BilinearSampler (src/operator/bilinear_sampler.cc)."""
    return _apply(_raw.bilinear_sampler, [data, grid],
                  name="BilinearSampler")


def SpatialTransformer(data, loc, target_shape=None,
                       transform_type="affine", sampler_type="bilinear"):
    """Parity: mx.nd.SpatialTransformer (src/operator/spatial_transformer.cc)
    = GridGenerator(loc) + BilinearSampler, fused in one recorded op."""
    if sampler_type != "bilinear":
        raise ValueError("only bilinear sampler_type is supported")

    def f(x, theta):
        grid = _raw.grid_generator(theta, transform_type, target_shape)
        return _raw.bilinear_sampler(x, grid)

    return _apply(f, [data, loc], name="SpatialTransformer")


def Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Parity: mx.nd.Correlation (src/operator/correlation.cc, FlowNet)."""
    return _apply(lambda a, b: _raw.correlation(
        a, b, kernel_size, max_displacement, stride1, stride2, pad_size,
        is_multiply),
        [data1, data2], name="Correlation")


# Mirror the op namespace onto mx.nd for reference-style calls, and expose
# the box/SSD family under mx.nd.contrib.* like the reference.
def _mirror_into_nd():
    import sys
    import types
    nd_mod = sys.modules["incubator_mxnet_tpu.ndarray"]
    for name in __all__:
        setattr(nd_mod, name, globals()[name])
    contrib = types.ModuleType("incubator_mxnet_tpu.ndarray.contrib")
    for name in ["box_iou", "box_nms", "MultiBoxPrior", "MultiBoxTarget",
                 "MultiBoxDetection", "multihead_attention"]:
        setattr(contrib, name, globals()[name])
    nd_mod.contrib = contrib
    sys.modules["incubator_mxnet_tpu.ndarray.contrib"] = contrib


_mirror_into_nd()
