"""NDArray-level operator namespace (parity: mx.nd.Convolution etc.).

Thin recordable wrappers over ops/_raw.py. Gluon layers call these in eager
mode; under hybridize the same code runs with tracers and compiles into one
XLA computation. `from incubator_mxnet_tpu import ops` or use the mirrored
names on `mx.nd`.
"""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

from .. import autograd
from ..ndarray import NDArray, _apply, _as_nd, _is_tracer
from ..ndarray import random as ndrandom
from . import _raw

from .box import (box_iou, box_nms, MultiBoxPrior, MultiBoxTarget,
                  MultiBoxDetection)

__all__ = ["FullyConnected", "Convolution", "Deconvolution", "Pooling",
           "ConvBNReLU",
           "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "Activation",
           "Dropout", "L2Normalization", "softmax_cross_entropy", "smooth_l1",
           "UpSampling", "multihead_attention", "box_iou", "box_nms",
           "MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection",
           "ROIPooling", "ROIAlign", "BilinearResize2D",
           "AdaptiveAvgPooling2D", "im2col", "SliceChannel",
           "SequenceMask", "SequenceLast", "SequenceReverse",
           "GridGenerator", "BilinearSampler", "SpatialTransformer",
           "Correlation", "foreach", "while_loop", "cond"]


def _symbolic(x):
    """True when a Gluon forward is being traced to a Symbol graph (the
    block was called with a Symbol input — see gluon/symbolize.py)."""
    return not isinstance(x, NDArray) and type(x).__name__ == "Symbol"


def _sym_call(name, out_index=None, **kw):
    from ..gluon.symbolize import sym_call
    return sym_call(name, out_index=out_index, **kw)


def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):
    if _symbolic(data):
        return _sym_call("FullyConnected", data=data, weight=weight,
                         bias=None if no_bias else bias,
                         no_bias=no_bias or bias is None,
                         num_hidden=num_hidden, flatten=flatten)
    if no_bias or bias is None:
        return _apply(lambda x, w: _raw.dense(x, w, None, flatten),
                      [data, weight], name="FullyConnected")
    return _apply(lambda x, w, b: _raw.dense(x, w, b, flatten),
                  [data, weight, bias], name="FullyConnected")


def Convolution(data, weight, bias=None, kernel=None, stride=None, pad=None,
                dilate=None, num_filter=None, num_group=1, no_bias=False,
                layout="NCHW"):
    if _symbolic(data):
        if num_filter is None and hasattr(weight, "shape"):
            num_filter = (weight.shape[-1] if layout == "NHWC"
                          else weight.shape[0])
        return _sym_call("Convolution", data=data, weight=weight,
                         bias=None if no_bias else bias,
                         no_bias=no_bias or bias is None, kernel=kernel,
                         stride=stride, pad=pad, dilate=dilate,
                         num_filter=num_filter, num_group=num_group,
                         layout=layout)
    kw = dict(kernel=kernel, stride=stride, pad=pad, dilate=dilate,
              num_group=num_group, layout=layout)
    if no_bias or bias is None:
        return _apply(lambda x, w: _raw.conv(x, w, None, **kw),
                      [data, weight], name="Convolution")
    return _apply(lambda x, w, b: _raw.conv(x, w, b, **kw),
                  [data, weight, bias], name="Convolution")


def Deconvolution(data, weight, bias=None, kernel=None, stride=None, pad=None,
                  dilate=None, adj=None, num_filter=None, num_group=1,
                  no_bias=False, layout="NCHW"):
    if _symbolic(data):
        if hasattr(weight, "shape"):
            if kernel is None:
                kernel = (weight.shape[:-2] if layout == "NHWC"
                          else weight.shape[2:])
            if num_filter is None:
                num_filter = num_group * (weight.shape[-2] if layout == "NHWC"
                                          else weight.shape[1])
        return _sym_call("Deconvolution", data=data, weight=weight,
                         bias=None if no_bias else bias,
                         no_bias=no_bias or bias is None, kernel=kernel,
                         stride=stride, pad=pad, dilate=dilate, adj=adj,
                         num_filter=num_filter, num_group=num_group,
                         layout=layout)
    kw = dict(stride=stride, pad=pad, dilate=dilate, adj=adj,
              num_group=num_group, layout=layout)
    if no_bias or bias is None:
        return _apply(lambda x, w: _raw.conv_transpose(x, w, None, **kw),
                      [data, weight], name="Deconvolution")
    return _apply(lambda x, w, b: _raw.conv_transpose(x, w, b, **kw),
                  [data, weight, bias], name="Deconvolution")


def ConvBNReLU(data, weight, gamma, beta, moving_mean, moving_var, *,
               eps=1e-5, stride=None, pad=None, dilate=None, num_group=1,
               layout="NHWC", act_type="relu"):
    """Fused conv + BatchNorm + activation — the inference/serving hot
    path (reference analogue: cuDNN's fused ConvBiasActivation). In
    predict mode, qualifying shapes (ops/select.py) run the pallas fused
    kernel (1x1 convs as one matmul+epilogue program); otherwise the op
    is the exact conv→BN→act chain. Moving stats are read, never
    written — training graphs should keep separate Conv/BatchNorm blocks
    so the stats update (this op discards batch-stat updates)."""
    training = autograd.is_training()

    def f(x, w, g, b, mm, mv):
        return _raw.conv_bn_relu(x, w, g, b, mm, mv, eps=eps, stride=stride,
                                 pad=pad, dilate=dilate,
                                 num_group=num_group, layout=layout,
                                 act=act_type, training=training)

    return _apply(f, [data, weight, gamma, beta, moving_mean, moving_var],
                  name="ConvBNReLU")


def Pooling(data, pool_type="max", kernel=(2, 2), stride=None, pad=None,
            global_pool=False, count_include_pad=True, layout="NCHW",
            ceil_mode=False):
    if _symbolic(data):
        return _sym_call("Pooling", data=data, pool_type=pool_type,
                         kernel=kernel, stride=stride, pad=pad,
                         global_pool=global_pool,
                         count_include_pad=count_include_pad, layout=layout,
                         ceil_mode=ceil_mode)
    return _apply(lambda x: _raw.pooling(x, pool_type, kernel, stride, pad,
                                         global_pool, count_include_pad, layout,
                                         ceil_mode),
                  [data], name="Pooling")


def BatchNorm(data, gamma, beta, moving_mean, moving_var, *, axis=1, eps=1e-5,
              momentum=0.9, fix_gamma=False, use_global_stats=False,
              output_mean_var=False):
    """Eager BatchNorm. In training mode (autograd.is_training) uses batch
    stats and updates moving_mean/var NDArrays in place (outside the tape),
    like the reference's in-place aux update. Single pass: y and new moving
    stats come from one recorded op."""
    training = autograd.is_training()

    def fwd(x, g, b, mm, mv):
        return _raw.batch_norm(x, g, b, mm, mv, axis=axis, eps=eps,
                               momentum=momentum, training=training,
                               use_global_stats=use_global_stats,
                               fix_gamma=fix_gamma)

    out, nm, nv = _apply(fwd, [data, gamma, beta, moving_mean, moving_var],
                         n_out=3, name="BatchNorm")
    if training and not use_global_stats:
        moving_mean._data = nm._data
        moving_var._data = nv._data
    return out


def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5):
    if _symbolic(data):
        return _sym_call("LayerNorm", data=data, gamma=gamma, beta=beta,
                         axis=axis, eps=eps)
    return _apply(lambda x, g, b: _raw.layer_norm(x, g, b, axis, eps),
                  [data, gamma, beta], name="LayerNorm")


def InstanceNorm(data, gamma, beta, eps=1e-5):
    if _symbolic(data):
        return _sym_call("InstanceNorm", data=data, gamma=gamma, beta=beta,
                         eps=eps)
    return _apply(lambda x, g, b: _raw.instance_norm(x, g, b, eps),
                  [data, gamma, beta], name="InstanceNorm")


def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5):
    return _apply(lambda x, g, b: _raw.group_norm(x, g, b, num_groups, eps),
                  [data, gamma, beta], name="GroupNorm")


def Activation(data, act_type="relu"):
    if _symbolic(data):
        return _sym_call("Activation", data=data, act_type=act_type)
    return _apply(lambda x: _raw.activation(x, act_type), [data], name="Activation")


def Dropout(data, p=0.5, mode="training", axes=()):
    if _symbolic(data):
        return _sym_call("Dropout", data=data, p=p, mode=mode, axes=axes)
    training = autograd.is_training() or mode == "always"
    if not training or p == 0.0:
        return data
    key = ndrandom._key()
    return _apply(lambda x: _raw.dropout(x, key, p, True, axes), [data],
                  name="Dropout")


def L2Normalization(data, eps=1e-10, mode="instance"):
    return _apply(lambda x: _raw.l2_normalization(x, eps, mode), [data],
                  name="L2Normalization")


def softmax_cross_entropy(data, label, axis=-1, sparse_label=True):
    label = _as_nd(label)
    return _apply(lambda x, l: _raw.softmax_cross_entropy(x, l, axis, sparse_label),
                  [data, label], name="softmax_cross_entropy")


def smooth_l1(data, scalar=1.0):
    return _apply(lambda x: _raw.smooth_l1(x, scalar), [data], name="smooth_l1")


def UpSampling(data, scale=2, sample_type="nearest", num_filter=None,
               layout="NCHW"):
    """Parity: mx.nd.UpSampling (src/operator/nn/upsampling.cc); `bilinear`
    is the reference's fixed-weight Deconvolution path (num_filter accepted
    for API parity; channels are inferred)."""
    if _symbolic(data):
        return _sym_call("UpSampling", data=data, scale=scale,
                         sample_type=sample_type, num_filter=num_filter,
                         layout=layout)
    return _apply(lambda x: _raw.upsampling(x, scale, sample_type, layout),
                  [data], name="UpSampling")


def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """ROI max pooling (reference: mx.nd.ROIPooling). data NCHW; rois (R,5)
    rows [batch_idx, x0, y0, x1, y1] image coords."""
    if _symbolic(data):
        return _sym_call("ROIPooling", data=data, rois=rois,
                         pooled_size=pooled_size,
                         spatial_scale=spatial_scale)
    return _apply(lambda x, r: _raw.roi_pooling(x, r, pooled_size,
                                                spatial_scale),
                  [data, _as_nd(rois)], name="ROIPooling")


def ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
             sample_ratio=-1):
    """ROIAlign (reference: mx.nd.contrib.ROIAlign,
    src/operator/contrib/roi_align.cc). data NCHW; rois (R,5)
    [batch_idx, x0, y0, x1, y1] image coords."""
    if _symbolic(data):
        return _sym_call("ROIAlign", data=data, rois=rois,
                         pooled_size=pooled_size,
                         spatial_scale=spatial_scale,
                         sample_ratio=sample_ratio)
    return _apply(lambda x, r: _raw.roi_align(x, r, pooled_size,
                                              spatial_scale, sample_ratio),
                  [data, _as_nd(rois)], name="ROIAlign")


def BilinearResize2D(data, height=None, width=None):
    """Bilinear resize, align-corners (reference:
    mx.nd.contrib.BilinearResize2D, src/operator/contrib/
    bilinear_resize.cc). Two MXU matrix contractions, no gathers."""
    height, width = _raw.validate_resize_sizes(height, width)
    if _symbolic(data):
        return _sym_call("BilinearResize2D", data=data, height=height,
                         width=width)
    return _apply(lambda x: _raw.bilinear_resize(x, height, width),
                  [data], name="BilinearResize2D")


def AdaptiveAvgPooling2D(data, output_size=1):
    """Adaptive average pooling (reference:
    mx.nd.contrib.AdaptiveAvgPooling2D)."""
    if _symbolic(data):
        return _sym_call("AdaptiveAvgPooling2D", data=data,
                         output_size=output_size)
    return _apply(lambda x: _raw.adaptive_avg_pool(x, output_size),
                  [data], name="AdaptiveAvgPooling2D")


def im2col(data, kernel, stride=None, dilate=None, pad=None):
    """Patch unfolding (reference: mx.nd.im2col)."""
    return _apply(lambda x: _raw.im2col(x, kernel, stride, dilate, pad),
                  [data], name="im2col")


def SliceChannel(data, num_outputs, axis=1, squeeze_axis=False):
    """Parity alias: mx.nd.SliceChannel == split."""
    from .. import ndarray as nd
    return nd.split(data, num_outputs, axis=axis, squeeze_axis=squeeze_axis)


def multihead_attention(q, k, v, num_heads, mask=None, dropout_rate=0.0,
                        scale=None, causal=False):
    if _symbolic(q):
        if dropout_rate and dropout_rate > 0.0:
            import warnings
            warnings.warn(
                "symbol trace of multihead_attention drops attention-"
                "weight dropout (the reference's symbol attention ops "
                "carry none either); residual/FFN Dropout nodes still "
                "honor is_train", stacklevel=3)
        return _sym_call("multihead_attention", queries=q, keys=k, values=v,
                         num_heads=num_heads, mask=mask, scale=scale,
                         causal=causal)
    training = autograd.is_training()
    key = ndrandom._key() if (dropout_rate > 0.0 and training) else None
    inputs = [q, k, v] + ([mask] if mask is not None else [])

    def f(qq, kk, vv, *rest):
        m = rest[0] if rest else None
        return _raw.multihead_attention(qq, kk, vv, num_heads, m, dropout_rate,
                                        key, training, scale, causal)
    return _apply(f, inputs, name="multihead_attention")


def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):
    """Parity: mx.nd.SequenceMask (src/operator/sequence_mask.cc)."""
    if sequence_length is None:
        return _apply(lambda x: _raw.sequence_mask(x, None, False, value,
                                                   axis),
                      [data], name="SequenceMask")
    sequence_length = _as_nd(sequence_length)
    return _apply(lambda x, ln: _raw.sequence_mask(x, ln,
                                                   use_sequence_length,
                                                   value, axis),
                  [data, sequence_length], name="SequenceMask")


def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0):
    """Parity: mx.nd.SequenceLast (src/operator/sequence_last.cc)."""
    if sequence_length is None:
        return _apply(lambda x: _raw.sequence_last(x, None, False, axis),
                      [data], name="SequenceLast")
    sequence_length = _as_nd(sequence_length)
    return _apply(lambda x, ln: _raw.sequence_last(x, ln,
                                                   use_sequence_length, axis),
                  [data, sequence_length], name="SequenceLast")


def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0):
    """Parity: mx.nd.SequenceReverse (src/operator/sequence_reverse.cc)."""
    if sequence_length is None:
        return _apply(lambda x: _raw.sequence_reverse(x, None, False, axis),
                      [data], name="SequenceReverse")
    sequence_length = _as_nd(sequence_length)
    return _apply(lambda x, ln: _raw.sequence_reverse(
        x, ln, use_sequence_length, axis),
        [data, sequence_length], name="SequenceReverse")


def GridGenerator(data, transform_type="affine", target_shape=None):
    """Parity: mx.nd.GridGenerator (src/operator/grid_generator.cc)."""
    return _apply(lambda d: _raw.grid_generator(d, transform_type,
                                                target_shape),
                  [data], name="GridGenerator")


def BilinearSampler(data, grid):
    """Parity: mx.nd.BilinearSampler (src/operator/bilinear_sampler.cc)."""
    return _apply(_raw.bilinear_sampler, [data, grid],
                  name="BilinearSampler")


def SpatialTransformer(data, loc, target_shape=None,
                       transform_type="affine", sampler_type="bilinear"):
    """Parity: mx.nd.SpatialTransformer (src/operator/spatial_transformer.cc)
    = GridGenerator(loc) + BilinearSampler, fused in one recorded op."""
    if sampler_type != "bilinear":
        raise ValueError("only bilinear sampler_type is supported")

    def f(x, theta):
        grid = _raw.grid_generator(theta, transform_type, target_shape)
        return _raw.bilinear_sampler(x, grid)

    return _apply(f, [data, loc], name="SpatialTransformer")


def Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Parity: mx.nd.Correlation (src/operator/correlation.cc, FlowNet)."""
    return _apply(lambda a, b: _raw.correlation(
        a, b, kernel_size, max_displacement, stride1, stride2, pad_size,
        is_multiply),
        [data1, data2], name="Correlation")


def _as_nd_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


from ..base import make_loop_caller as _make_loop_caller  # noqa: E402


def foreach(body, data, init_states):
    """Parity: mx.nd.contrib.foreach (src/operator/control_flow.cc).
    body(data_slice, states) -> (outputs, new_states); iterates over axis 0
    of `data`.

    Two execution modes, matching the reference's imperative semantics:
    while `autograd.record()` is active the loop runs eagerly step by step
    (the tape sees every op, so gradients flow to closure variables too);
    otherwise it lowers to ONE compiled lax.scan. Under hybridize/jit
    tracing the eager path simply unrolls into the trace."""
    from .. import ndarray as nd
    data_list = _as_nd_list(data)
    if not data_list:
        raise ValueError("foreach requires non-empty `data`")
    states_list = _as_nd_list(init_states)
    n_data = len(data_list)
    single_data = not isinstance(data, (list, tuple))
    single_states = not isinstance(init_states, (list, tuple))
    T = data_list[0].shape[0]

    if autograd.is_recording():
        states = init_states
        outs_acc = None
        single_out = True
        for t in range(T):
            xs = [d[t] for d in data_list]
            outs, states = body(xs[0] if single_data else xs, states)
            single_out = not isinstance(outs, (list, tuple))
            outs = _as_nd_list(outs)
            if outs_acc is None:
                outs_acc = [[] for _ in outs]
            for acc, o in zip(outs_acc, outs):
                acc.append(o)
        stacked = [nd.stack(*acc, axis=0) for acc in (outs_acc or [])]
        return (stacked[0] if single_out and stacked else stacked, states)

    import jax.lax as _lax
    meta = {}

    def fn(*raws):
        d_raws, s_raws = raws[:n_data], raws[n_data:]

        def step(carry, xs):
            s_nd = [NDArray(c) for c in carry]
            x_nd = [NDArray(x) for x in xs]
            outs, new_s = body(x_nd[0] if single_data else x_nd,
                               s_nd[0] if single_states else s_nd)
            meta["single_out"] = not isinstance(outs, (list, tuple))
            outs = _as_nd_list(outs)
            new_s = _as_nd_list(new_s)
            meta["n_out"] = len(outs)
            return (tuple(o._data for o in new_s),
                    tuple(o._data for o in outs))

        final, stacked = _lax.scan(step, tuple(s_raws), tuple(d_raws))
        return tuple(stacked) + tuple(final)

    all_in = data_list + states_list
    # probe ONE step (not the whole scan) just to learn the output count
    carry_avals = tuple(jax.ShapeDtypeStruct(s.shape, s._data.dtype)
                        for s in states_list)
    slice_avals = tuple(jax.ShapeDtypeStruct(d.shape[1:], d._data.dtype)
                        for d in data_list)

    def _one_step(c, xs):
        s_nd = [NDArray(r) for r in c]
        x_nd = [NDArray(r) for r in xs]
        outs, new_s = body(x_nd[0] if single_data else x_nd,
                           s_nd[0] if single_states else s_nd)
        meta["single_out"] = not isinstance(outs, (list, tuple))
        meta["n_out"] = len(_as_nd_list(outs))
        return tuple(o._data for o in _as_nd_list(new_s))

    jax.eval_shape(_one_step, carry_avals, slice_avals)
    n_out = meta["n_out"]
    res = _apply(fn, all_in, n_out=n_out + len(states_list), name="foreach")
    res = _as_nd_list(res)
    out_part = res[:n_out]
    state_part = res[n_out:]
    return (out_part[0] if meta["single_out"] else out_part,
            state_part[0] if single_states and len(state_part) == 1
            else state_part)


def while_loop(cond, func, loop_vars, max_iterations):
    """Parity: mx.nd.contrib.while_loop. func(loop_vars) ->
    (step_output, new_loop_vars); runs while cond(loop_vars) is true, at
    most max_iterations steps. Outputs are stacked padded to
    max_iterations (reference shape semantics).

    Calling convention: with multiple loop vars both the reference style
    `def func(a, b)` (called func(*loop_vars)) and this repo's list style
    `def func(vs)` are supported — the signature decides
    (base.make_loop_caller).

    Eager Python loop while recording (tape/closure gradients exact);
    otherwise a cond-gated lax.scan of static length — XLA-compilable AND
    reverse-mode differentiable (a raw while_loop is not). NOTE (matches
    the reference's imperative behavior): in recording mode a loop whose
    condition is false on entry returns an empty outputs list — output
    shapes are unknowable without running the body."""
    from .. import ndarray as nd
    lv = _as_nd_list(loop_vars)
    single = not isinstance(loop_vars, (list, tuple))
    n_lv = len(lv)
    call_cond = _make_loop_caller(cond, n_lv, single)
    call_func = _make_loop_caller(func, n_lv, single)

    if autograd.is_recording():
        cur = loop_vars
        outs_acc = None
        n_steps = 0
        while n_steps < max_iterations:
            pred = call_cond([cur] if single else _as_nd_list(cur))
            if not bool(np.asarray(pred._data if isinstance(pred, NDArray)
                                   else pred)):
                break
            outs, cur = call_func([cur] if single else _as_nd_list(cur))
            outs = _as_nd_list(outs)
            if outs_acc is None:
                outs_acc = [[] for _ in outs]
            for acc, o in zip(outs_acc, outs):
                acc.append(o)
            n_steps += 1
        stacked = []
        for acc in (outs_acc or []):
            pad = [nd.zeros_like(acc[0])] * (max_iterations - len(acc))
            stacked.append(nd.stack(*(acc + pad), axis=0))
        return stacked, cur

    import jax.lax as _lax
    meta = {}

    def fn(*raws):
        def step(carry, _):
            vars_raw, active = carry
            v_nd = [NDArray(r) for r in vars_raw]
            pred = call_cond(v_nd)
            pred_raw = pred._data if isinstance(pred, NDArray) else pred
            go = jnp.logical_and(
                active, jnp.asarray(pred_raw).astype(bool).reshape(()))
            outs, new_vars = call_func(v_nd)
            outs = _as_nd_list(outs)
            new_vars = _as_nd_list(new_vars)
            meta["n_out"] = len(outs)
            kept = tuple(jnp.where(go, nv._data, ov)
                         for nv, ov in zip(new_vars, vars_raw))
            out_raw = tuple(jnp.where(go, o._data,
                                      jnp.zeros_like(o._data))
                            for o in outs)
            return (kept, go), out_raw

        (final, _), stacked = _lax.scan(
            step, (tuple(raws), jnp.bool_(True)), None,
            length=max_iterations)
        return tuple(stacked) + tuple(final)

    def _one_step(raws):
        v_nd = [NDArray(r) for r in raws]
        outs, new_vars = call_func(v_nd)
        meta["n_out"] = len(_as_nd_list(outs))
        return tuple(o._data for o in _as_nd_list(new_vars))

    jax.eval_shape(_one_step,
                   tuple(jax.ShapeDtypeStruct(v.shape, v._data.dtype)
                         for v in lv))
    n_out = meta["n_out"]
    res = _as_nd_list(_apply(fn, lv, n_out=n_out + n_lv,
                             name="while_loop"))
    out_part = res[:n_out]
    var_part = res[n_out:n_out + n_lv]
    return (out_part, var_part[0] if single and n_lv == 1 else var_part)


def cond(pred, then_func, else_func, inputs):
    """Parity: mx.nd.contrib.cond. On a concrete predicate (eager mode) the
    chosen branch runs directly — tape gradients exact, branches need not
    match shapes. On a traced predicate both branches compile into
    lax.cond and XLA picks at runtime (shapes must match)."""
    import jax.lax as _lax
    ins = _as_nd_list(inputs)
    single = not isinstance(inputs, (list, tuple))
    pred_nd = pred if isinstance(pred, NDArray) else _as_nd(pred)

    if not _is_tracer(pred_nd._data):
        branch = then_func if bool(np.asarray(pred_nd._data)) else else_func
        return branch(inputs)

    def fn(p, *raws):
        def wrap(f):
            def g(rs):
                nds = [NDArray(r) for r in rs]
                out = f(nds[0] if single else nds)
                return tuple(o._data for o in _as_nd_list(out))
            return g
        outs = _lax.cond(p.astype(bool).reshape(()), wrap(then_func),
                         wrap(else_func), tuple(raws))
        return outs if len(outs) > 1 else outs[0]

    probe = jax.eval_shape(fn, pred_nd._data, *[x._data for x in ins])
    n_out = len(probe) if isinstance(probe, tuple) else 1
    res = _as_nd_list(_apply(fn, [pred_nd] + ins, n_out=n_out, name="cond"))
    return res[0] if len(res) == 1 else res


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """Parity: mx.nd.contrib.arange_like — arange sized by `data`'s shape
    (whole array flattened-shape when axis is None, else that axis); with
    repeat=r, r consecutive elements share a value, total size unchanged."""
    if _symbolic(data):
        return _sym_call("arange_like", data=data, start=start, step=step,
                         repeat=repeat, axis=axis)
    def f(x):
        n = x.shape[axis] if axis is not None else int(np.prod(x.shape))
        if n % repeat:
            raise ValueError(
                f"arange_like: size {n} not divisible by repeat {repeat}")
        # exact length: index arithmetic, never float-endpoint arange
        r = start + step * jnp.arange(n // repeat, dtype=jnp.float32)
        if repeat > 1:
            r = jnp.repeat(r, repeat)
        r = r.astype(x.dtype)
        return r.reshape(x.shape) if axis is None else r
    return _apply(f, [data], name="arange_like")


def fft(data, compute_size=128):
    """Parity: mx.nd.contrib.fft (src/operator/contrib/fft.cc): real input
    (..., d) -> packed complex output (..., 2d), interleaved re/im."""
    def f(x):
        c = jnp.fft.fft(x.astype(jnp.float32), axis=-1)
        out = jnp.stack([c.real, c.imag], axis=-1)
        return out.reshape(x.shape[:-1] + (2 * x.shape[-1],)).astype(x.dtype)
    return _apply(f, [data], name="fft")


def ifft(data, compute_size=128):
    """Parity: mx.nd.contrib.ifft — input packed (..., 2d) interleaved
    re/im, output real (..., d). Matches the reference's UNNORMALIZED
    inverse: ifft(fft(x)) == d * x."""
    def f(x):
        d = x.shape[-1] // 2
        z = x.astype(jnp.float32).reshape(x.shape[:-1] + (d, 2))
        c = z[..., 0] + 1j * z[..., 1]
        return (jnp.fft.ifft(c, axis=-1).real * d).astype(x.dtype)
    return _apply(f, [data], name="ifft")


# Mirror the op namespace onto mx.nd for reference-style calls, and expose
# the box/SSD family under mx.nd.contrib.* like the reference.
def _mirror_into_nd():
    import sys
    import types
    nd_mod = sys.modules["incubator_mxnet_tpu.ndarray"]
    for name in __all__:
        setattr(nd_mod, name, globals()[name])
    contrib = types.ModuleType("incubator_mxnet_tpu.ndarray.contrib")
    for name in ["box_iou", "box_nms", "MultiBoxPrior", "MultiBoxTarget",
                 "MultiBoxDetection", "multihead_attention",
                 "foreach", "while_loop", "cond",
                 "arange_like", "fft", "ifft",
                 "ROIAlign", "BilinearResize2D", "AdaptiveAvgPooling2D"]:
        setattr(contrib, name, globals()[name])

    def _contrib_getattr(name):
        # quantization ops live with contrib.quantization (which imports
        # gluon, loaded after ops) — resolve lazily, PEP 562 style
        if name in ("quantize", "dequantize", "quantize_v2"):
            from ..contrib import quantization as _q
            return getattr(_q, name)
        raise AttributeError(
            f"module 'incubator_mxnet_tpu.ndarray.contrib' has no "
            f"attribute {name!r}")

    contrib.__getattr__ = _contrib_getattr
    nd_mod.contrib = contrib
    sys.modules["incubator_mxnet_tpu.ndarray.contrib"] = contrib


_mirror_into_nd()

