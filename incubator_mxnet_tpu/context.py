"""Device contexts — the TPU-native analogue of MXNet's mx.cpu()/mx.gpu().

Reference parity: python/mxnet/context.py (Context, cpu, gpu, num_gpus,
current_context, context scope via `with`). Here a Context wraps a
`jax.Device`; `tpu(i)` is a first-class device alongside `cpu(i)`, per the
north star. Placement happens through `jax.device_put`; compute launched on
arrays resident on a device runs there (XLA), so MXNet's stream semantics
map onto XLA's async dispatch.
"""
from __future__ import annotations

import threading

import jax

_DEVTYPE_ALIASES = {
    "cpu": "cpu",
    "tpu": "tpu",
    # On this stack the accelerator platform may register as an experimental
    # name (e.g. "axon" tunnels a TPU); `gpu` maps to CUDA when present.
    "gpu": "gpu",
}


class Context:
    """A device context. ``with ctx:`` scopes the default context."""

    _tls = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = str(device_type)
        self.device_id = int(device_id)
        self._device = None  # resolved lazily

    # -- resolution -------------------------------------------------------
    @property
    def device(self) -> jax.Device:
        if self._device is None:
            self._device = _resolve_device(self.device_type, self.device_id)
        return self._device

    # -- identity ---------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    # -- scoping ----------------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._tls, "stack"):
            Context._tls.stack = []
        Context._tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._tls.stack.pop()
        return False

    @classmethod
    def current(cls) -> "Context":
        stack = getattr(cls._tls, "stack", None)
        if stack:
            return stack[-1]
        return default_context()


def _local(devs):
    """Process-local (addressable) devices only: in a multi-process
    cluster `mx.cpu(0)`/`mx.tpu(0)` means THIS worker's device 0, exactly
    as the reference's `mx.gpu(0)` is local to its worker — and jax
    refuses to place data on another process's devices anyway."""
    mine = [d for d in devs if d.process_index == jax.process_index()]
    return mine or devs


def _platform_devices(platform: str):
    try:
        return _local(jax.devices(platform))
    except RuntimeError:
        return []


def _resolve_device(device_type: str, device_id: int) -> jax.Device:
    platform = _DEVTYPE_ALIASES.get(device_type, device_type)
    devs = _platform_devices(platform)
    if not devs and platform == "tpu":
        # TPU may surface under an experimental platform name; fall back to
        # whatever the default backend exposes if it is not plain CPU, else
        # (CPU-only test envs) use CPU so `tpu()` code still runs.
        devs = _local([d for d in jax.devices() if d.platform != "cpu"]) \
            or _platform_devices("cpu")
    if not devs:
        raise ValueError(
            f"No device of type {device_type!r} available (jax platforms: "
            f"{[d.platform for d in jax.devices()]})"
        )
    if device_id >= len(devs):
        raise ValueError(f"{device_type}({device_id}) out of range: {len(devs)} available")
    return devs[device_id]


_default_ctx = None


def default_context() -> Context:
    """Default context: the first device of JAX's default backend (TPU on a
    TPU host, CPU in the test environment)."""
    global _default_ctx
    if _default_ctx is None:
        dev = _local(jax.devices())[0]
        devtype = "tpu" if dev.platform not in ("cpu", "gpu", "cuda") else dev.platform
        ctx = Context(devtype, 0)
        ctx._device = dev
        _default_ctx = ctx
    return _default_ctx


def current_context() -> Context:
    return Context.current()


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def num_tpus() -> int:
    return len([d for d in jax.devices() if d.platform not in ("cpu", "gpu", "cuda")])


def num_gpus() -> int:
    return len(_platform_devices("gpu"))


def ctx_from_device(dev: jax.Device) -> Context:
    devtype = "cpu" if dev.platform == "cpu" else ("gpu" if dev.platform in ("gpu", "cuda") else "tpu")
    ctx = Context(devtype, dev.id)
    ctx._device = dev
    return ctx


def gpu_memory_info(device_id=0):
    """Parity: mx.context.gpu_memory_info — (free, total) bytes for the
    accelerator. Backed by the jax device's memory_stats(); raises on
    backends that expose none (the reference raises on non-GPU builds)."""
    import jax
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if device_id >= len(devs):
        raise ValueError(f"no accelerator device {device_id} "
                         f"(have {len(devs)})")
    stats = devs[device_id].memory_stats()
    if not stats:
        raise RuntimeError("device exposes no memory statistics")
    total = stats.get("bytes_limit", stats.get("bytes_reservable_limit"))
    if not total:
        raise RuntimeError("device memory statistics carry no capacity "
                           f"limit (keys: {sorted(stats)})")
    used = stats.get("bytes_in_use", 0)
    return total - used, total
