"""Clock-aligned telemetry collection across fleet processes.

The Router (serving) and rank 0 (training, over the elastic wire)
cannot trust the other processes' wall clocks: merged timelines built
from raw ``ts`` fields interleave by whichever NTP daemon spoke last.
The collector pulls each process's counters snapshot, ``mxtpu.events``
tail, and health flags over the existing ``diagnostics.export`` HTTP
surface, and estimates the per-process clock offset from the
request/response midpoint — the classic NTP estimate::

    offset ≈ server_ts − (t_send + t_recv) / 2        (server − local)
    |error| ≤ (t_recv − t_send) / 2  =  rtt / 2

The bound is tight exactly when the two wire legs are symmetric; a
fully asymmetric route (all the rtt on one leg) reaches the bound but
never exceeds it, so a merged timeline is trustworthy to ± rtt/2 per
process. Events additionally carry a ``mono`` companion stamp
(``mxtpu.events/2``) so an NTP step *inside* one process cannot
reorder that process's own records in the merge.

Discipline (the house rules for every scope):

* **never raise** — a dead, torn, or slow replica produces a counted
  pull error (``fleetscope.pull_errors``) and a ``last_error`` string
  in the ring, never an exception on the control plane;
* **bounded** — per-process history is a ``deque(maxlen=ring)``;
  events tails are capped at ``tail`` records per pull;
* **off-path** — nothing here runs unless something constructed a
  Collector; the serving/routing hot paths only ever check
  ``fleetscope._FS``.
"""
from __future__ import annotations

import collections
import json
import threading
import time
import urllib.request

from ..profiler.counters import counter as _counter
from ..profiler.counters import observe as _observe
from ..profiler.counters import set_gauge as _set_gauge

__all__ = ["Collector", "estimate_offset", "events_tail",
           "merge_process_events", "join_traces"]


def estimate_offset(t_send: float, t_recv: float, server_ts: float):
    """NTP-style offset of the remote clock relative to ours.

    Returns ``(offset_s, bound_s)``: the midpoint estimate
    ``server_ts - (t_send + t_recv)/2`` and its worst-case error bound
    ``rtt/2`` (reached only by a fully asymmetric route). ``remote_wall
    ≈ local_wall + offset``."""
    rtt = max(0.0, t_recv - t_send)
    return server_ts - (t_send + t_recv) / 2.0, rtt / 2.0


def events_tail(path, n: int = 64) -> list:
    """Last ``n`` parsed records of an ``mxtpu.events`` JSONL file.
    Unparseable lines are dropped (the validator's job is elsewhere);
    any IO error yields an empty tail — tails are telemetry, not
    truth."""
    try:
        with open(path, "rb") as f:
            # bounded read from the end: tails must not scale with the
            # log (a long run's events file is unbounded)
            try:
                f.seek(-min(256 * 1024, _size(f)), 2)
            except OSError:
                pass
            raw = f.read().decode("utf-8", "replace")
    except OSError:
        return []
    out = []
    for ln in raw.splitlines()[-int(n):]:
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def _size(f) -> int:
    cur = f.tell()
    f.seek(0, 2)
    size = f.tell()
    f.seek(cur)
    return size


def merge_process_events(per_process, offsets=None) -> list:
    """Merge per-process event lists into one clock-aligned timeline.

    ``per_process``: {process_name: [event records]} — records are
    ``mxtpu.events`` dicts (``ts`` wall seconds, optional ``mono``).
    ``offsets``: {process_name: offset_s} as estimated by the
    collector (``remote ≈ local + offset``); missing processes merge
    uncorrected.

    Two-level ordering, NTP-step safe: WITHIN a process, records order
    by their ``mono`` companion (wall steps cannot reorder them), and
    each record's corrected wall time is clamped non-decreasing in
    that order; ACROSS processes, the corrected wall clocks interleave.
    Returns new records with ``ts`` rewritten to the collector's clock
    and the original preserved as ``ts_raw`` (+ ``src``)."""
    offsets = offsets or {}
    merged = []
    for name, recs in per_process.items():
        off = float(offsets.get(name, 0.0))
        local = [dict(r) for r in recs if isinstance(r, dict)]
        # mono is authoritative within the process when present
        local.sort(key=lambda r: (r.get("mono")
                                  if isinstance(r.get("mono"), (int, float))
                                  else r.get("ts", 0.0)))
        last = None
        for r in local:
            ts = r.get("ts")
            corrected = (float(ts) - off) if isinstance(ts, (int, float)) \
                else 0.0
            if last is not None and corrected < last:
                corrected = last       # an NTP step inside the process
            last = corrected
            r["ts_raw"] = ts
            r["ts"] = corrected
            r.setdefault("src", name)
            merged.append(r)
    merged.sort(key=lambda r: r["ts"])
    return merged


def join_traces(router_records, replica_records) -> dict:
    """Join router-side ``fleetscope.request`` records with replica-side
    ``serving.request`` records on ``trace_id``.

    Returns {trace_id: {"router": rec|None, "replica": rec|None,
    "replica_name": str|None}} over every trace either side saw. The
    caller derives the join rate and the wire gap; unjoined traces stay
    in the map — counted, never guessed away."""
    traces = {}
    for rec in router_records:
        args = rec.get("args") or {}
        tid = args.get("trace_id")
        if isinstance(tid, str) and tid:
            slot = traces.setdefault(tid, {"router": None, "replica": None,
                                           "replica_name": None})
            slot["router"] = rec
            if isinstance(args.get("replica"), str):
                slot["replica_name"] = args["replica"]
    for rec in replica_records:
        args = rec.get("args") or {}
        tid = args.get("trace_id")
        if isinstance(tid, str) and tid:
            slot = traces.setdefault(tid, {"router": None, "replica": None,
                                           "replica_name": None})
            slot["replica"] = rec
    return traces


class Collector:
    """Periodic puller of per-process telemetry over diagnostics.export.

    ``targets``: list of {"name": str, "host": str, "port": int} rows
    pointing at each process's export HTTP server (fleet workers print
    ``diag_port`` in their READY line; see fleet/worker.py). Every poll
    GETs ``/json`` (counters + the remote wall clock for the offset
    estimate) and ``/events?n=tail`` (events tail + armed flags)."""

    def __init__(self, targets, interval_s: float = 2.0, ring: int = 64,
                 tail: int = 64, timeout_s: float = 3.0):
        self.targets = [dict(t) for t in targets]
        self.interval_s = float(interval_s)
        self.tail = int(tail)
        self.timeout_s = float(timeout_s)
        self.rings = {t["name"]: collections.deque(maxlen=int(ring))
                      for t in self.targets}
        self.errors = {t["name"]: None for t in self.targets}
        self._c_pulls = _counter("fleetscope.pulls", "fleetscope")
        self._c_errors = _counter("fleetscope.pull_errors",
                                    "fleetscope")
        _set_gauge("fleetscope.processes", len(self.targets),
                   "fleetscope")
        self._stop = threading.Event()
        self._thread = None

    # -- one pull (never raises) -----------------------------------------
    def _get_json(self, host, port, path):
        t0 = time.time()
        with urllib.request.urlopen(
                f"http://{host}:{port}{path}",
                timeout=self.timeout_s) as resp:
            doc = json.loads(resp.read().decode("utf-8"))
        t1 = time.time()
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: not a JSON object")
        return doc, t0, t1

    def poll_one(self, target) -> dict | None:
        """Pull one process; append to its ring. Returns the entry, or
        None on a counted failure (dead/torn/slow — the reason lands in
        ``self.errors[name]``)."""
        name = target["name"]
        try:
            doc, t0, t1 = self._get_json(target["host"], target["port"],
                                         "/json")
            server_ts = doc.get("ts")
            if not isinstance(server_ts, (int, float)):
                raise ValueError("/json carries no numeric 'ts'")
            offset, bound = estimate_offset(t0, t1, float(server_ts))
            entry = {
                "name": name,
                "t_mid": (t0 + t1) / 2.0,
                "offset_s": offset,
                "offset_bound_s": bound,
                "rtt_s": max(0.0, t1 - t0),
                "counters": doc.get("counters") or {},
                "kinds": doc.get("kinds") or {},
            }
            try:
                ev, _, _ = self._get_json(target["host"], target["port"],
                                          f"/events?n={self.tail}")
                entry["events_tail"] = ev.get("tail") or []
                entry["health"] = ev.get("health") or {}
            except Exception as e:   # noqa: BLE001 — tail is optional
                entry["events_tail"] = []
                entry["health"] = {"tail_error":
                                   f"{type(e).__name__}: {e}"}
            self.rings[name].append(entry)
            self.errors[name] = None
            self._c_pulls.increment()
            _observe("fleetscope.pull_ms",
                     entry["rtt_s"] * 1000.0, "fleetscope")
            return entry
        except Exception as e:   # noqa: BLE001 — NEVER raise: a dead
            # replica is a datum, not a control-plane crash
            self.errors[name] = f"{type(e).__name__}: {e}"
            self._c_errors.increment()
            return None

    def poll_once(self) -> list:
        """Pull every target once; returns the successful entries."""
        return [e for t in self.targets
                if (e := self.poll_one(t)) is not None]

    # -- background loop --------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mxtpu-fleetscope-collector")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:   # noqa: BLE001 — belt over braces
                pass
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.timeout_s + 1.0)

    # -- views -------------------------------------------------------------
    def offsets(self) -> dict:
        """{name: latest offset_s} over processes with >= 1 good pull."""
        out = {}
        for name, ring in self.rings.items():
            if ring:
                out[name] = ring[-1]["offset_s"]
        return out

    def snapshot(self) -> dict:
        """One JSON-able view: per-process latest pull + history depth +
        last error (the pod renderer's input)."""
        procs = {}
        for t in self.targets:
            name = t["name"]
            ring = self.rings[name]
            last = ring[-1] if ring else None
            procs[name] = {
                "host": t["host"], "port": t["port"],
                "pulls": len(ring),
                "last_error": self.errors[name],
                "offset_s": last["offset_s"] if last else None,
                "offset_bound_s": (last["offset_bound_s"]
                                   if last else None),
                "rtt_s": last["rtt_s"] if last else None,
                "events_tail_len": (len(last.get("events_tail") or [])
                                    if last else 0),
                "health": (last.get("health") if last else None),
            }
        return {"interval_s": self.interval_s, "processes": procs}

    def merged_timeline(self) -> list:
        """The clock-aligned merge of every process's latest events
        tail (see :func:`merge_process_events`)."""
        per_process = {}
        for name, ring in self.rings.items():
            if ring:
                per_process[name] = ring[-1].get("events_tail") or []
        return merge_process_events(per_process, self.offsets())
