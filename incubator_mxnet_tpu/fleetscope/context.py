"""W3C-traceparent-style trace context: mint, parse, propagate.

One request through the fleet is ONE trace. The Router mints (or
accepts from the client) a ``trace_id``, opens its own span, and
forwards a child context on the ``traceparent`` header of the proxied
``POST /predict``; the replica's ModelServer threads the arriving
context into its servescope request span. Every hop keeps the 128-bit
``trace_id`` and re-mints the 64-bit ``span_id``, so the offline join
(`tools/mxdiag.py trace`, `tools/serve_load.py`'s ``extra.fleetscope``)
can reassemble router admit → wire → replica queue_wait → coalesce →
device_exec → respond from per-process event logs alone.

Header format (the W3C trace-context wire form)::

    traceparent: 00-<32 hex trace_id>-<16 hex span_id>-<2 hex flags>

Parsing is strict and total: anything malformed returns ``None`` —
callers COUNT the malformation (``fleetscope.ctx_malformed``) and mint
a fresh trace, they never guess at a half-parsed id. All-zero ids are
malformed per the W3C spec (they mean "no trace")."""
from __future__ import annotations

import os
import re

__all__ = ["TraceContext", "mint", "parse", "mint_span_id",
           "TRACEPARENT_RE"]

# strict wire shape: version 00 only (the only version we emit; an
# unknown version is treated as malformed — counted, re-minted)
TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

_ZERO_TRACE = "0" * 32
_ZERO_SPAN = "0" * 16


def mint_span_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """One hop's view of a trace: the shared ``trace_id``, this hop's
    ``span_id``, and the upstream hop's ``parent_id`` (None at the
    root)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id, span_id, parent_id=None, sampled=True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    def child(self) -> "TraceContext":
        """A downstream hop: same trace, fresh span, this span as
        parent."""
        return TraceContext(self.trace_id, mint_span_id(),
                            parent_id=self.span_id, sampled=self.sampled)

    def header(self) -> str:
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    def __repr__(self):
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, parent_id={self.parent_id!r})")


def mint(sampled: bool = True) -> TraceContext:
    """A fresh root context (new 128-bit trace, new 64-bit span)."""
    return TraceContext(os.urandom(16).hex(), mint_span_id(),
                        parent_id=None, sampled=sampled)


def parse(header) -> TraceContext | None:
    """Strictly parse a ``traceparent`` header value.

    Returns None for anything that is not a well-formed, non-zero,
    version-00 traceparent — the caller counts and re-mints, never
    guesses. The parsed context's span becomes the *parent* view: the
    accepting hop should call :meth:`TraceContext.child` (or re-mint
    its own span) before emitting."""
    if not isinstance(header, str):
        return None
    m = TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id, flags = m.groups()
    if trace_id == _ZERO_TRACE or span_id == _ZERO_SPAN:
        return None
    # flags: only the sampled bit is defined; anything else is opaque
    sampled = bool(int(flags, 16) & 0x01)
    return TraceContext(trace_id, span_id, parent_id=None,
                        sampled=sampled)
