"""mxtpu.fleetscope — cross-process distributed tracing for the fleet.

The NINTH observability layer (docs/observability.md): the first eight
explain what ONE process does, but a served request now crosses a real
HTTP wire (fleet Router → replica ModelServer) and a training step
crosses M ranks — and no per-process scope can see the hop. Fleetscope
joins them, in three parts (docs/fleetscope.md):

* **trace-context propagation** (:mod:`.context`) — the Router mints
  (or accepts from the client) a W3C-traceparent ``trace_id``,
  forwards a child context on the proxied ``POST /predict``, and the
  replica threads it into its servescope request span and the
  ``serving.batch`` event — one request is ONE trace: router admit →
  wire → replica queue_wait → coalesce → device_exec → respond;
* **clock-aligned collection** (:mod:`.collector`) — a collector on
  the router (rank 0 uses the elastic TCP wire instead) periodically
  pulls each process's counters, ``mxtpu.events`` tail, and health
  flags over the existing ``diagnostics.export`` HTTP surface,
  estimating per-process clock offset from request/response midpoints
  (± rtt/2), into bounded per-process rings; events carry a ``mono``
  companion (``mxtpu.events/2``) so an NTP step can't reorder a
  process's own records in the merge;
* **merged views that get spent** — ``mxdiag.py trace <id>`` renders
  one request's cross-process span tree with the wire gap (router
  wall minus replica wall) explicit, ``mxdiag.py pod`` renders the
  per-replica aggregate with skew and straggler flags (report-only
  context for the router's least-loaded score), and
  ``tools/serve_load.py`` writes ``extra.fleetscope`` (trace-join
  rate, per-replica spread, wire-gap percentiles) into BENCH json,
  validated by ``tools/trace_check.py``.

Cost model (the house off-path discipline): off = ONE predicate —
every hot-path hook guards with ``if fleetscope._FS is not None:``;
nothing is parsed, minted, or emitted until :func:`enable` ran.
Malformed headers are counted (``fleetscope.ctx_malformed``) and
re-minted, never guessed. ``MXTPU_FLEETSCOPE=1`` arms at import.
"""
from __future__ import annotations

import os

from ..profiler.counters import counter as _counter
from . import collector as _collector_mod
from . import context as _context_mod
from .collector import (Collector, estimate_offset, events_tail,
                        join_traces, merge_process_events)
from .context import TraceContext, mint, mint_span_id, parse

__all__ = ["enable", "disable", "enabled", "enable_from_env",
           "TraceContext", "mint", "mint_span_id", "parse",
           "Collector", "estimate_offset", "events_tail",
           "merge_process_events", "join_traces",
           "context", "collector"]

# module re-exports under their documented names
context = _context_mod
collector = _collector_mod

# module global: None = fleetscope off (THE fast-path predicate; the
# router/server/batcher guard every hook with
# `if _fleetscope._FS is not None:`)
_FS = None


class _FleetScope:
    """Marker object holding enable-time state: the context accounting
    counters every hop shares (created once at arm time — accepting a
    header on the hot path is a parse + at most one increment)."""

    def __init__(self):
        self.c_minted = _counter("fleetscope.ctx_minted", "fleetscope")
        self.c_accepted = _counter("fleetscope.ctx_accepted",
                                   "fleetscope")
        self.c_malformed = _counter("fleetscope.ctx_malformed",
                                    "fleetscope")
        self.c_propagated = _counter("fleetscope.ctx_propagated",
                                     "fleetscope")

    def accept(self, header, mint_on_missing: bool = True):
        """The one entry point a hop uses on an incoming request.

        * well-formed header → accepted context (counted);
        * malformed header → counted ``ctx_malformed``, then a FRESH
          trace is minted when ``mint_on_missing`` (the root hop) or
          None is returned (a mid-trace hop must not invent a root);
        * absent header → minted (root hop) or None (mid-trace hop).

        Returned contexts are the UPSTREAM view: callers derive their
        own span via :meth:`TraceContext.child` before emitting."""
        if header is not None:
            ctx = parse(header)
            if ctx is not None:
                self.c_accepted.increment()
                return ctx
            self.c_malformed.increment()
        if mint_on_missing:
            self.c_minted.increment()
            return mint()
        return None


def enable():
    """Arm cross-process tracing. Idempotent: re-enabling keeps the
    registry counters (they are process-lifetime accounting, not a
    window)."""
    global _FS
    if _FS is None:
        _FS = _FleetScope()
    return _FS


def disable():
    global _FS
    _FS = None


def enabled() -> bool:
    return _FS is not None


def enable_from_env():
    """MXTPU_FLEETSCOPE=1 arms fleetscope at import (like
    MXTPU_SERVESCOPE / MXTPU_DEVICESCOPE)."""
    if os.environ.get("MXTPU_FLEETSCOPE", "") == "1":
        enable()


enable_from_env()
