"""Network visualization (parity: python/mxnet/visualization.py).

print_summary walks a Symbol graph and prints the reference's layer table
(name, output shape, params, previous layers). plot_network returns a
Digraph-like object carrying the network in DOT form (`.source`,
`.save('net.dot')`); only `.render()` — which needs the graphviz binary
absent from this image — raises, with instructions.
"""
from __future__ import annotations

import numpy as np

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary of a Symbol graph (parity:
    mx.viz.print_summary). `shape`: dict of input name -> shape, needed for
    per-layer output shapes and param counts."""
    from .symbol import Symbol
    if not isinstance(symbol, Symbol):
        raise TypeError("print_summary expects a Symbol")
    shape = shape or {}
    shapes = {}
    if shape:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        for name, s in zip(symbol.list_arguments(), arg_shapes):
            shapes[name] = s

    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields):
        line = ""
        for i, f in enumerate(fields):
            line += str(f)
            line = line[:positions[i] - 1].ljust(positions[i])
        print(line)

    print("=" * line_length)
    print_row(headers)
    print("=" * line_length)

    from .symbol import _topo
    nodes = _topo(symbol._entries)

    total_params = 0
    # output shapes per node, via eval_shape on the whole graph
    out_shape_by_name = {}
    if shape:
        try:
            for name, s in zip(symbol.list_outputs(), out_shapes):
                out_shape_by_name[name] = s
        except Exception:
            pass

    for node in nodes:
        if node.op is None:
            continue  # variables are inputs, not layers
        prevs = []
        n_params = 0
        for (pnode, _pi) in node.inputs:
            if pnode.op is None and pnode.name not in shape \
                    and shapes.get(pnode.name) is not None:
                # a learned argument (weight/bias/...), not a data input
                n_params += int(np.prod(shapes[pnode.name]))
            else:
                prevs.append(pnode.name)
        total_params += n_params
        oshape = out_shape_by_name.get(node.name + "_output", "")
        print_row([f"{node.name} ({node.op})", oshape, n_params,
                   ", ".join(prevs[:3])])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("=" * line_length)
    return total_params


class _Digraph:
    """Minimal graphviz.Digraph stand-in: collects nodes/edges and renders
    DOT source. The python `graphviz` package is not in this image, so
    plot_network returns this instead — `.source` is valid DOT (feed it to
    an external `dot -Tpdf`), `.save(path)` writes the .dot file, and
    `.render()` explains what is unavailable rather than failing silently."""

    def __init__(self, title):
        self.title = title
        self._lines = []

    @staticmethod
    def _q(s):
        """DOT double-quoted string: escape backslashes and quotes (but
        keep \\n, the DOT line-break escape labels rely on)."""
        s = str(s).replace("\\", "\\\\").replace('"', '\\"')
        return s.replace("\\\\n", "\\n")

    def node(self, name, label, **attrs):
        a = ", ".join([f'label="{self._q(label)}"'] +
                      [f'{k}="{self._q(v)}"'
                       for k, v in sorted(attrs.items())])
        self._lines.append(f'  "{self._q(name)}" [{a}];')

    def edge(self, src, dst, label=None):
        suffix = f' [label="{self._q(label)}"]' if label else ""
        self._lines.append(f'  "{self._q(src)}" -> "{self._q(dst)}"'
                           f'{suffix};')

    @property
    def source(self):
        return (f'digraph "{self._q(self.title)}" {{\n'
                "  rankdir=BT;\n" + "\n".join(self._lines) + "\n}\n")

    def save(self, filename):
        with open(filename, "w") as f:
            f.write(self.source)
        return filename

    def render(self, *a, **kw):
        raise ImportError(
            "rendering needs the graphviz binary, which is not in this "
            "image; use .source / .save('net.dot') and run "
            "`dot -Tpdf net.dot` elsewhere")

    def _repr_mimebundle_(self, *a, **kw):   # notebook display: show DOT
        return {"text/plain": self.source}


_NODE_COLORS = {
    "Convolution": "royalblue1", "Deconvolution": "royalblue3",
    "FullyConnected": "brown3", "Activation": "salmon",
    "BatchNorm": "orchid1", "Pooling": "firebrick", "Flatten": "gold",
    "Reshape": "gold", "Concat": "seagreen1", "softmax": "yellow",
    "SoftmaxOutput": "yellow",
}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Parity: mx.viz.plot_network (python/mxnet/visualization.py).
    Returns a Digraph-like object whose `.source` is the network in DOT
    form (same node shapes/colors scheme as the reference); the graphviz
    renderer is not in this image, so `.render()` raises with
    instructions while `.save()` writes the .dot file."""
    from .symbol import Symbol, _topo
    if not isinstance(symbol, Symbol):
        raise TypeError("plot_network expects a Symbol")
    shapes = {}
    if shape:
        arg_shapes, _, _ = symbol.infer_shape(**shape)
        shapes = dict(zip(symbol.list_arguments(), arg_shapes))
    node_attrs = dict(node_attrs or {})   # merged into every node, like
    g = _Digraph(title)                   # the reference
    order = _topo(symbol._entries)
    def is_weight(n):
        return n.is_var and (n.name.endswith(("_weight", "_bias", "_gamma",
                                              "_beta", "_moving_mean",
                                              "_moving_var")))
    keep = {id(n) for n in order
            if not (hide_weights and is_weight(n))}
    for n in order:
        if id(n) not in keep:
            continue
        if n.is_var:
            label = n.name
            if n.name in shapes:
                label += f"\\n{tuple(shapes[n.name])}"
            g.node(n.name, label, **{"shape": "oval",
                                     "fillcolor": "lightblue",
                                     "style": "filled", **node_attrs})
        else:
            color = _NODE_COLORS.get(n.op, "olivedrab1")
            g.node(n.name, f"{n.name}\\n({n.op})",
                   **{"shape": "box", "fillcolor": color,
                      "style": "filled", **node_attrs})
        for m, _i in n.inputs:
            if id(m) in keep:
                g.edge(m.name, n.name)
    return g
