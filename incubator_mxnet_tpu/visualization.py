"""Network visualization (parity: python/mxnet/visualization.py).

print_summary walks a Symbol graph and prints the reference's layer table
(name, output shape, params, previous layers). plot_network requires
graphviz, which is not in this image — it raises with instructions, rather
than silently producing nothing.
"""
from __future__ import annotations

import numpy as np

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary of a Symbol graph (parity:
    mx.viz.print_summary). `shape`: dict of input name -> shape, needed for
    per-layer output shapes and param counts."""
    from .symbol import Symbol
    if not isinstance(symbol, Symbol):
        raise TypeError("print_summary expects a Symbol")
    shape = shape or {}
    shapes = {}
    if shape:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        for name, s in zip(symbol.list_arguments(), arg_shapes):
            shapes[name] = s

    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields):
        line = ""
        for i, f in enumerate(fields):
            line += str(f)
            line = line[:positions[i] - 1].ljust(positions[i])
        print(line)

    print("=" * line_length)
    print_row(headers)
    print("=" * line_length)

    from .symbol import _topo
    nodes = _topo(symbol._entries)

    total_params = 0
    # output shapes per node, via eval_shape on the whole graph
    out_shape_by_name = {}
    if shape:
        try:
            for name, s in zip(symbol.list_outputs(), out_shapes):
                out_shape_by_name[name] = s
        except Exception:
            pass

    for node in nodes:
        if node.op is None:
            continue  # variables are inputs, not layers
        prevs = []
        n_params = 0
        for (pnode, _pi) in node.inputs:
            if pnode.op is None and pnode.name not in shape \
                    and shapes.get(pnode.name) is not None:
                # a learned argument (weight/bias/...), not a data input
                n_params += int(np.prod(shapes[pnode.name]))
            else:
                prevs.append(pnode.name)
        total_params += n_params
        oshape = out_shape_by_name.get(node.name + "_output", "")
        print_row([f"{node.name} ({node.op})", oshape, n_params,
                   ", ".join(prevs[:3])])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("=" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    raise ImportError(
        "plot_network needs graphviz, which is not available in this "
        "image; use print_summary(symbol, shape) for a text summary")
