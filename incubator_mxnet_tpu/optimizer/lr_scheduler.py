"""LR schedulers (parity: python/mxnet/lr_scheduler.py).

Every stock scheduler additionally exposes :meth:`LRScheduler.as_jax` — a
PURE jax-traceable closed form of the schedule, ``fn(t) -> lr`` over a
traced ``num_update``. The whole-loop executor (mxtpu.trainloop) compiles
it INSIDE the train program so each micro-step of a k-step chunk sees its
own exact lr without a host round trip; custom subclasses that don't
override ``as_jax`` fall back to a host-computed per-micro-step lr table
(still step-exact, just not host-free). The closed form is evaluated
against the scheduler's CURRENT state, so stateful schedulers
(Factor/MultiFactor) hand off mid-run correctly as long as ``t`` keeps
moving forward — the same contract the stateful host path has.
"""
from __future__ import annotations

import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "LinearScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0.0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        assert num_update < self.warmup_steps
        if self.warmup_mode == "linear":
            inc = ((self.warmup_final_lr - self.warmup_begin_lr) *
                   num_update / self.warmup_steps)
            return self.warmup_begin_lr + inc
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        raise ValueError(self.warmup_mode)

    def _jax_warmup(self, t, main_lr):
        """Wrap a traced main-schedule lr with the warmup ramp (pure
        analogue of get_warmup_lr; f32 math like the host path)."""
        import jax.numpy as jnp
        if not self.warmup_steps:
            return main_lr
        if self.warmup_mode == "linear":
            w = (self.warmup_begin_lr
                 + (self.warmup_final_lr - self.warmup_begin_lr)
                 * t / self.warmup_steps)
        else:                                  # constant
            w = jnp.full_like(main_lr, self.warmup_begin_lr)
        return jnp.where(t < self.warmup_steps, w, main_lr)

    def as_jax(self):
        """Pure traceable form ``fn(t) -> lr`` (t = traced num_update),
        or None when this scheduler has no closed form (custom
        subclasses): callers then fall back to host-side per-step
        sampling."""
        return None

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8, base_lr=0.01,
                 **kwargs):
        super().__init__(base_lr, **kwargs)
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr = max(self.base_lr * self.factor, self.stop_factor_lr)
        return self.base_lr

    def as_jax(self):
        import jax.numpy as jnp
        # closed form relative to the CURRENT state: the host loop drops
        # once per crossed `step` boundary, i.e. floor((u-1)/step) total
        # drops, of which count/step already happened
        base, factor = float(self.base_lr), float(self.factor)
        stop, step = float(self.stop_factor_lr), int(self.step)
        done = self.count // step

        def fn(t):
            t = jnp.asarray(t, jnp.float32)
            drops = jnp.maximum(jnp.floor((t - 1.0) / step) - done, 0.0)
            lr = jnp.maximum(base * factor ** drops, stop)
            return self._jax_warmup(t, lr.astype(jnp.float32))
        return fn


class MultiFactorScheduler(LRScheduler):
    def __init__(self, step, factor=1.0, base_lr=0.01, **kwargs):
        super().__init__(base_lr, **kwargs)
        assert list(step) == sorted(step)
        self.step = list(step)
        self.factor = factor
        self.cur_step_ind = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while (self.cur_step_ind < len(self.step)
               and num_update > self.step[self.cur_step_ind]):
            self.base_lr *= self.factor
            self.cur_step_ind += 1
        return self.base_lr

    def as_jax(self):
        import jax.numpy as jnp
        base, factor = float(self.base_lr), float(self.factor)
        remaining = jnp.asarray(self.step[self.cur_step_ind:],
                                jnp.float32)

        def fn(t):
            t = jnp.asarray(t, jnp.float32)
            drops = (jnp.sum(t > remaining) if remaining.size
                     else jnp.float32(0.0))
            lr = base * factor ** drops.astype(jnp.float32)
            return self._jax_warmup(t, lr.astype(jnp.float32))
        return fn


class PolyScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0.0, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.power = pwr
        self.final_lr = final_lr
        self.max_steps = max_update - self.warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update >= self.max_update:
            return self.final_lr
        frac = (num_update - self.warmup_steps) / self.max_steps
        return self.final_lr + (self.base_lr - self.final_lr) * (1 - frac) ** self.power

    def as_jax(self):
        import jax.numpy as jnp
        base, final = float(self.base_lr), float(self.final_lr)
        power, w = float(self.power), int(self.warmup_steps)
        max_update, max_steps = int(self.max_update), int(self.max_steps)

        def fn(t):
            t = jnp.asarray(t, jnp.float32)
            frac = (t - w) / max_steps
            lr = final + (base - final) * jnp.maximum(1.0 - frac, 0.0) ** power
            lr = jnp.where(t >= max_update, final, lr)
            return self._jax_warmup(t, lr.astype(jnp.float32))
        return fn


class CosineScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, final_lr=0.0, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - self.warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update >= self.max_update:
            return self.final_lr
        frac = (num_update - self.warmup_steps) / self.max_steps
        return (self.final_lr + (self.base_lr - self.final_lr) *
                (1 + math.cos(math.pi * frac)) / 2)

    def as_jax(self):
        import jax.numpy as jnp
        base, final = float(self.base_lr), float(self.final_lr)
        w, max_update = int(self.warmup_steps), int(self.max_update)
        max_steps = int(self.max_steps)

        def fn(t):
            t = jnp.asarray(t, jnp.float32)
            frac = (t - w) / max_steps
            lr = final + (base - final) * (1.0 + jnp.cos(math.pi * frac)) / 2.0
            lr = jnp.where(t >= max_update, final, lr)
            return self._jax_warmup(t, lr.astype(jnp.float32))
        return fn


class LinearScheduler(PolyScheduler):
    def __init__(self, max_update, base_lr=0.01, final_lr=0.0, **kwargs):
        super().__init__(max_update, base_lr, pwr=1, final_lr=final_lr, **kwargs)
