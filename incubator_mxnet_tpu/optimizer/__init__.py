"""Optimizers (parity: python/mxnet/optimizer/optimizer.py).

Each optimizer defines a PURE update rule `_update(weight, grad, state,
lr, wd, mult) -> (new_weight, new_state)` over raw jax arrays. The eager
Trainer path jit-compiles the rule per (shape, dtype) — XLA fuses the whole
update into one kernel — and the fused train-step path (parallel/) inlines
the same rule inside the global jit. Multi-precision: `multi_precision=True`
keeps a float32 master copy for bf16/fp16 weights, like the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import profiler as _prof
from ..base import _Registry
from ..ndarray import NDArray
from . import lr_scheduler  # noqa: F401

registry = _Registry("optimizer")
register = registry.register


def create(name, **kwargs):
    return registry.create(name, **kwargs)


class Optimizer:
    def __init__(self, learning_rate=0.01, wd=0.0, rescale_grad=1.0,
                 clip_gradient=None, lr_scheduler=None, multi_precision=False,
                 param_dict=None, begin_num_update=0):
        self.lr = learning_rate
        self.wd = wd
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            # reference python/mxnet/optimizer/optimizer.py: the
            # optimizer's learning_rate becomes the scheduler's base_lr.
            # Reference quirk carried over verbatim: warmup_final_lr keeps
            # the value captured at scheduler construction, so a warmup
            # ramp targets the scheduler's ORIGINAL base_lr — pass a
            # matching learning_rate/base_lr pair when using warmup, as
            # reference users must.
            lr_scheduler.base_lr = learning_rate
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.param_dict = param_dict or {}
        self._jit_cache = {}

    # -- hyper access -----------------------------------------------------
    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return float(self.lr_scheduler(self.num_update))
        return self.lr

    def set_learning_rate(self, lr):
        self.lr = lr

    def _get_lr_wd(self, index):
        lr, wd = self.learning_rate, self.wd
        p = self.param_dict.get(index)
        if p is not None:
            lr *= p.lr_mult
            wd *= p.wd_mult
        return lr, wd

    def _update_count(self, index):
        self._index_update_count[index] = self._index_update_count.get(index, 0) + 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    # -- state ------------------------------------------------------------
    def create_state(self, index, weight_raw):
        return ()

    def create_state_multi_precision(self, index, weight_raw):
        if self.multi_precision and weight_raw.dtype in (jnp.float16, jnp.bfloat16):
            master = weight_raw.astype(jnp.float32)
            return (master,) + tuple(self.create_state(index, master))
        return self.create_state(index, weight_raw)

    # -- pure rule (subclasses implement) ---------------------------------
    def _update(self, weight, grad, state, lr, wd, t):
        raise NotImplementedError

    def update_step(self, weight, grad, state, lr, wd, t, rescale=None,
                    clip=None, skip=None):
        """Pure entry incl. rescale/clip/multi-precision — safe inside jit.
        rescale/clip are runtime args so a jitted wrapper must pass them as
        tracers (Trainer.step changes rescale_grad with the batch size).
        `skip` is an on-device bool (AMP found-inf): when True the update is
        a select back to the old weight/state — the step stays one
        unconditionally-dispatched XLA computation, no host branch."""
        rescale = self.rescale_grad if rescale is None else rescale
        grad = grad.astype(jnp.float32) * rescale
        clip = self.clip_gradient if clip is None else clip
        if clip is not None:
            grad = jnp.clip(grad, -clip, clip)
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master, inner = state[0], state[1:]
            new_master, new_inner = self._update(master, grad, inner, lr, wd, t)
            new_w, new_state = (new_master.astype(weight.dtype),
                                (new_master,) + tuple(new_inner))
        else:
            new_w, new_state = self._update(weight.astype(jnp.float32), grad,
                                            state, lr, wd, t)
            new_w = new_w.astype(weight.dtype)
        if skip is not None:
            new_w = jnp.where(skip, weight, new_w)
            new_state = jax.tree_util.tree_map(
                lambda ns, os: jnp.where(skip, os, ns), new_state, state)
        return new_w, new_state

    # -- eager path (Trainer / KVStore server-side update) ----------------
    def update(self, index, weight: NDArray, grad: NDArray, state, skip=None):
        from ..ndarray import sparse as _sparse
        if isinstance(grad, _sparse.RowSparseNDArray):
            return self._update_sparse(index, weight, grad, state, skip=skip)
        self._update_count(index)
        lr, wd = self._get_lr_wd(index)
        t = self._index_update_count[index]
        has_clip = self.clip_gradient is not None
        has_skip = skip is not None
        key = (weight.shape, str(weight._data.dtype), bool(self.multi_precision),
               has_clip, has_skip)
        fn = self._jit_cache.get(key)
        if fn is None:
            # None for cl_/sk_ is pytree-static, so one jitted impl covers
            # all four arities; the cache key pins the chosen arity.
            fn = jax.jit(lambda w, g, s, lr_, wd_, t_, rs_, cl_=None, sk_=None:
                         self.update_step(w, g, s, lr_, wd_, t_, rs_, cl_, sk_))
            self._jit_cache[key] = fn
        cl = jnp.float32(self.clip_gradient) if has_clip else None
        new_w, new_state = fn(weight._data, grad._data, state,
                              jnp.float32(lr), jnp.float32(wd), jnp.int32(t),
                              jnp.float32(self.rescale_grad), cl, skip)
        weight._data = new_w
        return new_state

    def update_multi_precision(self, index, weight, grad, state):
        return self.update(index, weight, grad, state)

    # -- fused multi-tensor apply (Trainer fused_update=True) -------------
    def supports_fused(self) -> bool:
        """Dense rules whose eager `update` is the stock jitted wrapper can
        run N parameters in ONE compiled call. Rules that override the
        eager entry (SGLD's per-call host RNG) keep the per-param path."""
        return (type(self).update is Optimizer.update
                and type(self).update_multi_precision
                is Optimizer.update_multi_precision)

    def fused_update(self, indices, weights, grads, states, skip=None):
        """Multi-tensor apply: every (index, weight, grad, state) in the
        group updates inside ONE jit-compiled XLA computation (the
        multi_tensor_apply / LazyTensor-fusion lineage), with weight and
        optimizer-state buffers DONATED on accelerators so the update is
        in-place at the XLA level. Per-param bookkeeping (update counts,
        lr/wd multipliers, per-param t) matches the eager `update` exactly;
        results are bit-identical to calling `update` per parameter.

        Caller contract: dense grads only (route RowSparse through
        `update`), and all weights share a dtype (the Trainer groups by
        (rule, dtype)). Returns the list of new states; weights are
        updated in place. Donation caveat: on TPU/GPU the previous weight
        and state buffers are invalidated by the call — stale NDArray
        references to pre-update weights must not be read afterwards."""
        for i in indices:
            self._update_count(i)
        lws = [self._get_lr_wd(i) for i in indices]
        ts = [self._index_update_count[i] for i in indices]
        has_clip = self.clip_gradient is not None
        has_skip = skip is not None
        key = ("fused",
               tuple((w.shape, str(w._data.dtype)) for w in weights),
               bool(self.multi_precision), has_clip, has_skip)
        fn = self._jit_cache.get(key)
        if fn is None:
            n = len(indices)

            def fused_step(ws, gs, ss, lr_, wd_, t_, rs_, cl_, sk_):
                new_ws, new_ss = [], []
                for j in range(n):
                    nw, ns = self.update_step(ws[j], gs[j], ss[j], lr_[j],
                                              wd_[j], t_[j], rs_, cl_, sk_)
                    new_ws.append(nw)
                    new_ss.append(ns)
                return new_ws, new_ss

            # donate weight+state buffers where XLA implements donation
            # (grads are NOT donated: grad_req='add' re-reads them)
            donate = ((0, 2) if jax.default_backend() in ("tpu", "gpu")
                      else ())
            fn = jax.jit(fused_step, donate_argnums=donate)
            self._jit_cache[key] = fn
            _prof.counter("jit.cache_miss", "optimizer").increment()
        else:
            _prof.counter("jit.cache_hit", "optimizer").increment()
        cl = jnp.float32(self.clip_gradient) if has_clip else None
        new_ws, new_ss = fn(
            [w._data for w in weights], [g._data for g in grads],
            list(states),
            [jnp.float32(lr) for lr, _ in lws],
            [jnp.float32(wd) for _, wd in lws],
            [jnp.int32(t) for t in ts],
            jnp.float32(self.rescale_grad), cl, skip)
        for w, nw in zip(weights, new_ws):
            w._data = nw
        return list(new_ss)

    def _update_sparse(self, index, weight, grad, state, skip=None):
        """RowSparse gradient. Optimizers with no lazy rule densify — the
        mathematically exact fallback (parity: reference optimizers without
        a sparse kernel do the same via FallBackStorageType). SGD overrides
        with the true lazy row update."""
        return self.update(index, weight, grad.todense(), state, skip=skip)


@register("sgd")
class SGD(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=True,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight_raw):
        if self.momentum != 0.0:
            return (jnp.zeros(weight_raw.shape, jnp.float32),)
        return ()

    def _update(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.momentum != 0.0:
            (mom,) = state
            mom = self.momentum * mom - lr * g
            return w + mom, (mom,)
        return w - lr * g, ()

    def _update_sparse(self, index, weight, grad, state, skip=None):
        """Lazy row update (parity: sgd_update w/ lazy_update=True,
        src/operator/optimizer_op.cc): only the rows present in the
        RowSparse gradient touch weight/momentum — one gather + scatter,
        jit-cached per (shape, nnz). `skip` (AMP found-inf) selects the old
        rows back inside the same computation."""
        if (not self.lazy_update
                or (self.multi_precision
                    and weight._data.dtype in (jnp.float16, jnp.bfloat16))):
            return super()._update_sparse(index, weight, grad, state,
                                          skip=skip)
        self._update_count(index)
        lr, wd = self._get_lr_wd(index)
        has_mom = self.momentum != 0.0
        has_clip = self.clip_gradient is not None
        has_skip = skip is not None
        key = ("rsp", weight.shape, str(weight._data.dtype), int(grad.nnz),
               has_mom, has_clip, has_skip)
        fn = self._jit_cache.get(key)
        if fn is None:
            momentum = self.momentum

            def sparse_step(w, mom, rows, g, lr_, wd_, rs_, cl_, sk_):
                g32 = g.astype(jnp.float32) * rs_
                if cl_ is not None:
                    g32 = jnp.clip(g32, -cl_, cl_)
                w_rows = jnp.take(w, rows, axis=0).astype(jnp.float32)
                g32 = g32 + wd_ * w_rows
                if mom is not None:
                    m_rows = jnp.take(mom, rows, axis=0)
                    new_m_rows = momentum * m_rows - lr_ * g32
                    new_rows = w_rows + new_m_rows
                    if sk_ is not None:
                        new_m_rows = jnp.where(sk_, m_rows, new_m_rows)
                    mom = mom.at[rows].set(new_m_rows)
                else:
                    new_rows = w_rows - lr_ * g32
                if sk_ is not None:
                    new_rows = jnp.where(sk_, w_rows, new_rows)
                w = w.at[rows].set(new_rows.astype(w.dtype))
                return w, mom

            fn = jax.jit(sparse_step)
            self._jit_cache[key] = fn
        mom = state[0] if has_mom else None
        cl = jnp.float32(self.clip_gradient) if has_clip else None
        new_w, new_mom = fn(weight._data, mom,
                            grad.indices.astype(jnp.int32), grad._data,
                            jnp.float32(lr), jnp.float32(wd),
                            jnp.float32(self.rescale_grad), cl, skip)
        weight._data = new_w
        return (new_mom,) if has_mom else ()


@register("nag")
class NAG(SGD):
    """Nesterov accelerated SGD (parity: mx.optimizer.NAG)."""

    def _update_sparse(self, index, weight, grad, state, skip=None):
        # SGD's hand-written lazy sparse_step hardcodes plain-momentum
        # math; NAG must densify through its own _update rule instead
        return Optimizer._update_sparse(self, index, weight, grad, state,
                                        skip=skip)

    def _update(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.momentum != 0.0:
            (mom,) = state
            mom = self.momentum * mom - lr * g
            return w + self.momentum * mom - lr * g, (mom,)
        return w - lr * g, ()


@register("sgld")
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity: mx.optimizer.SGLD)."""

    def update(self, index, weight, grad, state, skip=None):
        # bypass the jit cache: a traced PRNG key would freeze the noise
        from ..ndarray import random as ndrandom
        from ..ndarray import sparse as _sparse
        if isinstance(grad, _sparse.RowSparseNDArray):
            grad = grad.todense()
        self._update_count(index)
        lr, wd = self._get_lr_wd(index)
        g = grad._data.astype(jnp.float32) * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data.astype(jnp.float32)
        noise = jax.random.normal(ndrandom._key(), weight.shape, jnp.float32)
        new_w = weight._data.astype(jnp.float32) - lr / 2 * g + jnp.sqrt(lr) * noise
        new_w = new_w.astype(weight._data.dtype)
        if skip is not None:
            new_w = jnp.where(skip, weight._data, new_w)
        weight._data = new_w
        return state


@register("signum")
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight_raw):
        if self.momentum != 0.0:
            return (jnp.zeros(weight_raw.shape, jnp.float32),)
        return ()

    def _update(self, w, g, state, lr, wd, t):
        if self.momentum != 0.0:
            (mom,) = state
            mom = self.momentum * mom + (1 - self.momentum) * (g + wd * w)
            step = jnp.sign(mom)
            new_w = w * (1 - lr * self.wd_lh) - lr * step
            return new_w, (mom,)
        return w - lr * jnp.sign(g + wd * w), ()


@register("adam")
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight_raw):
        # fresh buffers (aliased states break XLA buffer donation)
        return (jnp.zeros(weight_raw.shape, jnp.float32),
                jnp.zeros(weight_raw.shape, jnp.float32))

    def _update(self, w, g, state, lr, wd, t):
        m, v = state
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        vhat = v / (1 - self.beta2 ** tf)
        return w - lr * mhat / (jnp.sqrt(vhat) + self.epsilon), (m, v)


@register("adamw")
class AdamW(Adam):
    """Decoupled weight decay (used by BERT; parity: contrib BERTAdam/AdamW)."""

    def _update(self, w, g, state, lr, wd, t):
        m, v = state
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** tf)
        vhat = v / (1 - self.beta2 ** tf)
        return w - lr * (mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w), (m, v)


@register("adagrad")
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight_raw):
        return (jnp.zeros(weight_raw.shape, jnp.float32),)

    def _update(self, w, g, state, lr, wd, t):
        (hist,) = state
        g = g + wd * w
        hist = hist + jnp.square(g)
        return w - lr * g / (jnp.sqrt(hist) + self.float_stable_eps), (hist,)


@register("adadelta")
class AdaDelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight_raw):
        # fresh buffers (aliased states break XLA buffer donation)
        return (jnp.zeros(weight_raw.shape, jnp.float32),
                jnp.zeros(weight_raw.shape, jnp.float32))

    def _update(self, w, g, state, lr, wd, t):
        acc_g, acc_d = state
        g = g + wd * w
        acc_g = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_d + self.epsilon) / jnp.sqrt(acc_g + self.epsilon) * g
        acc_d = self.rho * acc_d + (1 - self.rho) * jnp.square(delta)
        return w - lr * delta, (acc_g, acc_d)


@register("rmsprop")
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered

    def create_state(self, index, weight_raw):
        if self.centered:
            return tuple(jnp.zeros(weight_raw.shape, jnp.float32) for _ in range(3))
        return (jnp.zeros(weight_raw.shape, jnp.float32),)

    def _update(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.centered:
            n, mg, delta = state
            n = self.gamma1 * n + (1 - self.gamma1) * jnp.square(g)
            mg = self.gamma1 * mg + (1 - self.gamma1) * g
            delta = (self.gamma2 * delta -
                     lr * g / jnp.sqrt(n - jnp.square(mg) + self.epsilon))
            return w + delta, (n, mg, delta)
        (n,) = state
        n = self.gamma1 * n + (1 - self.gamma1) * jnp.square(g)
        return w - lr * g / (jnp.sqrt(n) + self.epsilon), (n,)


@register("ftrl")
class Ftrl(Optimizer):
    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight_raw):
        # fresh buffers (aliased states break XLA buffer donation)
        return (jnp.zeros(weight_raw.shape, jnp.float32),
                jnp.zeros(weight_raw.shape, jnp.float32))

    def _update(self, w, g, state, lr, wd, t):
        z, n = state
        g = g + wd * w
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + jnp.square(g)
        new_w = jnp.where(
            jnp.abs(z) <= self.lamda1,
            jnp.zeros_like(w),
            -(z - jnp.sign(z) * self.lamda1) / ((self.beta + jnp.sqrt(n)) / lr))
        return new_w, (z, n)


@register("lamb")
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (BERT pretraining;
    parity: mx.optimizer.LAMB)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight_raw):
        # fresh buffers (aliased states break XLA buffer donation)
        return (jnp.zeros(weight_raw.shape, jnp.float32),
                jnp.zeros(weight_raw.shape, jnp.float32))

    def _update(self, w, g, state, lr, wd, t):
        m, v = state
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        if self.bias_correction:
            tf = t.astype(jnp.float32)
            mhat = m / (1 - self.beta1 ** tf)
            vhat = v / (1 - self.beta2 ** tf)
        else:
            mhat, vhat = m, v
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w
        w_norm = jnp.linalg.norm(w)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        if self.lower_bound is not None:
            ratio = jnp.maximum(ratio, self.lower_bound)
        if self.upper_bound is not None:
            ratio = jnp.minimum(ratio, self.upper_bound)
        return w - lr * ratio * r, (m, v)


@register("dcasgd")
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: mx.optimizer.DCASGD)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight_raw):
        # fresh buffers (aliased states break XLA buffer donation)
        return (jnp.zeros(weight_raw.shape, jnp.float32),
                jnp.zeros(weight_raw.shape, jnp.float32))  # (momentum, previous_weight)

    def _update(self, w, g, state, lr, wd, t):
        mom, prev_w = state
        g = g + wd * w
        comp = g + self.lamda * g * g * (w - prev_w)
        mom = self.momentum * mom - lr * comp
        return w + mom, (mom, w)


@register("adamax")
class Adamax(Optimizer):
    """Adam with an infinity-norm second moment (parity:
    mx.optimizer.Adamax / AdaMax paper §7.1)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight_raw):
        return (jnp.zeros(weight_raw.shape, jnp.float32),
                jnp.zeros(weight_raw.shape, jnp.float32))  # (m, u)

    def _update(self, w, g, state, lr, wd, t):
        m, u = state
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        tf = t.astype(jnp.float32)
        lr_t = lr / (1 - self.beta1 ** tf)
        return w - lr_t * m / (u + self.epsilon), (m, u)


@register("nadam")
class Nadam(Optimizer):
    """Adam with Nesterov momentum and the warming momentum schedule
    (parity: mx.optimizer.Nadam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight_raw):
        return (jnp.zeros(weight_raw.shape, jnp.float32),   # m
                jnp.zeros(weight_raw.shape, jnp.float32),   # v
                jnp.ones((), jnp.float32))                  # m_schedule

    def _update(self, w, g, state, lr, wd, t):
        m, v, m_schedule = state
        g = g + wd * w
        tf = t.astype(jnp.float32)
        mom_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (tf * self.schedule_decay))
        mom_t1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((tf + 1)
                                                    * self.schedule_decay))
        m_schedule = m_schedule * mom_t
        m_schedule_next = m_schedule * mom_t1
        g_prime = g / (1.0 - m_schedule)
        m = self.beta1 * m + (1 - self.beta1) * g
        m_prime = m / (1.0 - m_schedule_next)
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        v_prime = v / (1.0 - self.beta2 ** tf)
        m_bar = (1.0 - mom_t) * g_prime + mom_t1 * m_prime
        return (w - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon),
                (m, v, m_schedule))


@register("ftml")
class FTML(Optimizer):
    """Follow the Moving Leader (parity: mx.optimizer.FTML /
    src/operator/optimizer_op ftml_update)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight_raw):
        return (jnp.zeros(weight_raw.shape, jnp.float32),   # d
                jnp.zeros(weight_raw.shape, jnp.float32),   # v
                jnp.zeros(weight_raw.shape, jnp.float32))   # z

    def _update(self, w, g, state, lr, wd, t):
        d, v, z = state
        g = g + wd * w
        tf = t.astype(jnp.float32)
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        d_t = (1 - self.beta1 ** tf) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** tf)) + self.epsilon)
        sigma = d_t - self.beta1 * d
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * w
        return -z / d_t, (d_t, v, z)


@register("lars")
class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling: per-tensor trust ratio
    eta*||w||/(||g|| + wd*||w||) scales the SGD-momentum step (parity:
    mx.contrib LARS optimizer; the large-batch companion of LAMB)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, eta=0.001,
                 epsilon=1e-9, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight_raw):
        return (jnp.zeros(weight_raw.shape, jnp.float32),)

    def _update(self, w, g, state, lr, wd, t):
        (mom,) = state
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon),
            1.0)
        g = g + wd * w
        mom = self.momentum * mom + trust * lr * g
        return w - mom, (mom,)
