"""DynamicBatcher — request coalescing under a latency/size policy.

The Clipper/ORCA dynamic-batching pattern rebuilt over FrozenModel's
bucketed executables: single-sample requests enter a bounded thread-safe
queue; one dispatcher thread coalesces whatever is waiting into the
smallest compiled bucket that fits, bounded by

* ``max_batch``    — never batch more than this many requests, and
* ``max_delay_ms`` — never hold the FIRST request of a batch longer than
  this before dispatching (the tail-latency knob).

Admission control is explicit and total — a request is never silently
dropped:

* **validation** at submit: shape/dtype mismatch and
  larger-than-largest-bucket inputs raise :class:`InvalidInputError`
  immediately (client error, nothing enqueued);
* **backpressure** at submit: a full queue raises
  :class:`QueueFullError` (fail-fast, the Clipper deadline-aware
  shedding move) instead of stacking unbounded latency;
* **deadlines**: each request carries `enqueue time + timeout`; the
  dispatcher rejects expired requests with
  :class:`DeadlineExceededError` *before* spending device time on them,
  and the waiting client is woken with that error;
* **drain**: ``stop(drain=True)`` stops admissions
  (:class:`ServerClosedError`) but completes every request already
  accepted before the dispatcher exits.

Telemetry (always-on, through ``profiler.counters`` so the diagnostics
sampler/flight recorder see serving traffic for free): request/response/
reject counters, batch count + coalesced-size counter (their ratio is
the batch-fill), a queue-depth gauge, and `serving.latency_ms` /
`serving.batch_exec_ms` histograms.
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as np

from .. import fleetscope as _fs
from .. import profiler as _prof
from .. import servescope as _ss
from ..diagnostics import flight as _flight
from ..healthmon import events as _events
from .errors import (DeadlineExceededError, QueueFullError,
                     ServerClosedError)

__all__ = ["DynamicBatcher", "Request"]


def _c(name):
    return _prof.counter(name, "serving")


class Request:
    """One in-flight prediction: the dispatcher fulfils it (result or
    error) and sets the event; the submitting thread blocks in `wait`."""

    __slots__ = ("x", "enqueued_at", "deadline", "batch_size",
                 "batch_id", "batch_index", "span", "trace_id",
                 "_event", "_result", "_error")

    def __init__(self, x, timeout_ms):
        self.x = x
        self.enqueued_at = time.perf_counter()
        self.deadline = (self.enqueued_at + timeout_ms / 1e3
                         if timeout_ms else None)
        self.batch_size = None          # size of the batch that served us
        self.batch_id = None            # dispatch sequence number
        self.batch_index = None         # our row within that batch
        self.span = None                # servescope lifecycle span (sampled)
        self.trace_id = None            # fleetscope context (reply echo)
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _fulfil(self, result=None, error=None):
        self._result = result
        self._error = error
        self._event.set()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout=None):
        """Block until served; returns the per-output list of np arrays
        (batch dim stripped) or raises the rejection error."""
        if not self._event.wait(timeout):
            raise DeadlineExceededError(
                "request not served within the client wait timeout")
        if self._error is not None:
            raise self._error
        return self._result


class DynamicBatcher:
    def __init__(self, model, max_batch=None, max_delay_ms=5.0,
                 queue_limit=256, default_timeout_ms=1000.0):
        self.model = model
        self.max_batch = int(max_batch or model.max_batch)
        if self.max_batch > model.max_batch:
            raise ValueError(
                f"max_batch={self.max_batch} exceeds the largest compiled "
                f"bucket {model.max_batch}")
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.queue_limit = int(queue_limit)
        self.default_timeout_ms = float(default_timeout_ms)
        self._q = collections.deque()
        self._cond = threading.Condition()
        self._closed = False           # no new admissions
        self._stopped = False          # dispatcher must exit (after drain)
        self._thread = None
        self._dispatch_seq = 0         # only the dispatcher increments
        # liveness breadcrumbs for the deep /healthz: when did a predict
        # last succeed, and when did the dispatcher last attempt a batch
        self.last_response_ts = None   # wall time of last fulfilled batch
        self.last_batch_ts = None      # wall time of last dispatch attempt

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._closed = False
        self._stopped = False
        self._thread = threading.Thread(target=self._run,
                                        name="mxtpu-serving-batcher",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop admissions; with `drain` (default) the dispatcher serves
        everything already queued before exiting, otherwise queued
        requests are rejected with ServerClosedError (still not silently
        dropped)."""
        with self._cond:
            self._closed = True
            if not drain:
                self._flush_closed_locked()
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        # drain backstop: with a dead / never-started dispatcher (or a
        # join that timed out) there is nobody left to serve what is
        # still queued — without this flush those clients hang in
        # req.wait() until their wait timeout. Every flushed request
        # gets a settled rejected_closed span, same as a reject at
        # submit.
        with self._cond:
            self._flush_closed_locked()
        _prof.set_gauge("serving.queue_depth", 0, "serving")

    def _flush_closed_locked(self):
        """Reject everything still queued after close (caller holds
        ``self._cond``): counter + settled span + ServerClosedError to
        the waiting client — the same taxonomy a reject-at-submit gets,
        so a drained-away request is never distinguishable from one
        that was turned away at the door."""
        now = time.perf_counter()
        while self._q:
            req = self._q.popleft()
            _c("serving.rejected_closed").increment()
            if req.span is not None:
                _ss.spans.reject(req.span, "rejected_closed", now)
            req._fulfil(error=ServerClosedError(
                "server stopped before this request was served"))
        _prof.set_gauge("serving.queue_depth", 0, "serving")

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def queue_depth(self) -> int:
        return len(self._q)        # len(deque) is GIL-atomic; no lock

    # -- admission --------------------------------------------------------
    def submit(self, x, timeout_ms=None, traceparent=None) -> Request:
        """Enqueue one SINGLE-SAMPLE request (shape = model.input_shape,
        or (1,) + input_shape). Raises instead of queueing when invalid,
        closed, or over capacity.

        ``traceparent`` is an optional W3C trace-context header from the
        upstream hop (router or client); when fleetscope is armed the
        request's servescope span joins that trace (same trace_id, fresh
        span_id, parent = the upstream span). A replica never mints a
        root here — an absent header just means an untraced request."""
        x = np.asarray(x)
        if x.ndim == len(self.model.input_shape) + 1 and x.shape[0] == 1:
            x = x[0]
        _c("serving.requests").increment()
        try:
            self.model.validate(x)     # InvalidInputError on mismatch
        except Exception:
            _c("serving.rejected_invalid").increment()
            raise
        req = Request(np.ascontiguousarray(x),
                      self.default_timeout_ms if timeout_ms is None
                      else timeout_ms)
        ss = _ss._SS    # snapshot: disable() must not race the two reads
        if ss is not None:
            # sampled lifecycle span: admitted at the enqueue timestamp
            req.span = _ss.spans.begin(req.enqueued_at, ss.sample_every)
        fs = _fs._FS    # same snapshot discipline as servescope above
        if fs is not None and traceparent is not None:
            ctx = fs.accept(traceparent, mint_on_missing=False)
            if ctx is not None:
                req.trace_id = ctx.trace_id
                fs.c_propagated.increment()
                if req.span is not None:
                    req.span.trace_id = ctx.trace_id
                    req.span.parent_id = ctx.span_id
                    req.span.span_id = _fs.context.mint_span_id()
        with self._cond:
            if self._closed:
                _c("serving.rejected_closed").increment()
                if req.span is not None:
                    _ss.spans.reject(req.span, "rejected_closed",
                                     time.perf_counter())
                raise ServerClosedError("server is draining; not "
                                        "accepting new requests")
            if len(self._q) >= self.queue_limit:
                _c("serving.rejected_queue_full").increment()
                if req.span is not None:
                    _ss.spans.reject(req.span, "rejected_queue_full",
                                     time.perf_counter())
                raise QueueFullError(
                    f"request queue at capacity ({self.queue_limit})")
            self._q.append(req)
            self._on_admit(req)
            _prof.set_gauge("serving.queue_depth", len(self._q), "serving")
            self._cond.notify()
        return req

    def _on_admit(self, req):
        """Admission hook, called under ``self._cond`` right after the
        request lands in the queue. The base batcher does nothing; the
        continuous batcher stamps mid-flight admissions here."""

    def predict(self, x, timeout_ms=None):
        """Blocking submit-and-wait convenience."""
        req = self.submit(x, timeout_ms=timeout_ms)
        # the dispatcher enforces the queue deadline; the extra margin
        # here only guards against a dead dispatcher thread
        wait_s = ((timeout_ms or self.default_timeout_ms) / 1e3) + 30.0
        return req.wait(wait_s)

    # -- dispatch loop ----------------------------------------------------
    def _gather(self):
        """Wait for the first request, then coalesce until max_batch or
        the first request has waited max_delay. Returns [] at shutdown."""
        with self._cond:
            while not self._q:
                if self._stopped:
                    return []
                self._cond.wait(0.05)
            # servescope boundary between queue_wait and coalesce_delay:
            # from here on the dispatcher is assembling THIS batch —
            # any further waiting is the deliberate coalescing window,
            # not dispatcher backlog
            gather_start = time.perf_counter()
            first = self._q[0]
            dispatch_at = first.enqueued_at + self.max_delay_s
            while len(self._q) < self.max_batch:
                remaining = dispatch_at - time.perf_counter()
                if remaining <= 0 or self._stopped:
                    break
                self._cond.wait(remaining)
            batch = []
            while self._q and len(batch) < self.max_batch:
                batch.append(self._q.popleft())
            _prof.set_gauge("serving.queue_depth", len(self._q), "serving")
            if _ss._SS is not None:
                for req in batch:
                    if req.span is not None:
                        _ss.spans.mark_gather(req.span, gather_start)
            return batch

    def _run(self):
        while True:
            batch = self._gather()
            if not batch:
                with self._cond:
                    if self._stopped and not self._q:
                        return
                continue
            self._serve(batch)

    def _serve(self, batch):
        now = time.perf_counter()
        live = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                if req.span is not None:
                    _ss.spans.reject(req.span, "rejected_deadline", now)
                req._fulfil(error=DeadlineExceededError(
                    f"deadline exceeded after "
                    f"{(now - req.enqueued_at) * 1e3:.1f} ms in queue"))
                _c("serving.rejected_deadline").increment()
            else:
                live.append(req)
        if not live:
            return
        self.last_batch_ts = time.time()
        bid = self._dispatch_seq
        self._dispatch_seq = bid + 1
        n = len(live)
        ss = _ss._SS    # snapshot: disable() mid-batch must not race
        spanned = (ss is not None
                   and any(r.span is not None for r in live))
        try:
            bucket = self.model.bucket_for(n)
            x = np.stack([r.x for r in live])
            timings = {} if spanned else None
            t0 = time.perf_counter()
            outs = self.model.predict_batch(x, timings=timings)
            t_done = time.perf_counter()
            exec_ms = (t_done - t0) * 1e3
        except Exception as e:  # noqa: BLE001 — a bad batch must not kill
            if spanned:         # the dispatcher; reject and keep serving
                terr = time.perf_counter()
                for req in live:
                    if req.span is not None:
                        _ss.spans.reject(req.span, "batch_error", terr)
            for req in live:
                req._fulfil(error=e if isinstance(e, Exception) else
                            RuntimeError(str(e)))
            _c("serving.batch_errors").increment()
            return
        if spanned:
            for req in live:
                if req.span is not None:
                    _ss.spans.mark_batch(req.span, bid, bucket, n,
                                         t0, t_done, timings)
        # a devicescope capture window over serving dispatches: one mark
        # per executed batch (predict_batch converts outputs to host
        # arrays, so the dispatch is already synced — no barrier needed)
        try:
            from .. import devicescope as _ds
            if _ds._DS is not None:
                win = _ds.active_window()
                if win is not None:
                    win.step(1, dispatch_ms=exec_ms, workload="serving")
        except Exception:  # noqa: BLE001 — measurement never breaks serving
            pass
        _c("serving.batches").increment()
        _c("serving.batched_requests").increment(n)
        _prof.observe("serving.batch_exec_ms", exec_ms, "serving")
        _prof.observe("serving.batch_size", float(n), "serving")
        bargs = {"n": n, "bucket": bucket, "batch_id": bid,
                 "exec_ms": round(exec_ms, 3)}
        if _fs._FS is not None:
            # member trace ids: which cross-process traces this coalesced
            # dispatch served (bounded — a batch never exceeds the largest
            # compiled bucket, but cap anyway so the record stays small)
            traces = [r.trace_id for r in live
                      if r.trace_id is not None][:64]
            if traces:
                bargs["traces"] = traces
        if _flight._REC is not None:
            _flight.record("serving", "serving.batch", dict(bargs))
        if _events._LOG is not None:
            _events.emit("serving", "serving.batch", args=bargs)
        self.last_response_ts = time.time()
        done = time.perf_counter()
        # a deadline that expired DURING batch execution is a rejection,
        # not a success: the deadline is the client's stated SLA, and a
        # result produced after it is past-deadline work — fulfilling it
        # as a 200 would hide exactly the tail the deadline exists to
        # bound (waiters do linger past the deadline, so they receive a
        # crisp DeadlineExceededError, not a silently late success).
        # Counted under its own name — these were lost entirely before
        # (neither a response nor any rejection counter).
        responded, late = [], []
        for i, req in enumerate(live):
            req.batch_size = n
            req.batch_id = bid
            req.batch_index = i
            if req.deadline is not None and done > req.deadline:
                late.append(req)
            else:
                responded.append((i, req))
        # telemetry BEFORE fulfil: a /stats (or bench snapshot) taken the
        # instant a client's predict() returns must already contain that
        # request — observing after _fulfil let percentiles/responses mix
        # epochs mid-read (the waiting client races the counter updates)
        for _, req in responded:
            _prof.observe("serving.latency_ms",
                          (done - req.enqueued_at) * 1e3, "serving")
        if responded:
            _c("serving.responses").increment(len(responded))
        if late:
            _c("serving.rejected_deadline_post_batch").increment(len(late))
        if spanned:
            for i, req in responded:
                if req.span is not None:
                    comp = _ss.spans.finish(req.span, done, batch_index=i)
                    ss.budget.observe(req.span, comp)
            for req in late:
                if req.span is not None:
                    _ss.spans.reject(req.span,
                                     "rejected_deadline_post_batch", done)
        for _, req in responded:
            req._fulfil(result=[o[req.batch_index] for o in outs])
        for req in late:
            req._fulfil(error=DeadlineExceededError(
                f"deadline exceeded during batch execution "
                f"({exec_ms:.1f} ms in bucket {bucket})"))

    # -- stats ------------------------------------------------------------
    @staticmethod
    def stats() -> dict:
        """Serving-domain counters + derived headline numbers (shared by
        /stats and the bench)."""
        snap = {k.split("/", 1)[1]: v
                for k, v in _prof.counters().items()
                if k.startswith("serving/")}
        batches = snap.get("serving.batches", 0)
        coalesced = snap.get("serving.batched_requests", 0)
        snap["batch_fill"] = (coalesced / batches) if batches else 0.0
        lat = snap.get("serving.latency_ms")
        if isinstance(lat, dict):
            snap["p50_ms"] = lat.get("p50")
            snap["p95_ms"] = lat.get("p95")
            snap["p99_ms"] = lat.get("p99")
        return snap
