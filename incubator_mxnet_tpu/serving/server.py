"""ModelServer — stdlib HTTP front end over FrozenModel + DynamicBatcher.

Mirrors `diagnostics/export.py`'s server pattern (ThreadingHTTPServer in
a daemon thread, quiet logs, JSON bodies) so the whole serving stack —
like the rest of the observability layer — needs nothing outside the
standard library. The reference analogue is `mxnet-model-server`'s
frontend, collapsed to its essentials:

* ``POST /predict`` — body ``{"data": <nested list>, "timeout_ms": N?}``;
  200 with ``{"output": ..., "batch_size": n, "latency_ms": t}``, or the
  admission error's HTTP code (400 invalid, 429 queue full, 504
  deadline, 503 draining) with ``{"error": ..., "message": ...}``;
* ``GET /healthz`` — a DEEP health check, not an unconditional 200:
  ``{"status": "ok"|"degraded"|"draining", "checks": {...}}`` reporting
  batcher liveness, queue saturation, the age of the last successful
  predict, and the healthmon watchdog status. 200 only while genuinely
  able to serve; 503 when draining, when the dispatcher thread is dead,
  when the queue is saturated, or when requests are queued but no
  predict has completed within ``MXTPU_SERVING_STALL_S`` (default 30) —
  so load balancers stop routing to a wedged replica, not just a
  closing one;
* ``GET /stats`` — serving counters, batch-fill ratio, latency
  percentiles, queue depth, uptime and QPS.

Shutdown is a graceful drain: ``stop()`` flips /healthz to draining,
stops admissions, lets the batcher finish every accepted request, then
closes the listener.

Env knobs: MXTPU_SERVING_HOST / MXTPU_SERVING_PORT,
MXTPU_SERVING_MAX_BATCH, MXTPU_SERVING_MAX_DELAY_MS,
MXTPU_SERVING_QUEUE_LIMIT, MXTPU_SERVING_TIMEOUT_MS (see
docs/serving.md).
"""
from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from .. import fleetscope as _fs
from .. import healthmon as _healthmon
from .. import profiler as _prof
from .. import resilience as _resilience
from .. import servescope as _ss
from .batcher import DynamicBatcher
from .errors import InvalidInputError, ServerClosedError, ServingError
from .frozen import FrozenModel

__all__ = ["ModelServer"]


def _env_float(name, default):
    from ..autotune.knobs import env_float
    return float(env_float(name, default))


class ModelServer:
    """Serve a FrozenModel (or freeze a HybridBlock in place) over HTTP.

    ``ModelServer(net, input_shape=(1, 28, 28)).start()`` returns
    ``(host, port)``; port 0 (default) binds a free one.
    """

    def __init__(self, model, input_shape=None, host=None, port=None,
                 max_batch=None, max_delay_ms=None, queue_limit=None,
                 default_timeout_ms=None, batcher=None, **freeze_kwargs):
        if not isinstance(model, FrozenModel):
            if input_shape is None:
                raise ValueError("input_shape is required when passing an "
                                 "unfrozen block")
            model = FrozenModel(model, input_shape, **freeze_kwargs)
        self.model = model
        from ..autotune.knobs import env_int, env_str
        self.host = host or env_str("MXTPU_SERVING_HOST", "127.0.0.1")
        self.port = env_int("MXTPU_SERVING_PORT", 0, call_site=port)
        # scheduler selection: "dynamic" (coalesce-then-dispatch, the
        # sporadic-traffic default) or "continuous" (iteration-level
        # slots, the fleet/sustained-load path — docs/serving.md)
        self.batcher_kind = env_str("MXTPU_SERVING_BATCHER", "dynamic",
                                    call_site=batcher)
        if self.batcher_kind not in ("dynamic", "continuous"):
            raise ValueError(f"batcher must be 'dynamic' or 'continuous',"
                             f" got {self.batcher_kind!r}")
        self._batcher_settings = {
            "max_batch": max_batch or
            env_int("MXTPU_SERVING_MAX_BATCH", 0) or None,
            "max_delay_ms": max_delay_ms if max_delay_ms is not None
            else _env_float("MXTPU_SERVING_MAX_DELAY_MS", 5.0),
            "queue_limit": queue_limit or
            env_int("MXTPU_SERVING_QUEUE_LIMIT", 256),
            "default_timeout_ms": default_timeout_ms
            if default_timeout_ms is not None
            else _env_float("MXTPU_SERVING_TIMEOUT_MS", 1000.0)}
        self.batcher = self._make_batcher(model)
        self._httpd = None
        self._started_at = None
        self._draining = False

    def _make_batcher(self, model):
        """One batcher of the server's configured kind over `model` —
        shared by construction and `swap_model` so a hot-swapped model
        serves under exactly the same scheduler + knobs."""
        if self.batcher_kind == "continuous":
            from ..fleet.continuous import ContinuousBatcher
            cls = ContinuousBatcher
        else:
            cls = DynamicBatcher
        return cls(model, **self._batcher_settings)

    # -- lifecycle --------------------------------------------------------
    def start(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # loopback p99 killer: headers and body leave as separate
            # small segments, and Nagle holds the second until the
            # first is ACKed — which the peer's delayed ACK sits on for
            # ~40 ms. TCP_NODELAY turns that stall into microseconds.
            disable_nagle_algorithm = True

            def _reply(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path.startswith("/healthz"):
                        code, doc = server.health()
                        self._reply(code, doc)
                    elif self.path.startswith("/stats"):
                        self._reply(200, server.stats())
                    else:
                        self._reply(404, {"error": "NotFound",
                                          "message": self.path})
                except Exception as e:  # noqa: BLE001
                    self._safe_500(e)

            def do_POST(self):
                try:
                    if not self.path.startswith("/predict"):
                        self._reply(404, {"error": "NotFound",
                                          "message": self.path})
                        return
                    length = int(self.headers.get("Content-Length") or 0)
                    try:
                        doc = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(doc, dict) or "data" not in doc:
                            raise ValueError("body must be a JSON object "
                                             "with a 'data' key")
                        x = np.asarray(doc["data"],
                                       dtype=server.model.dtype)
                    except (ValueError, TypeError) as e:
                        raise InvalidInputError(str(e)) from e
                    t0 = time.perf_counter()
                    # fleetscope: the upstream hop's W3C trace context
                    # rides the standard header; read only while armed
                    # (off = this one predicate on the request path)
                    tp = (self.headers.get("traceparent")
                          if _fs._FS is not None else None)
                    # swap-safe admission: a hot swap may close the
                    # batcher we read between the read and the submit —
                    # when a NEW batcher has already been published,
                    # resubmit there instead of bouncing the client
                    # (zero dropped requests across a deploy); a real
                    # drain (batcher unchanged) still raises 503
                    for _ in range(8):
                        b = server.batcher
                        try:
                            req = b.submit(
                                x, timeout_ms=doc.get("timeout_ms"),
                                traceparent=tp)
                            break
                        except ServerClosedError:
                            if server.batcher is b:
                                raise
                    else:
                        raise ServerClosedError(
                            "server is swapping models faster than "
                            "requests can be admitted")
                    outs = req.wait(
                        (doc.get("timeout_ms")
                         or b.default_timeout_ms) / 1e3 + 30.0)
                    out = outs[0] if len(outs) == 1 else outs
                    reply = {
                        "output": (out.tolist() if isinstance(out, np.ndarray)
                                   else [o.tolist() for o in out]),
                        "batch_size": req.batch_size,
                        "batch_id": req.batch_id,
                        "batch_index": req.batch_index,
                        "latency_ms": round(
                            (time.perf_counter() - t0) * 1e3, 3)}
                    if req.trace_id is not None:
                        reply["trace_id"] = req.trace_id
                    self._reply(200, reply)
                except ServingError as e:
                    self._reply(e.code, e.to_json())
                except Exception as e:  # noqa: BLE001
                    self._safe_500(e)

            def _safe_500(self, e):
                try:
                    self._reply(500, {"error": type(e).__name__,
                                      "message": str(e)[:500]})
                except Exception:
                    pass

            def log_message(self, *a):   # stay quiet on stderr
                pass

        class _Server(ThreadingHTTPServer):
            # socketserver's default accept backlog is 5 — under a
            # concurrent-client burst the SYN queue overflows and
            # clients pay kernel retransmit timeouts (a measured 1s/3s
            # p99 quantization that has nothing to do with serving).
            # Size it like the admission queue: beyond this the 429
            # backpressure path is the bounded-latency answer.
            request_queue_size = max(128, self.batcher.queue_limit)

        self.batcher.start()
        self._httpd = _Server((self.host, self.port), _Handler)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="mxtpu-serving-http", daemon=True)
        t.start()
        self._started_at = time.time()
        self._draining = False
        _prof.set_gauge("serving.up", 1, "serving")
        return self.host, self.port

    def swap_model(self, model, input_shape=None, **freeze_kwargs):
        """Zero-downtime model hot-swap (the deploy primitive under
        `fleet.Router.deploy`): build and START the new model's batcher
        first, publish it atomically (`self.batcher` — the request
        handler re-reads it per request, and resubmits there if it
        raced the old one's close), then drain the old batcher so every
        request it had already accepted is served. At no instant is
        there no admitting batcher, so a swap drops zero requests even
        under concurrent load."""
        if not isinstance(model, FrozenModel):
            if input_shape is None:
                raise ValueError("input_shape is required when passing an "
                                 "unfrozen block")
            model = FrozenModel(model, input_shape, **freeze_kwargs)
        new_batcher = self._make_batcher(model).start()
        old = self.batcher
        self.model = model
        self.batcher = new_batcher
        _prof.counter("serving.model_swaps", "serving").increment()
        old.stop(drain=True)
        return model

    def stop(self, drain: bool = True):
        """Graceful shutdown: mark draining (healthz 503), stop
        admissions, finish accepted requests, then close the listener."""
        self._draining = True
        self.batcher.stop(drain=drain)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        _prof.set_gauge("serving.up", 0, "serving")

    @property
    def address(self):
        return f"http://{self.host}:{self.port}"

    # -- deep health ------------------------------------------------------
    def health(self):
        """(http_code, body) for /healthz — the deep check. Policy:

        * draining → 503 "draining" (the graceful-shutdown signal);
        * dispatcher thread dead → 503 (accepted requests can never
          complete);
        * queue saturated (depth >= limit) → 503 (every new predict
          would be rejected 429 anyway — stop routing here);
        * requests queued but nothing served for MXTPU_SERVING_STALL_S
          → 503 (a wedged executable looks exactly like "slow");
        * otherwise 200, with the same observations reported so
          dashboards see saturation BEFORE it trips the threshold.

        The healthmon watchdog status rides along as a report-only
        section: a training-side stall in a co-hosted process is context
        for the operator, not a reason for the LB to drop this replica.
        """
        now = time.time()
        b = self.batcher
        depth = b.queue_depth
        saturation = depth / b.queue_limit if b.queue_limit else 0.0
        last_ts = b.last_response_ts
        age = (now - last_ts) if last_ts is not None else None
        stall_s = _env_float("MXTPU_SERVING_STALL_S", 30.0)
        checks = {
            "batcher_alive": b.running,
            "queue_depth": depth,
            "queue_limit": b.queue_limit,
            "queue_saturation": round(saturation, 3),
            "last_predict_age_s": (round(age, 3) if age is not None
                                   else None),
        }
        snap = _prof.counters()
        checks["healthmon"] = {
            "enabled": _healthmon.enabled(),
            "stall_alerts": snap.get(
                "healthmon/healthmon.stall_alerts", 0),
            "nan_alerts": snap.get("healthmon/healthmon.nan_alerts", 0),
        }
        # resilience (who ACTS on those verdicts): checkpoint freshness,
        # recovery totals, rollback-in-progress — report-only context
        # like the healthmon block (a co-hosted training run mid-rollback
        # is operator context, not an LB drop reason)
        checks["healthmon"]["resilience"] = _resilience.status()
        # commscope's last resharding verdict per compiled bucket: an
        # accidental all-gather on the serve path is a per-request p99
        # catastrophe (docs/commscope.md). Report-only, like healthmon —
        # a layout verdict is for the operator, not a reason for the LB
        # to drop an otherwise-serving replica — but flagged loudly.
        verdicts = self.model.comm_verdicts()
        if verdicts:
            flagged = sorted(b for b, v in verdicts.items()
                             if v.get("resharding_collectives"))
            checks["resharding"] = {
                "buckets": verdicts,
                "buckets_flagged": flagged,
            }
        # servescope's current p99 attribution: WHAT the tail is, not
        # just how tall (docs/servescope.md)
        brief = _ss.attribution_brief()
        if brief is not None:
            checks["servescope_p99"] = brief
        # memscope's live memory headroom (capacity x target vs current
        # in-use, docs/memscope.md). Report-only, same discipline as the
        # healthmon block: a "tight" verdict is admission/operator
        # context, not a reason for the LB to drop a serving replica.
        try:
            from .. import memscope as _memscope
            if _memscope._MS is not None:
                hs = _memscope.headroom_state()
                checks["memscope"] = {
                    "headroom_fraction": hs.get("headroom_fraction"),
                    "verdict": hs.get("verdict"),
                    "capacity_bytes": hs.get("capacity_bytes"),
                    "in_use_bytes": hs.get("in_use_bytes"),
                    "oom_events": _prof.counters().get(
                        "memscope/memscope.oom_events", 0),
                }
        except Exception:  # noqa: BLE001 — telemetry never breaks /healthz
            pass
        problems = []
        if not b.running:
            problems.append("batcher_dead")
        if depth >= b.queue_limit:
            problems.append("queue_saturated")
        # stalled = work is waiting and nothing has completed recently;
        # the reference point falls back to server start so a server
        # whose FIRST batch wedges is caught too
        progress_ref = max(x for x in (last_ts, b.last_batch_ts,
                                       self._started_at, 0.0)
                           if x is not None)
        if depth > 0 and (now - progress_ref) > stall_s:
            problems.append("predict_stalled")
        if self._draining:
            status = "draining"
        elif problems:
            status = "degraded"
        else:
            status = "ok"
        doc = {"status": status,
               "model": repr(self.model),
               "buckets": list(self.model.buckets),
               "checks": checks}
        if problems:
            doc["problems"] = problems
        return (200 if status == "ok" else 503), doc

    # -- stats ------------------------------------------------------------
    def stats(self) -> dict:
        """One consistent registry snapshot per call: every derived
        number (percentiles, fill, qps) comes from the SINGLE
        ``batcher.stats()`` read — a second read mid-traffic would mix
        epochs (the histogram and the response counter advancing
        between reads). Callers that also want the raw latency
        histogram read it from this same dict
        (``s["serving.latency_ms"]``), never from a fresh snapshot."""
        s = self.batcher.stats()
        uptime = (time.time() - self._started_at) if self._started_at \
            else 0.0
        s["uptime_s"] = round(uptime, 3)
        responses = s.get("serving.responses", 0)
        s["qps"] = round(responses / uptime, 3) if uptime > 0 else 0.0
        s["draining"] = self._draining
        s["buckets"] = list(self.model.buckets)
        s["max_batch"] = self.batcher.max_batch
        s["max_delay_ms"] = self.batcher.max_delay_s * 1e3
        s["queue_limit"] = self.batcher.queue_limit
        s["batcher"] = self.batcher_kind
        verdicts = self.model.comm_verdicts()
        if verdicts:
            s["resharding"] = verdicts
        brief = _ss.attribution_brief()
        if brief is not None:
            s["servescope"] = brief
        return s
