"""mxtpu.serving — AOT-compiled inference serving with dynamic batching.

The inference path the training-side subsystems were missing (reference:
`mxnet-model-server` over exported symbol+params checkpoints), rebuilt
TPU-native in three layers:

* :class:`FrozenModel` (:mod:`.frozen`) — freeze a trained
  `HybridBlock`/`SymbolBlock` (or a `HybridBlock.export()` checkpoint,
  via :meth:`FrozenModel.from_exported`) and ahead-of-time compile one
  donated executable per batch-size bucket, warmed up before traffic;
* :class:`DynamicBatcher` (:mod:`.batcher`) — bounded thread-safe queue
  coalescing single requests into padded bucket batches under a
  max-latency/max-batch policy, with fail-fast backpressure, per-request
  deadlines, and graceful drain;
* :class:`ModelServer` (:mod:`.server`) — stdlib HTTP front end
  (`/predict`, `/healthz`, `/stats`) with drain-aware shutdown.

Quick start::

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import serving

    net = mx.gluon.model_zoo.get_model("lenet", classes=10)
    net.initialize(init=mx.init.Xavier())
    frozen = net.freeze(input_shape=(1, 28, 28))      # AOT compile+warmup
    srv = serving.ModelServer(frozen)
    host, port = srv.start()
    # POST {"data": [[...28x28...]]} to http://host:port/predict
    srv.stop()                                        # graceful drain

All serving telemetry (QPS, batch-fill, queue depth, latency histograms)
rides the `profiler.counters` registry, so the diagnostics sampler, the
Prometheus/JSON exporters, and the flight recorder pick it up with zero
extra wiring. See docs/serving.md.
"""
from __future__ import annotations

from .errors import (ServingError, InvalidInputError, QueueFullError,
                     DeadlineExceededError, ServerClosedError,
                     ReshardingGateError)
from .frozen import FrozenModel, default_buckets
from .batcher import DynamicBatcher, Request
from .server import ModelServer

__all__ = [
    "FrozenModel", "default_buckets", "DynamicBatcher", "Request",
    "ModelServer",
    "ServingError", "InvalidInputError", "QueueFullError",
    "DeadlineExceededError", "ServerClosedError", "ReshardingGateError",
]
