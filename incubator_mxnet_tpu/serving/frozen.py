"""FrozenModel — ahead-of-time-compiled inference executables.

The serving counterpart of `HybridBlock.hybridize()`: where hybridize
compiles lazily on first call per signature (fine for training, fatal for
tail latency), FrozenModel **freezes** a trained block and precompiles —
at construction time, before traffic arrives — one XLA executable per
batch-size bucket:

* **freeze** — parameters are snapshotted (and optionally `device_put`
  onto an explicit Context) at construction; later training updates to
  the source block do not leak into serving, and no autograd state is
  ever touched (the trace runs with recording off, training=False, so
  BatchNorm uses running stats and dropout is identity);
* **AOT compile** — the forward is traced ONCE (`jax.eval_shape`, no
  device work) to learn the output tree, then `jit.lower(...).compile()`
  builds a concrete executable per bucket — compile cost is paid at
  deploy time, with an explicit warmup execution per bucket so first
  requests never see allocator/runtime lazy-init either;
* **donation** — the padded input batch buffer is donated to the
  executable on backends that support it (TPU/GPU), so steady-state
  serving does not hold two copies of every in-flight batch; params are
  passed (not donated) and live on-device for the model's lifetime.

The reference lineage is `mxnet-model-server`'s frozen
symbol+params checkpoint; `FrozenModel.from_exported` loads exactly that
artifact (`prefix-symbol.json` + `prefix-0000.params`, via SymbolBlock).
"""
from __future__ import annotations

import time

import jax
import numpy as np

from .. import autograd
from .. import perfscope as _ps
from .. import profiler as _prof
from ..diagnostics import flight as _flight
from ..gluon.block import HybridBlock, _flatten_out, _unflatten_out
from ..gluon.parameter import DeferredInitializationError, _ParamTraceScope
from ..ndarray import NDArray
from ..ndarray import random as ndrandom
from .errors import InvalidInputError, ReshardingGateError

__all__ = ["FrozenModel", "default_buckets"]


def default_buckets(max_batch: int | None = None):
    """Power-of-two bucket ladder, overridable via MXTPU_SERVING_BUCKETS
    (comma-separated batch sizes)."""
    from ..autotune.knobs import env_str
    env = env_str("MXTPU_SERVING_BUCKETS")
    if env:
        sizes = sorted({int(s) for s in env.split(",") if s.strip()})
    else:
        sizes, b = [], 1
        cap = int(max_batch or 32)
        while b < cap:
            sizes.append(b)
            b *= 2
        sizes.append(cap)
        sizes = sorted(set(sizes))
    if not sizes or sizes[0] < 1:
        raise ValueError(f"invalid serving buckets {sizes!r}")
    return tuple(sizes)


class FrozenModel:
    """An immutable, serving-ready snapshot of a Gluon block.

    Parameters
    ----------
    block : HybridBlock (SymbolBlock included)
        Trained model; params must be initialized (or initializable from
        `input_shape` via one deferred-shape inference pass).
    input_shape : tuple
        PER-SAMPLE input shape (no batch dimension).
    dtype : str
        Input dtype requests must match.
    batch_buckets : sequence of int, optional
        Batch sizes to precompile; default `default_buckets()`.
    ctx : Context, optional
        Freeze params onto this device (default: wherever they live).
    warmup : bool
        Execute each compiled bucket once at construction (default True).
    donate : bool, optional
        Donate the input buffer to the executable. Default: only on
        backends that support donation (not CPU, where XLA would warn
        and ignore it).
    compute_dtype : str, optional
        Execute the forward in this dtype ("bfloat16"/"bf16") while the
        request/response surface stays `dtype`: params are cast once at
        freeze, the input is cast on entry, floating outputs are cast
        back on exit. None/"float32" leaves the path untouched.
    mesh : Mesh, optional
        Shard the frozen params across this device mesh via the
        resolution layer (`parallel.sharding.resolve_param` — logical
        axis rules, counted replicated fallback) and compile every
        bucket as a GSPMD program over it.
    mesh_mode : str
        Commscope layout-signature mode for the resharding detector
        ("auto" default; "dp"/"mp"/"fsdp" narrow the expected kinds).
    reshard_gate : bool
        With a mesh, refuse to deploy (raise
        :class:`ReshardingGateError`) when any compiled bucket's
        optimized HLO contains resharding collectives — an accidental
        all-gather per request is a p99 catastrophe, caught at freeze
        time. Default True; False serves degraded with the verdict
        still flagged in /healthz + /stats.
    compile_cache : optional
        A `fleet.CompileCache`-shaped object (``load(lowered)`` /
        ``store(lowered, compiled)``): buckets found in the cache are
        deserialized instead of compiled, so replica N+1 of a fleet
        skips the XLA compiles replica 0 already paid for.
    """

    def __init__(self, block, input_shape, dtype="float32",
                 batch_buckets=None, ctx=None, warmup=True, donate=None,
                 compute_dtype=None, mesh=None, mesh_mode="auto",
                 reshard_gate=True, compile_cache=None):
        if not isinstance(block, HybridBlock):
            raise TypeError("FrozenModel requires a HybridBlock (or "
                            f"SymbolBlock), got {type(block).__name__}")
        self._block = block
        self._input_shape = tuple(int(d) for d in input_shape)
        self._dtype = np.dtype(dtype)
        self._ctx = ctx
        self._mesh = mesh
        self._mesh_mode = mesh_mode
        self._compile_cache = compile_cache
        self._compute = None
        if compute_dtype is not None and str(compute_dtype) != "float32":
            if str(compute_dtype) not in ("bfloat16", "bf16"):
                raise ValueError(
                    f"compute_dtype must be 'float32' or 'bfloat16', "
                    f"got {compute_dtype!r}")
            self._compute = jax.numpy.bfloat16
        self.buckets = tuple(sorted(batch_buckets)) if batch_buckets \
            else default_buckets()

        params = self._frozen_params(block)
        self._param_ids = [id(p) for p in params]
        self._param_raws = tuple(p.data()._data if ctx is None
                                 else jax.device_put(p.data()._data,
                                                     ctx.device)
                                 for p in params)
        if self._compute is not None:
            # cast once at freeze: floating params live in the compute
            # dtype for the model's lifetime (integer tables untouched)
            self._param_raws = tuple(
                r.astype(self._compute)
                if jax.numpy.issubdtype(r.dtype, jax.numpy.floating)
                else r for r in self._param_raws)
        self._x_sharding = None
        self._key = jax.random.PRNGKey(0)  # inference: dropout is identity
        if mesh is not None:
            # the resolution layer decides each param's placement
            # (logical axis rules; counted replicated fallback); the
            # request batch and the trace key ride replicated
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel.sharding import resolve_param
            self._param_raws = tuple(
                jax.device_put(r, resolve_param(p, mesh))
                for p, r in zip(params, self._param_raws))
            self._x_sharding = NamedSharding(mesh, PartitionSpec())
            self._key = jax.device_put(self._key, self._x_sharding)
        if donate is None:
            donate = jax.default_backend() not in ("cpu",)
        self.donate = bool(donate)

        self._out_tree = None
        raw_fn = self._make_raw_fn()
        self._jit = jax.jit(raw_fn,
                            donate_argnums=(2,) if self.donate else ())
        self._exec = {}
        for b in self.buckets:
            self._compile_bucket(b, warmup)
        _prof.set_gauge("serving.compiled_buckets", len(self._exec),
                        "serving")
        if mesh is not None and reshard_gate:
            self._check_reshard_gate()

    # -- freezing ---------------------------------------------------------
    def _frozen_params(self, block):
        params = list(block.collect_params().values())
        try:
            for p in params:
                p.data()
        except DeferredInitializationError:
            # one shape-inference forward on a zero sample completes
            # deferred init (same move as HybridBlock._call_cached)
            from .. import ndarray as nd_mod
            with autograd.pause(False):
                block(nd_mod.zeros((1,) + self._input_shape,
                                   dtype=self._dtype.name))
            params = list(block.collect_params().values())
            for p in params:
                p.data()
        return params

    # -- tracing / compilation -------------------------------------------
    def _make_raw_fn(self):
        block = self._block
        param_ids = self._param_ids
        compute = self._compute
        out_dtype = self._dtype
        info = {}

        def raw_fn(key_raw, p_raws, x_raw):
            if compute is not None:
                # the compute-dtype boundary: requests stay `dtype` on
                # the wire, the forward runs in bf16, floating outputs
                # come back in `dtype` (int outputs — argmax heads —
                # pass through)
                x_raw = x_raw.astype(compute)
            sub = dict(zip(param_ids, p_raws))
            # recording=False, training=False: pure inference semantics —
            # BN running stats are read, never written; dropout passes
            # through; nothing lands on any autograd tape
            with _ParamTraceScope(sub), autograd._Scope(False, False), \
                    ndrandom._TraceKeyScope(key_raw):
                out = block.forward(NDArray(x_raw))
                leaves, tree = _flatten_out(out)
            info["tree"] = tree
            outs = tuple(x._data for x in leaves)
            if compute is not None:
                outs = tuple(
                    o.astype(out_dtype)
                    if jax.numpy.issubdtype(o.dtype, jax.numpy.floating)
                    else o for o in outs)
            return outs

        self._raw_info = info
        return raw_fn

    def _compile_bucket(self, b, warmup):
        shape = (b,) + self._input_shape
        if self._x_sharding is not None:
            x_spec = jax.ShapeDtypeStruct(shape, self._dtype,
                                          sharding=self._x_sharding)
        else:
            x_spec = jax.ShapeDtypeStruct(shape, self._dtype)
        if _flight._REC is not None:
            _flight.record("compile", f"serving.freeze:b{b}",
                           {"shape": list(shape), "dtype": str(self._dtype)})
        with _prof.Scope(f"serving.compile:b{b}", "serving", sync=False):
            # lower always (it is cheap tracing, and it learns the
            # output tree); the expensive compile consults the shared
            # AOT cache first — a hit deserializes replica 0's
            # executable instead of recompiling it
            lowered = self._jit.lower(self._key, self._param_raws, x_spec)
            compiled = (self._compile_cache.load(lowered)
                        if self._compile_cache is not None else None)
            if compiled is None:
                compiled = lowered.compile()
                if self._compile_cache is not None:
                    self._compile_cache.store(lowered, compiled)
            self._exec[b] = compiled
        if self._out_tree is None:
            self._out_tree = self._raw_info["tree"]
        commscoped = False
        if _ps._PS is not None:
            # the bucket is already lowered — the roofline verdict is a
            # free host-side read here (no extra trace). The compiled
            # executable rides along so commscope's collective
            # extraction reads the optimized HLO without compiling again
            _ps.analyze_lowered(
                lowered, name=self.program_name(b),
                dtype=self._dtype, kind="serving_bucket",
                extra={"bucket": b}, compiled=self._exec[b],
                mesh=self._mesh, mode=self._mesh_mode)
            try:
                from .. import commscope as _cs
                commscoped = _cs._CS is not None
            except Exception:  # noqa: BLE001
                commscoped = False
        if self._mesh is not None and not commscoped:
            # the resharding gate must see a verdict even with the
            # observability stack unarmed: hand the compiled HLO to
            # commscope's extractor directly (total, never raises)
            try:
                from .. import commscope as _cs
                _cs.capture(self.program_name(b), compiled=self._exec[b],
                            mesh=self._mesh, mode=self._mesh_mode,
                            kind="serving_bucket", extra={"bucket": b})
            except Exception:  # noqa: BLE001 — verdicts, not serving
                pass
        _prof.counter("serving.compiles", "serving").increment()
        if warmup:
            x0 = np.zeros(shape, self._dtype)
            outs = self.run_raw(x0)
            jax.block_until_ready(outs)
            _prof.counter("serving.warmup_runs", "serving").increment()

    def _check_reshard_gate(self):
        """Refuse a sharded deploy whose compiled buckets contain
        resharding collectives (commscope's verdict over the optimized
        HLO) — the accidental all-gather is caught at freeze time, not
        in production p99."""
        verdicts = self.comm_verdicts()
        flagged = sorted(b for b, v in verdicts.items()
                         if v.get("resharding_collectives"))
        if flagged:
            detail = {b: verdicts[b]["resharding_collectives"]
                      for b in flagged}
            raise ReshardingGateError(
                f"sharded serve path for {self._block.name!r} contains "
                f"resharding collectives in buckets {detail} — fix the "
                f"param layout (see docs/commscope.md) or pass "
                f"reshard_gate=False to serve degraded")

    # -- execution --------------------------------------------------------
    @property
    def input_shape(self):
        return self._input_shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def program_name(self, b: int) -> str:
        """The perfscope/commscope program-table name of one bucket's
        AOT executable — the ONE join key servescope, /healthz and
        /stats use to attach roofline + resharding verdicts."""
        return f"serving:{self._block.name}:b{b}"

    def comm_verdicts(self) -> dict:
        """Per-bucket commscope resharding verdict for the compiled
        executables: ``{bucket: {resharding_collectives, hlo_available,
        collective_count, collective_bytes}}``. An accidental
        all-gather on the serve path is a per-request p99 catastrophe
        (docs/commscope.md), so the deep /healthz and /stats surface
        this verdict. Empty when commscope never captured the buckets
        (unarmed, or compiled before arming). Never raises."""
        out = {}
        try:
            from .. import commscope as _cs
            progs = {p.get("name"): p for p in _cs.programs()}
        except Exception:  # noqa: BLE001
            return out
        for b in self.buckets:
            rec = progs.get(self.program_name(b))
            if not isinstance(rec, dict):
                continue
            totals = rec.get("totals") or {}
            out[str(b)] = {
                "resharding_collectives":
                    rec.get("resharding_collectives", 0),
                "hlo_available": rec.get("hlo_available", True),
                "collective_count": totals.get("count"),
                "collective_bytes": totals.get("bytes"),
            }
        return out

    def roofline_verdicts(self) -> dict:
        """Per-bucket perfscope roofline verdict for the compiled
        executables (``{bucket: verdict}``); empty when perfscope never
        captured them. Never raises."""
        out = {}
        try:
            from .. import perfscope as _ps_mod
            progs = {p.get("name"): p for p in _ps_mod.programs()}
        except Exception:  # noqa: BLE001
            return out
        for b in self.buckets:
            rec = progs.get(self.program_name(b))
            if isinstance(rec, dict):
                out[str(b)] = rec.get("verdict")
        return out

    def bucket_for(self, n: int) -> int:
        """Smallest compiled bucket that fits n samples."""
        for b in self.buckets:
            if b >= n:
                return b
        raise InvalidInputError(
            f"batch of {n} exceeds the largest compiled bucket "
            f"({self.buckets[-1]}); recompile with larger batch_buckets")

    def validate(self, x: np.ndarray):
        """Shape/dtype admission check for ONE sample (no batch dim)."""
        if tuple(x.shape) != self._input_shape:
            raise InvalidInputError(
                f"sample shape {tuple(x.shape)} != expected "
                f"{self._input_shape}")
        if np.dtype(x.dtype) != self._dtype:
            raise InvalidInputError(
                f"sample dtype {x.dtype} != expected {self._dtype.name}")

    def run_raw(self, x) -> tuple:
        """Execute the bucket exactly matching `x.shape[0]`. Returns the
        flat tuple of raw output arrays (still batched/padded)."""
        n = int(x.shape[0])
        ex = self._exec.get(n)
        if ex is None:
            raise InvalidInputError(
                f"no compiled bucket for batch {n}; buckets={self.buckets}")
        xj = jax.numpy.asarray(x)
        if self._x_sharding is not None:
            xj = jax.device_put(xj, self._x_sharding)
        return ex(self._key, self._param_raws, xj)

    def predict_batch(self, x: np.ndarray, timings: dict | None = None) \
            -> list:
        """Serve a host batch of n <= max_batch samples: pad up to the
        bucket, execute, slice back to n. Returns the per-output list of
        np arrays (length n each). Rows are independent in inference
        graphs, so padding rows never changes real rows' values.

        ``timings``: when a dict is passed (servescope's sampled path)
        it is filled with the per-phase wall split ``{"pad_ms",
        "exec_ms", "unpad_ms"}`` — pad copy, executable wall (transfer
        + device, closed by an explicit ``block_until_ready`` so the
        boundary is real on async backends), and the unpad slice/host
        conversion. With ``timings=None`` the path is unchanged (the
        conversion itself is the sync)."""
        n = int(x.shape[0])
        b = self.bucket_for(n)
        if timings is None:
            if b != n:
                pad = np.zeros((b - n,) + self._input_shape, self._dtype)
                x = np.concatenate([np.ascontiguousarray(x), pad], axis=0)
            outs = self.run_raw(x)
            return [np.asarray(o)[:n] for o in outs]
        t0 = time.perf_counter()
        if b != n:
            pad = np.zeros((b - n,) + self._input_shape, self._dtype)
            x = np.concatenate([np.ascontiguousarray(x), pad], axis=0)
        t1 = time.perf_counter()
        outs = self.run_raw(x)
        jax.block_until_ready(outs)
        t2 = time.perf_counter()
        res = [np.asarray(o)[:n] for o in outs]
        t3 = time.perf_counter()
        timings["pad_ms"] = (t1 - t0) * 1e3
        timings["exec_ms"] = (t2 - t1) * 1e3
        timings["unpad_ms"] = (t3 - t2) * 1e3
        return res

    def __call__(self, x):
        """NDArray-level convenience matching `block(x)`: accepts an
        NDArray or np array WITH batch dim, returns NDArray(s) in the
        block's output structure."""
        x_np = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        outs = self.predict_batch(x_np.astype(self._dtype, copy=False))
        leaves = [NDArray(jax.numpy.asarray(o)) for o in outs]
        return _unflatten_out(self._out_tree, leaves)

    # -- quantization -----------------------------------------------------
    def quantize(self, mode="int8", calib_data=None, calib_mode=None,
                 exclude=(), **freeze_kwargs):
        """A NEW serving-ready FrozenModel in reduced precision; this
        model keeps serving float32 unchanged from its frozen snapshot.

        * ``mode="bf16"`` — same block, ``compute_dtype="bfloat16"``:
          params cast once at freeze, activations computed in bf16,
          floating outputs cast back; the request/response dtype is
          untouched. No calibration needed.
        * ``mode="int8"`` — `contrib.quantization.quantize_net` swaps
          every Dense/Conv2D for its int8 twin (symmetric, per-output-
          channel weight scales; with ``calib_data`` + ``calib_mode``
          the activation scales are baked static first). NOTE: the
          conversion mutates the underlying block in place (the contrib
          contract); this FrozenModel's already-compiled executables
          and its frozen param snapshot are unaffected, but the source
          block object the caller holds is converted.

        ``freeze_kwargs`` override the new freeze (``mesh=``,
        ``compile_cache=``, ``batch_buckets=``, ...); buckets and ctx
        default to this model's.
        """
        kw = {"batch_buckets": self.buckets, "ctx": self._ctx}
        kw.update(freeze_kwargs)
        if mode in ("bf16", "bfloat16"):
            kw.setdefault("compute_dtype", "bfloat16")
            return FrozenModel(self._block, self._input_shape,
                               dtype=self._dtype.name, **kw)
        if mode == "int8":
            from ..contrib.quantization import quantize_net
            qnet = quantize_net(self._block, calib_data=calib_data,
                                exclude=exclude, calib_mode=calib_mode)
            return FrozenModel(qnet, self._input_shape,
                               dtype=self._dtype.name, **kw)
        raise ValueError(
            f"quantize mode must be 'int8' or 'bf16', got {mode!r}")

    # -- checkpoints ------------------------------------------------------
    @staticmethod
    def from_exported(prefix, input_shape, epoch=0, input_name="data",
                      ctx=None, **kwargs):
        """Load a `HybridBlock.export()` checkpoint
        (`prefix-symbol.json` + `prefix-{epoch:04d}.params`) straight
        into a serving-ready FrozenModel — the mxnet-model-server flow."""
        from ..gluon.block import SymbolBlock
        block = SymbolBlock.imports(f"{prefix}-symbol.json", [input_name],
                                    f"{prefix}-{epoch:04d}.params", ctx=ctx)
        return FrozenModel(block, input_shape, ctx=ctx, **kwargs)

    def __repr__(self):
        bits = [f"FrozenModel(input={self._input_shape}",
                f"dtype={self._dtype.name}", f"buckets={self.buckets}",
                f"donate={self.donate}"]
        if self._compute is not None:
            bits.append("compute=bfloat16")
        if self._mesh is not None:
            bits.append(f"mesh={dict(self._mesh.shape)}")
        return ", ".join(bits) + ")"
