"""FrozenModel — ahead-of-time-compiled inference executables.

The serving counterpart of `HybridBlock.hybridize()`: where hybridize
compiles lazily on first call per signature (fine for training, fatal for
tail latency), FrozenModel **freezes** a trained block and precompiles —
at construction time, before traffic arrives — one XLA executable per
batch-size bucket:

* **freeze** — parameters are snapshotted (and optionally `device_put`
  onto an explicit Context) at construction; later training updates to
  the source block do not leak into serving, and no autograd state is
  ever touched (the trace runs with recording off, training=False, so
  BatchNorm uses running stats and dropout is identity);
* **AOT compile** — the forward is traced ONCE (`jax.eval_shape`, no
  device work) to learn the output tree, then `jit.lower(...).compile()`
  builds a concrete executable per bucket — compile cost is paid at
  deploy time, with an explicit warmup execution per bucket so first
  requests never see allocator/runtime lazy-init either;
* **donation** — the padded input batch buffer is donated to the
  executable on backends that support it (TPU/GPU), so steady-state
  serving does not hold two copies of every in-flight batch; params are
  passed (not donated) and live on-device for the model's lifetime.

The reference lineage is `mxnet-model-server`'s frozen
symbol+params checkpoint; `FrozenModel.from_exported` loads exactly that
artifact (`prefix-symbol.json` + `prefix-0000.params`, via SymbolBlock).
"""
from __future__ import annotations

import time

import jax
import numpy as np

from .. import autograd
from .. import perfscope as _ps
from .. import profiler as _prof
from ..diagnostics import flight as _flight
from ..gluon.block import HybridBlock, _flatten_out, _unflatten_out
from ..gluon.parameter import DeferredInitializationError, _ParamTraceScope
from ..ndarray import NDArray
from ..ndarray import random as ndrandom
from .errors import InvalidInputError

__all__ = ["FrozenModel", "default_buckets"]


def default_buckets(max_batch: int | None = None):
    """Power-of-two bucket ladder, overridable via MXTPU_SERVING_BUCKETS
    (comma-separated batch sizes)."""
    from ..autotune.knobs import env_str
    env = env_str("MXTPU_SERVING_BUCKETS")
    if env:
        sizes = sorted({int(s) for s in env.split(",") if s.strip()})
    else:
        sizes, b = [], 1
        cap = int(max_batch or 32)
        while b < cap:
            sizes.append(b)
            b *= 2
        sizes.append(cap)
        sizes = sorted(set(sizes))
    if not sizes or sizes[0] < 1:
        raise ValueError(f"invalid serving buckets {sizes!r}")
    return tuple(sizes)


class FrozenModel:
    """An immutable, serving-ready snapshot of a Gluon block.

    Parameters
    ----------
    block : HybridBlock (SymbolBlock included)
        Trained model; params must be initialized (or initializable from
        `input_shape` via one deferred-shape inference pass).
    input_shape : tuple
        PER-SAMPLE input shape (no batch dimension).
    dtype : str
        Input dtype requests must match.
    batch_buckets : sequence of int, optional
        Batch sizes to precompile; default `default_buckets()`.
    ctx : Context, optional
        Freeze params onto this device (default: wherever they live).
    warmup : bool
        Execute each compiled bucket once at construction (default True).
    donate : bool, optional
        Donate the input buffer to the executable. Default: only on
        backends that support donation (not CPU, where XLA would warn
        and ignore it).
    """

    def __init__(self, block, input_shape, dtype="float32",
                 batch_buckets=None, ctx=None, warmup=True, donate=None):
        if not isinstance(block, HybridBlock):
            raise TypeError("FrozenModel requires a HybridBlock (or "
                            f"SymbolBlock), got {type(block).__name__}")
        self._block = block
        self._input_shape = tuple(int(d) for d in input_shape)
        self._dtype = np.dtype(dtype)
        self._ctx = ctx
        self.buckets = tuple(sorted(batch_buckets)) if batch_buckets \
            else default_buckets()

        params = self._frozen_params(block)
        self._param_ids = [id(p) for p in params]
        self._param_raws = tuple(p.data()._data if ctx is None
                                 else jax.device_put(p.data()._data,
                                                     ctx.device)
                                 for p in params)
        if donate is None:
            donate = jax.default_backend() not in ("cpu",)
        self.donate = bool(donate)

        self._key = jax.random.PRNGKey(0)  # inference: dropout is identity
        self._out_tree = None
        raw_fn = self._make_raw_fn()
        self._jit = jax.jit(raw_fn,
                            donate_argnums=(2,) if self.donate else ())
        self._exec = {}
        for b in self.buckets:
            self._compile_bucket(b, warmup)
        _prof.set_gauge("serving.compiled_buckets", len(self._exec),
                        "serving")

    # -- freezing ---------------------------------------------------------
    def _frozen_params(self, block):
        params = list(block.collect_params().values())
        try:
            for p in params:
                p.data()
        except DeferredInitializationError:
            # one shape-inference forward on a zero sample completes
            # deferred init (same move as HybridBlock._call_cached)
            from .. import ndarray as nd_mod
            with autograd.pause(False):
                block(nd_mod.zeros((1,) + self._input_shape,
                                   dtype=self._dtype.name))
            params = list(block.collect_params().values())
            for p in params:
                p.data()
        return params

    # -- tracing / compilation -------------------------------------------
    def _make_raw_fn(self):
        block = self._block
        param_ids = self._param_ids
        info = {}

        def raw_fn(key_raw, p_raws, x_raw):
            sub = dict(zip(param_ids, p_raws))
            # recording=False, training=False: pure inference semantics —
            # BN running stats are read, never written; dropout passes
            # through; nothing lands on any autograd tape
            with _ParamTraceScope(sub), autograd._Scope(False, False), \
                    ndrandom._TraceKeyScope(key_raw):
                out = block.forward(NDArray(x_raw))
                leaves, tree = _flatten_out(out)
            info["tree"] = tree
            return tuple(x._data for x in leaves)

        self._raw_info = info
        return raw_fn

    def _compile_bucket(self, b, warmup):
        shape = (b,) + self._input_shape
        x_spec = jax.ShapeDtypeStruct(shape, self._dtype)
        if _flight._REC is not None:
            _flight.record("compile", f"serving.freeze:b{b}",
                           {"shape": list(shape), "dtype": str(self._dtype)})
        with _prof.Scope(f"serving.compile:b{b}", "serving", sync=False):
            lowered = self._jit.lower(self._key, self._param_raws, x_spec)
            self._exec[b] = lowered.compile()
        if self._out_tree is None:
            self._out_tree = self._raw_info["tree"]
        if _ps._PS is not None:
            # the bucket is already lowered — the roofline verdict is a
            # free host-side read here (no extra trace). The compiled
            # executable rides along so commscope's collective
            # extraction reads the optimized HLO without compiling again
            _ps.analyze_lowered(
                lowered, name=self.program_name(b),
                dtype=self._dtype, kind="serving_bucket",
                extra={"bucket": b}, compiled=self._exec[b])
        _prof.counter("serving.compiles", "serving").increment()
        if warmup:
            x0 = np.zeros(shape, self._dtype)
            outs = self._exec[b](self._key, self._param_raws,
                                 jax.numpy.asarray(x0))
            jax.block_until_ready(outs)
            _prof.counter("serving.warmup_runs", "serving").increment()

    # -- execution --------------------------------------------------------
    @property
    def input_shape(self):
        return self._input_shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def program_name(self, b: int) -> str:
        """The perfscope/commscope program-table name of one bucket's
        AOT executable — the ONE join key servescope, /healthz and
        /stats use to attach roofline + resharding verdicts."""
        return f"serving:{self._block.name}:b{b}"

    def comm_verdicts(self) -> dict:
        """Per-bucket commscope resharding verdict for the compiled
        executables: ``{bucket: {resharding_collectives, hlo_available,
        collective_count, collective_bytes}}``. An accidental
        all-gather on the serve path is a per-request p99 catastrophe
        (docs/commscope.md), so the deep /healthz and /stats surface
        this verdict. Empty when commscope never captured the buckets
        (unarmed, or compiled before arming). Never raises."""
        out = {}
        try:
            from .. import commscope as _cs
            progs = {p.get("name"): p for p in _cs.programs()}
        except Exception:  # noqa: BLE001
            return out
        for b in self.buckets:
            rec = progs.get(self.program_name(b))
            if not isinstance(rec, dict):
                continue
            totals = rec.get("totals") or {}
            out[str(b)] = {
                "resharding_collectives":
                    rec.get("resharding_collectives", 0),
                "hlo_available": rec.get("hlo_available", True),
                "collective_count": totals.get("count"),
                "collective_bytes": totals.get("bytes"),
            }
        return out

    def roofline_verdicts(self) -> dict:
        """Per-bucket perfscope roofline verdict for the compiled
        executables (``{bucket: verdict}``); empty when perfscope never
        captured them. Never raises."""
        out = {}
        try:
            from .. import perfscope as _ps_mod
            progs = {p.get("name"): p for p in _ps_mod.programs()}
        except Exception:  # noqa: BLE001
            return out
        for b in self.buckets:
            rec = progs.get(self.program_name(b))
            if isinstance(rec, dict):
                out[str(b)] = rec.get("verdict")
        return out

    def bucket_for(self, n: int) -> int:
        """Smallest compiled bucket that fits n samples."""
        for b in self.buckets:
            if b >= n:
                return b
        raise InvalidInputError(
            f"batch of {n} exceeds the largest compiled bucket "
            f"({self.buckets[-1]}); recompile with larger batch_buckets")

    def validate(self, x: np.ndarray):
        """Shape/dtype admission check for ONE sample (no batch dim)."""
        if tuple(x.shape) != self._input_shape:
            raise InvalidInputError(
                f"sample shape {tuple(x.shape)} != expected "
                f"{self._input_shape}")
        if np.dtype(x.dtype) != self._dtype:
            raise InvalidInputError(
                f"sample dtype {x.dtype} != expected {self._dtype.name}")

    def run_raw(self, x) -> tuple:
        """Execute the bucket exactly matching `x.shape[0]`. Returns the
        flat tuple of raw output arrays (still batched/padded)."""
        n = int(x.shape[0])
        ex = self._exec.get(n)
        if ex is None:
            raise InvalidInputError(
                f"no compiled bucket for batch {n}; buckets={self.buckets}")
        return ex(self._key, self._param_raws, jax.numpy.asarray(x))

    def predict_batch(self, x: np.ndarray, timings: dict | None = None) \
            -> list:
        """Serve a host batch of n <= max_batch samples: pad up to the
        bucket, execute, slice back to n. Returns the per-output list of
        np arrays (length n each). Rows are independent in inference
        graphs, so padding rows never changes real rows' values.

        ``timings``: when a dict is passed (servescope's sampled path)
        it is filled with the per-phase wall split ``{"pad_ms",
        "exec_ms", "unpad_ms"}`` — pad copy, executable wall (transfer
        + device, closed by an explicit ``block_until_ready`` so the
        boundary is real on async backends), and the unpad slice/host
        conversion. With ``timings=None`` the path is unchanged (the
        conversion itself is the sync)."""
        n = int(x.shape[0])
        b = self.bucket_for(n)
        if timings is None:
            if b != n:
                pad = np.zeros((b - n,) + self._input_shape, self._dtype)
                x = np.concatenate([np.ascontiguousarray(x), pad], axis=0)
            outs = self.run_raw(x)
            return [np.asarray(o)[:n] for o in outs]
        t0 = time.perf_counter()
        if b != n:
            pad = np.zeros((b - n,) + self._input_shape, self._dtype)
            x = np.concatenate([np.ascontiguousarray(x), pad], axis=0)
        t1 = time.perf_counter()
        outs = self.run_raw(x)
        jax.block_until_ready(outs)
        t2 = time.perf_counter()
        res = [np.asarray(o)[:n] for o in outs]
        t3 = time.perf_counter()
        timings["pad_ms"] = (t1 - t0) * 1e3
        timings["exec_ms"] = (t2 - t1) * 1e3
        timings["unpad_ms"] = (t3 - t2) * 1e3
        return res

    def __call__(self, x):
        """NDArray-level convenience matching `block(x)`: accepts an
        NDArray or np array WITH batch dim, returns NDArray(s) in the
        block's output structure."""
        x_np = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        outs = self.predict_batch(x_np.astype(self._dtype, copy=False))
        leaves = [NDArray(jax.numpy.asarray(o)) for o in outs]
        return _unflatten_out(self._out_tree, leaves)

    # -- checkpoints ------------------------------------------------------
    @staticmethod
    def from_exported(prefix, input_shape, epoch=0, input_name="data",
                      ctx=None, **kwargs):
        """Load a `HybridBlock.export()` checkpoint
        (`prefix-symbol.json` + `prefix-{epoch:04d}.params`) straight
        into a serving-ready FrozenModel — the mxnet-model-server flow."""
        from ..gluon.block import SymbolBlock
        block = SymbolBlock.imports(f"{prefix}-symbol.json", [input_name],
                                    f"{prefix}-{epoch:04d}.params", ctx=ctx)
        return FrozenModel(block, input_shape, ctx=ctx, **kwargs)

    def __repr__(self):
        return (f"FrozenModel(input={self._input_shape}, "
                f"dtype={self._dtype.name}, buckets={self.buckets}, "
                f"donate={self.donate})")
