"""Serving error taxonomy. Every error carries an HTTP-ish status code so
the stdlib front end (serving/server.py) can map rejections to proper
client/server status lines, and callers embedding the batcher directly
can branch on `code` without string matching.

Contract (tested in tests/test_serving.py): a request is NEVER silently
dropped — every accepted `submit()` either resolves with a result or
raises one of these from `wait()`, including during shutdown drain.
"""
from __future__ import annotations

__all__ = ["ServingError", "InvalidInputError", "QueueFullError",
           "DeadlineExceededError", "ServerClosedError",
           "ReshardingGateError"]


class ServingError(RuntimeError):
    """Base serving failure; `code` follows HTTP semantics."""

    code = 500

    def to_json(self) -> dict:
        return {"error": type(self).__name__, "message": str(self),
                "code": self.code}


class InvalidInputError(ServingError):
    """Malformed request: wrong shape/dtype, or larger than the largest
    compiled bucket (client error, not capacity)."""

    code = 400


class QueueFullError(ServingError):
    """Backpressure: the bounded request queue is at capacity — fail fast
    so the client can retry/shed instead of stacking latency."""

    code = 429


class DeadlineExceededError(ServingError):
    """The request's deadline passed before (or while) it could be
    served; it was rejected, not dropped."""

    code = 504


class ServerClosedError(ServingError):
    """The server/batcher is draining or stopped; no new work accepted."""

    code = 503


class ReshardingGateError(ServingError):
    """A mesh-sharded FrozenModel compile produced resharding
    collectives (commscope's accidental-all-gather verdict) on the
    serve path — a per-request p99 catastrophe, refused at deploy time
    rather than discovered in production tails. Fix the layout (or pass
    ``reshard_gate=False`` to serve degraded, flagged in /healthz)."""

    code = 500
