"""Monitor (parity: python/mxnet/monitor.py): per-batch inspection of a
Module executor's arrays — outputs, arguments, gradients, aux — with a
stat function and interval. The reference hooks the C++ executor's output
callbacks; here `tic()` snapshots nothing and `toc()` reads the executor
dicts after the step (same observable behavior, no async machinery to
intercept because XLA owns the schedule)."""
from __future__ import annotations

import logging
import re

import numpy as np

from . import profiler as _prof

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or (lambda x: np.abs(x).mean())
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self._sources = []
        self.queue = []

    def install(self, module_or_exec):
        """Attach to a Module, BucketingModule, or raw Executor. Executors
        are resolved at toc() time, so rebinds and buckets created after
        install are still observed."""
        if not (hasattr(module_or_exec, "_exec")
                or hasattr(module_or_exec, "_buckets")
                or hasattr(module_or_exec, "arg_dict")):
            raise TypeError(f"cannot monitor {type(module_or_exec).__name__};"
                            " expected Module, BucketingModule or Executor")
        self._sources.append(module_or_exec)
        return self

    def _live_execs(self):
        out = []
        for src in self._sources:
            if hasattr(src, "arg_dict"):          # raw Executor
                out.append(src)
            elif hasattr(src, "_buckets"):        # BucketingModule
                out.extend(m._exec for m in src._buckets.values()
                           if m._exec is not None)
            elif getattr(src, "_exec", None) is not None:
                out.append(src._exec)
        return out

    def tic(self):
        """Start-of-batch: arm collection for this step if due."""
        self.activated = (self.step % self.interval == 0)
        self.queue = []
        self.step += 1

    def _collect(self, ex):
        rows = []
        # an executor may have no outputs (e.g. bound for backward only, or
        # a partial bind mid-rebuild) — treat that as an empty output dict
        # instead of indexing blindly
        try:
            outputs = ex.outputs or []
        except Exception:
            outputs = []
        outs = {f"output{i}": o for i, o in enumerate(outputs)}
        for source in (ex.arg_dict, ex.aux_dict, ex.grad_dict, outs):
            for name, arr in source.items():
                tag = name if source is not ex.grad_dict else name + "_grad"
                if arr is None or not self.pattern.match(tag):
                    continue
                rows.append((self.step - 1, tag,
                             self.stat_func(np.asarray(arr._data))))
        return rows

    def toc(self):
        """End-of-batch: collect stats from every installed executor. Each
        scalar stat is also published as a `monitor/<tag>` gauge in the
        profiler counters registry — the single stats path shared with
        bench/profiler consumers."""
        if not self.activated:
            return []
        res = []
        for ex in self._live_execs():
            res.extend(self._collect(ex))
        if self.sort:
            res.sort(key=lambda r: r[1])
        for _step, tag, value in res:
            v = np.asarray(value)
            # only scalar numeric stats become gauges; custom stat funcs may
            # return strings/arrays, which stay rows-only
            if v.size == 1 and np.issubdtype(v.dtype, np.number):
                _prof.set_gauge(tag, float(v.reshape(())), domain="monitor")
        self.queue = res
        return res

    def toc_print(self):
        for step, name, value in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, value)
