"""Windowed device-timeline capture: a bounded N-step jax-profiler trace.

A :class:`CaptureWindow` wraps a few steps of the steady train loop in
``jax.profiler.trace`` (via the :mod:`..profiler.tpu` bridge) and turns
the artifact into the measured summary :mod:`.ingest` derives. The
lifecycle is built for a hot loop that must not care about profiling:

* ``start()`` — rotate old artifact dirs (keep the newest
  ``MXTPU_DEVICESCOPE_KEEP``, default 3, so repeated bench runs never
  grow the dir unboundedly), snapshot the gap-taxonomy counters
  (``io.wait_ms`` + ``trainloop.dispatch_ms``), start the device trace.
  A profiler that is already tracing (``profile_xla``, a concurrent
  window) or unavailable DECLINES the window — counted, never raised —
  and every later call is a no-op.
* ``step(n, dispatch_ms=...)`` — the loop's per-dispatch mark; on the
  Nth captured step the trace stops *immediately* (keeping the window
  bounded no matter how long the run is) but ingestion is DEFERRED: the
  artifact parse runs lazily at the first ``summary()`` call, after the
  steady phase, so the capture's in-loop cost is the tracing overhead
  plus one ``stop_trace`` — not a JSON parse in the middle of the
  measured region.
* ``stop()`` — idempotent early stop (loop ended before N steps; the
  context-manager exit calls it).

The module-global active window is what instrumented executors
(:meth:`TrainLoop.run_chunk`) mark, so ``devicescope.capture()`` works
around ``loop.fit(...)`` with no user-side marking.
"""
from __future__ import annotations

import os
import shutil
import time

from ..profiler import tpu as _tpu
from ..profiler.counters import (counter as _counter,
                                 counters as _registry_snapshot,
                                 set_gauge as _set_gauge)
from . import ingest as _ingest

__all__ = ["CaptureWindow", "base_dir", "rotate_dirs", "DEFAULT_KEEP"]

DEFAULT_KEEP = 3

# counters the gap taxonomy reads as window-scoped deltas; the io stage
# walls (read/decode/put) split the input_starved bucket into
# disk-vs-decode-vs-transfer attribution (ingest.input_starved_split)
_TRACKED = {"io_wait_ms": "io/io.wait_ms",
            "io_read_ms": "io/io.read_ms",
            "io_decode_ms": "io/io.decode_ms",
            "io_put_ms": "io/io.put_ms",
            "dispatch_ms": "trainloop/trainloop.dispatch_ms"}


def base_dir() -> str:
    from ..autotune.knobs import env_str
    return env_str("MXTPU_DEVICESCOPE_DIR", "/tmp/mxtpu_devicescope")


def _env_keep() -> int:
    from ..autotune.knobs import env_int
    return max(1, env_int("MXTPU_DEVICESCOPE_KEEP", DEFAULT_KEEP,
                          on_error="default"))


def rotate_dirs(base: str, keep: int | None = None) -> int:
    """Delete the oldest ``win_*`` capture dirs under ``base`` so at
    most ``keep - 1`` remain (the caller is about to create one more).
    Returns how many were removed. Best-effort, never raises."""
    keep = _env_keep() if keep is None else max(1, int(keep))
    removed = 0
    try:
        subdirs = [os.path.join(base, d) for d in os.listdir(base)
                   if d.startswith("win_")
                   and os.path.isdir(os.path.join(base, d))]
        subdirs.sort(key=os.path.getmtime)
        while len(subdirs) > keep - 1:
            victim = subdirs.pop(0)
            shutil.rmtree(victim, ignore_errors=True)
            removed += 1
    except Exception:  # noqa: BLE001 — rotation is housekeeping
        pass
    return removed


_seq = [0]


class CaptureWindow:
    """One bounded capture window. States: created → active →
    stopped (→ ingested) | declined."""

    def __init__(self, steps: int = 10, logdir: str | None = None):
        self.requested_steps = max(1, int(steps))
        if logdir is None:
            _seq[0] += 1
            logdir = os.path.join(
                base_dir(),
                f"win_{os.getpid()}_{_seq[0]:03d}_{int(time.time())}")
        self.logdir = logdir
        self.steps_done = 0
        self.dispatch_ms = 0.0        # caller-accumulated dispatch wall
        self.workload = None          # who stepped it: "train"/"serving"
                                      # ("mixed" if both) — consumers
                                      # joining against a window must
                                      # check this, not just freshness
        self.wall_ms = None
        self.state = "created"
        self.completed_at = None      # time.monotonic() at trace stop —
                                      # budgets only reconcile against
                                      # windows completed AFTER they began
        self.trace_file = None
        self._t0 = None
        self._snap0 = {}
        self._counters_delta = {}
        self._summary = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self.state != "created":
            return self
        self._snap0 = self._snapshot()
        if not _tpu.start_device_trace(self.logdir):
            # already tracing (profile_xla / a concurrent window) or a
            # stripped profiler build: decline, don't break the loop.
            # NOTHING was created on disk (jax makes the logdir itself),
            # so a declined window can never count against — or evict
            # real artifacts from — the rotation budget below
            self.state = "declined"
            _counter("devicescope.declined", "devicescope").increment()
            return self
        # trim the oldest artifact dirs now that THIS capture is real:
        # keep-1 survivors + the dir jax writes at stop = keep total
        rotate_dirs(os.path.dirname(self.logdir) or base_dir())
        self._t0 = time.perf_counter()
        self.state = "active"
        from . import _set_active
        _set_active(self)
        return self

    def step(self, n: int = 1, dispatch_ms: float = 0.0, sync=None,
             workload: str | None = None):
        """Mark n train steps (one dispatch). Stops the trace the
        moment the requested step count is reached.

        ``sync``: optional zero-arg barrier called ONLY when this mark
        triggers the stop, BEFORE the trace closes. Through an async
        dispatch path the host mark runs ahead of the device (a relay
        returns at enqueue), so without a barrier the window could
        close with its own steps still in flight and under-count busy
        time. Pass a host value fetch of the step's result (bench
        fetches the latest loss — steps chain through params, so that
        one fetch completes them all). Never raises.

        ``workload``: identity stamp ("train"/"serving") so consumers
        that join against the last window (servescope's device_exec
        upgrade) can tell whose dispatches it measured — a fresh
        window is not enough when train and serve share a process.
        Steppers with different stamps degrade the window to "mixed"."""
        if self.state != "active":
            return
        if workload is not None:
            self.workload = (workload if self.workload in (None, workload)
                             else "mixed")
        self.steps_done += max(1, int(n))
        self.dispatch_ms += float(dispatch_ms or 0.0)
        if self.steps_done >= self.requested_steps:
            if sync is not None:
                try:
                    sync()
                except Exception:  # noqa: BLE001 — a failed barrier
                    pass           # costs accuracy, never the run
            self._stop_trace()

    def stop(self):
        """Idempotent early stop (context-manager exit / loop end)."""
        if self.state == "active":
            self._stop_trace()
        return self

    def _stop_trace(self):
        self.wall_ms = (time.perf_counter() - self._t0) * 1e3
        self.completed_at = time.monotonic()
        _tpu.stop_device_trace()
        snap1 = self._snapshot()
        self._counters_delta = {
            k: max(0.0, snap1.get(k, 0.0) - self._snap0.get(k, 0.0))
            for k in _TRACKED}
        # the caller-accumulated dispatch wall (FusedTrainStep loops have
        # no dispatch counter) adds to the counter-based delta
        self._counters_delta["dispatch_ms"] += self.dispatch_ms
        self.state = "stopped"
        _counter("devicescope.windows", "devicescope").increment()
        _counter("devicescope.steps_captured",
                 "devicescope").increment(self.steps_done)
        from . import _set_active, _set_last
        _set_active(None)
        _set_last(self)

    # -- context manager --------------------------------------------------
    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results ----------------------------------------------------------
    @property
    def active(self) -> bool:
        return self.state == "active"

    @property
    def complete(self) -> bool:
        """True when the window captured its full requested step count."""
        return self.state == "stopped" \
            and self.steps_done >= self.requested_steps

    def summary(self):
        """The ingested measured summary (lazy: the artifact is parsed
        on first access, cached after). None until the window stopped,
        or when it declined."""
        if self.state != "stopped":
            return None
        if self._summary is None:
            self._summary = self._ingest()
        return self._summary

    def _ingest(self):
        try:
            events, self.trace_file = _ingest.load_trace_events(self.logdir)
            program_map, programs, comms = {}, [], []
            try:
                from . import program_map as _pm
                program_map = _pm()
                from .. import perfscope as _ps
                programs = _ps.programs()
            except Exception:  # noqa: BLE001
                pass
            try:
                from ..commscope import extract as _cse
                comms = _cse.programs()
            except Exception:  # noqa: BLE001
                pass
            s = _ingest.summarize(
                events, self.wall_ms, self.steps_done,
                counters_delta=self._counters_delta,
                program_map=program_map, programs=programs,
                comms_programs=comms)
            s["window"] = {
                "path": self.logdir,
                "trace_file": self.trace_file,
                "steps": self.steps_done,
                "requested_steps": self.requested_steps,
                "wall_ms": round(self.wall_ms, 4)
                if self.wall_ms is not None else None,
                "complete": self.complete,
            }
            s.setdefault("reconciliation", None)
            if s.get("error"):
                _counter("devicescope.ingest_errors",
                         "devicescope").increment()
            if s.get("busy_fraction") is not None:
                _set_gauge("devicescope.busy_fraction",
                           s["busy_fraction"], "devicescope")
            ps = s.get("per_step") or {}
            for key, gauge in (("device_busy_ms",
                                "devicescope.device_busy_ms"),
                               ("collective_ms",
                                "devicescope.collective_ms"),
                               ("idle_ms", "devicescope.idle_ms")):
                if isinstance(ps.get(key), (int, float)):
                    _set_gauge(gauge, ps[key], "devicescope")
            return s
        except Exception as e:  # noqa: BLE001 — measurement must never
            _counter("devicescope.ingest_errors",    # break the run
                     "devicescope").increment()
            return {"window": {"path": self.logdir, "trace_file": None,
                               "steps": self.steps_done,
                               "requested_steps": self.requested_steps,
                               "wall_ms": self.wall_ms,
                               "complete": self.complete},
                    "busy_fraction": None, "per_step": None,
                    "top_ops": [], "gaps": None, "reconciliation": None,
                    "error": f"{type(e).__name__}: {e}"[:200]}

    @staticmethod
    def _snapshot():
        snap = _registry_snapshot()
        out = {}
        for key, full in _TRACKED.items():
            v = snap.get(full)
            out[key] = float(v) if isinstance(v, (int, float)) else 0.0
        return out
