"""Trace ingestion: Chrome-trace artifacts → measured device truth.

``jax.profiler.trace`` (driven by :mod:`.window`) writes a TensorBoard
profile directory whose ``<host>.trace.json.gz`` is a Chrome trace-event
file: ``M`` metadata events naming processes/threads, and ``X`` complete
events for everything the backend timed. The events that matter here are
the **device-op events** — on XLA:CPU they run on the client/Eigen
threadpool lanes and carry ``args.hlo_op``/``args.hlo_module``; on TPU
they additionally live under ``/device:TPU:n`` processes. Everything
else (the python lane, ``TfrtCpuBuffer::Await``, threadpool bookkeeping)
is host machinery.

From those events this module derives the measured ground truth the
analytic layers (perfscope's probe, commscope's ring estimates) are
reconciled against:

* **busy fraction** — the union of device-op intervals across every
  device lane, over the host-measured window wall: the chip was doing
  *something* during that fraction of the window. Union, not sum: four
  fake devices (or four TPU cores) running the same all-reduce
  concurrently are one busy interval, comparable with wall-clock step
  components.
* **top-K ops** — per-op device time (summed across lanes — the
  attribution view: "where do device-milliseconds go"), joined to
  perfscope's program table via the ``hlo_module`` name so each hot
  fusion carries its roofline verdict.
* **measured collectives** — device events whose op name matches the
  commscope kind taxonomy, as a union time (comparable with the step
  budget's ``collective`` component) and per kind, with the mesh-axis
  attribution joined from commscope's static inventory of the same
  program.
* **idle-gap taxonomy** — gaps in the union timeline, histogrammed, and
  the window's total idle classified input-starved / dispatch-serialized
  / host-gap from the ``io.wait_ms`` and dispatch-wall counter deltas
  the window snapshotted.

Every entry point is never-raise by contract: a malformed artifact (the
profiler was killed mid-write, an XLA upgrade renamed a lane) degrades
to an empty summary, not a crashed bench run. tests/test_devicescope.py
pins the edge cases (empty trace, single event, overlapping lanes,
missing metadata) against a checked-in real XLA:CPU artifact.
"""
from __future__ import annotations

import gzip
import json
import os
import re

from ..commscope.hlo import COLLECTIVE_KINDS as _CS_KINDS

__all__ = ["find_trace_file", "load_trace_events", "device_events",
           "union_intervals", "collective_kind_of", "summarize",
           "GAP_BUCKETS_MS"]

# gap-duration histogram bucket upper bounds (milliseconds) + overflow
GAP_BUCKETS_MS = (0.1, 1.0, 10.0, 100.0)

# measured collective op kinds ARE commscope's closed taxonomy (one
# home; a kind added there is measured here automatically), prefix-
# matched against the HLO op name ("all-reduce.5", "all-gather-start.2"
# and XLA:CPU's plain "all-to-all" all resolve). "other" is a bucket,
# not a spelling — nothing to prefix-match.
_COLLECTIVE_PREFIXES = tuple(k for k in _CS_KINDS if k != "other")

# "dot.3", "reduce.58.clone", "fusion.12.remat" → one op family each
_TRAILING_ID = re.compile(r"(\.(\d+|clone|remat\d*))+$")


def find_trace_file(path):
    """Newest ``*.trace.json(.gz)`` under ``path`` (a profile logdir),
    or ``path`` itself when it already names a file. None when nothing
    is there — the profiler wrote no artifact."""
    try:
        if os.path.isfile(path):
            return path
        best, best_mtime = None, -1.0
        for root, _dirs, files in os.walk(path):
            for fn in files:
                if fn.endswith((".trace.json.gz", ".trace.json")):
                    p = os.path.join(root, fn)
                    m = os.path.getmtime(p)
                    if m > best_mtime:
                        best, best_mtime = p, m
        return best
    except Exception:  # noqa: BLE001 — discovery must never raise
        return None


def load_trace_events(path):
    """The trace-event list from one artifact (file or profile logdir).
    Accepts both container shapes (bare list / ``{"traceEvents": []}``)
    and gzipped or plain JSON. Returns ``(events, trace_file)``;
    ``([], None)`` when nothing loadable is found."""
    f = find_trace_file(path) if path else None
    if not f:
        return [], None
    try:
        opener = gzip.open if f.endswith(".gz") else open
        with opener(f, "rt") as fh:
            doc = json.load(fh)
        if isinstance(doc, dict):
            doc = doc.get("traceEvents")
        if not isinstance(doc, list):
            return [], f
        return [e for e in doc if isinstance(e, dict)], f
    except Exception:  # noqa: BLE001 — a torn artifact is not a crash
        return [], f


def _num(x):
    return x if isinstance(x, (int, float)) and not isinstance(x, bool) \
        else None


def device_events(events):
    """Split a raw event list into (device_ops, lane_meta).

    A device-op event is an ``X`` event that carries ``args.hlo_op`` or
    lives under a process whose name contains ``/device:`` (the TPU
    layout; XLA:CPU op events run on host threadpool lanes and are
    recognized by their args). Returned ops are normalized dicts
    ``{lane, ts, dur, name, op, module}`` with ts/dur in microseconds;
    lane_meta maps ``(pid, tid) -> {process, thread}``."""
    procs, threads = {}, {}
    for e in events:
        try:
            if e.get("ph") != "M":
                continue
            args = e.get("args") or {}
            if e.get("name") == "process_name":
                procs[e.get("pid")] = str(args.get("name", ""))
            elif e.get("name") == "thread_name":
                threads[(e.get("pid"), e.get("tid"))] = \
                    str(args.get("name", ""))
        except Exception:  # noqa: BLE001
            continue
    ops, lanes = [], {}
    for e in events:
        try:
            if e.get("ph") != "X":
                continue
            ts, dur = _num(e.get("ts")), _num(e.get("dur"))
            if ts is None or dur is None or dur < 0:
                continue
            args = e.get("args") or {}
            if not isinstance(args, dict):
                args = {}
            pid, tid = e.get("pid"), e.get("tid")
            proc = procs.get(pid, "")
            is_dev = "hlo_op" in args or "/device:" in proc
            if not is_dev:
                continue
            name = str(e.get("name") or args.get("hlo_op") or "?")
            lane = (pid, tid)
            lanes.setdefault(lane, {
                "pid": pid, "tid": tid, "process": proc,
                "thread": threads.get(lane, "")})
            ops.append({"lane": lane, "ts": float(ts), "dur": float(dur),
                        "name": name,
                        "op": _TRAILING_ID.sub("", name),
                        "module": args.get("hlo_module")})
        except Exception:  # noqa: BLE001 — one bad event never sinks a trace
            continue
    return ops, lanes


def union_intervals(intervals):
    """Merge ``(start, end)`` pairs; returns (merged_list, total_length).
    Tolerates unordered and overlapping input (concurrent lanes)."""
    ivs = sorted((float(a), float(b)) for a, b in intervals if b > a)
    merged, total = [], 0.0
    for a, b in ivs:
        if merged and a <= merged[-1][1]:
            if b > merged[-1][1]:
                total += b - merged[-1][1]
                merged[-1][1] = b
        else:
            merged.append([a, b])
            total += b - a
    return [(a, b) for a, b in merged], total


def collective_kind_of(op_name):
    """The commscope kind a device-op name measures, or None for a
    non-collective op."""
    n = str(op_name)
    for k in _COLLECTIVE_PREFIXES:
        if n.startswith(k):
            return k
    return None


def _gap_histogram(gaps_ms):
    hist = {str(b): 0 for b in GAP_BUCKETS_MS}
    hist["+Inf"] = 0
    for g in gaps_ms:
        for b in GAP_BUCKETS_MS:
            if g <= b:
                hist[str(b)] += 1
                break
        else:
            hist["+Inf"] += 1
    return hist


def _starved_split(input_starved_ms, counters_delta):
    """Split the ``input_starved`` bucket into disk / decode / transfer
    attribution from the io pipeline's per-stage wall deltas
    (``io.read_ms`` / ``io.decode_ms`` / ``io.put_ms``).

    The stage walls are not spans of the idle gaps themselves — decode
    runs on N workers concurrently with compute — so they are used as
    attribution WEIGHTS: each stage's share of the starved time is its
    share of the summed stage wall, scaled so the split sums to
    ``input_starved_ms``. Returns None when there is nothing to split
    (no starvation, or a pre-pipeline artifact with no stage walls) —
    absent, not zeros, so old artifacts stay schema-stable."""
    if not input_starved_ms or input_starved_ms <= 0:
        return None
    read = max(0.0, float(counters_delta.get("io_read_ms") or 0.0))
    decode = max(0.0, float(counters_delta.get("io_decode_ms") or 0.0))
    put = max(0.0, float(counters_delta.get("io_put_ms") or 0.0))
    total = read + decode + put
    if total <= 0:
        return None
    shares = {"read_ms": read, "decode_ms": decode, "transfer_ms": put}
    dominant = {"read_ms": "read", "decode_ms": "decode",
                "transfer_ms": "transfer"}[max(shares, key=shares.get)]
    return {
        **{k: round(v / total * input_starved_ms, 4)
           for k, v in shares.items()},
        "dominant": dominant,
    }


def _axis_map_for(program, comms_programs):
    """kind -> mesh axis for one program, from commscope's static
    inventory (None when ambiguous: two axes running the same kind).
    Delegates to commscope's :func:`axis_by_kind` — one home for the
    join rule — with a record-matching shim over the caller-provided
    inventory snapshot (the pure-data path fixture tests drive)."""
    recs = [r for r in comms_programs or []
            if isinstance(r, dict) and r.get("name") == program]
    if not recs:
        return {}
    try:
        from ..commscope.extract import axis_by_kind
    except Exception:  # noqa: BLE001 — ingest stays standalone-usable
        return {}
    out = {}
    for rec in recs:
        for k, ax in axis_by_kind(rec).items():
            if k in out and out[k] != ax:
                out[k] = None          # ambiguous across records
            else:
                out[k] = ax
    return out


def summarize(events, wall_ms, steps, counters_delta=None,
              program_map=None, programs=None, comms_programs=None,
              top_k=10):
    """Derive the measured-truth summary from one window's raw events.

    wall_ms / steps: the HOST-measured window wall and the step count
    the caller marked — the denominators every per-step number uses.
    counters_delta: ``{"io_wait_ms", "dispatch_ms"}`` deltas over the
    window (gap taxonomy inputs), plus the optional io stage walls
    (``io_read_ms`` / ``io_decode_ms`` / ``io_put_ms``) that split the
    input_starved bucket into disk/decode/transfer attribution. program_map: ``hlo_module name ->
    perfscope program name`` (the join key recorded at compile capture);
    programs: perfscope's program table (roofline verdicts);
    comms_programs: commscope's inventory (mesh-axis attribution).
    Never raises."""
    try:
        return _summarize(events, wall_ms, steps, counters_delta or {},
                          program_map or {}, programs or [],
                          comms_programs or [], int(top_k))
    except Exception as e:  # noqa: BLE001 — a parse bug costs the summary,
        return {                       # never the run that asked for it
            "busy_fraction": None, "busy_ms": 0.0, "idle_ms": None,
            "per_step": None, "lanes": [], "top_ops": [],
            "collectives": {"union_ms": 0.0, "sum_ms": 0.0, "by_kind": []},
            "gaps": None, "device_events": 0,
            "error": f"{type(e).__name__}: {e}"[:200],
        }


def _summarize(events, wall_ms, steps, counters_delta, program_map,
               programs, comms_programs, top_k):
    ops, lanes = device_events(events)
    steps = max(1, int(steps or 1))
    wall = float(wall_ms) if _num(wall_ms) else None

    busy_iv, busy_us = union_intervals(
        (o["ts"], o["ts"] + o["dur"]) for o in ops)
    busy_ms = busy_us / 1e3
    # per-lane busy (diagnostic detail, not the headline denominator):
    # one grouping pass, not a rescan of the op list per lane
    ops_by_lane: "dict[tuple, list]" = {}
    for o in ops:
        ops_by_lane.setdefault(o["lane"], []).append(o)
    lane_rows = []
    for lane, meta in lanes.items():
        lane_ops = ops_by_lane.get(lane, [])
        _, lb = union_intervals((o["ts"], o["ts"] + o["dur"])
                                for o in lane_ops)
        lane_rows.append(dict(meta, events=len(lane_ops),
                              busy_ms=round(lb / 1e3, 4)))
    lane_rows.sort(key=lambda r: -r["busy_ms"])

    # top-K ops by summed device time, joined to the roofline table
    by_op = {}
    verdict_by_name = {p.get("name"): p.get("verdict")
                       for p in programs if isinstance(p, dict)}
    for o in ops:
        slot = by_op.setdefault((o["op"], o["module"]),
                                {"op": o["op"], "module": o["module"],
                                 "count": 0, "total_us": 0.0})
        slot["count"] += 1
        slot["total_us"] += o["dur"]
    top = sorted(by_op.values(), key=lambda s: -s["total_us"])[:top_k]
    top_ops = []
    for s in top:
        prog = program_map.get(s["module"]) if s["module"] else None
        top_ops.append({
            "op": s["op"], "count": s["count"],
            "total_ms": round(s["total_us"] / 1e3, 4),
            "mean_us": round(s["total_us"] / s["count"], 3),
            "module": s["module"], "program": prog,
            "verdict": verdict_by_name.get(prog),
        })

    # measured collectives: union time (step-budget-comparable) + per kind
    coll_ops = [(o, collective_kind_of(o["op"])) for o in ops]
    coll_ops = [(o, k) for o, k in coll_ops if k]
    _, coll_union_us = union_intervals(
        (o["ts"], o["ts"] + o["dur"]) for o, _k in coll_ops)
    by_kind = {}
    for o, k in coll_ops:
        slot = by_kind.setdefault(k, {"kind": k, "count": 0,
                                      "total_us": 0.0})
        slot["count"] += 1
        slot["total_us"] += o["dur"]
    kind_rows = []
    for k, s in sorted(by_kind.items(), key=lambda kv: -kv[1]["total_us"]):
        # axis join: the program the collective ran in, via module map
        mods = {o["module"] for o, kk in coll_ops if kk == k}
        progs = {program_map.get(m) for m in mods if m}
        axis = None
        if len(progs) == 1:
            axis = _axis_map_for(next(iter(progs)), comms_programs).get(k)
        kind_rows.append({"kind": k, "count": s["count"],
                          "total_ms": round(s["total_us"] / 1e3, 4),
                          "axis": axis})

    # idle gaps inside the device span (union-timeline holes)
    gaps_ms = [(nxt[0] - cur[1]) / 1e3
               for cur, nxt in zip(busy_iv, busy_iv[1:])
               if nxt[0] > cur[1]]
    span_ms = ((busy_iv[-1][1] - busy_iv[0][0]) / 1e3) if busy_iv else 0.0

    denom = wall if wall and wall > 0 else (span_ms or None)
    busy_fraction = None
    idle_ms = None
    gaps = None
    if denom:
        busy_fraction = round(min(1.0, busy_ms / denom), 6)
        idle_ms = max(0.0, denom - busy_ms)
        io_wait = max(0.0, float(counters_delta.get("io_wait_ms") or 0.0))
        disp = max(0.0, float(counters_delta.get("dispatch_ms") or 0.0))
        input_starved = min(idle_ms, io_wait)
        rest = idle_ms - input_starved
        dispatch_serialized = min(rest, disp)
        host_gap = rest - dispatch_serialized
        gaps = {
            "count": len(gaps_ms),
            "total_ms": round(sum(gaps_ms), 4),
            "max_ms": round(max(gaps_ms), 4) if gaps_ms else 0.0,
            "histogram_ms": _gap_histogram(gaps_ms),
            "taxonomy": {
                "input_starved_ms": round(input_starved, 4),
                "dispatch_serialized_ms": round(dispatch_serialized, 4),
                "host_gap_ms": round(host_gap, 4),
            },
        }
        split = _starved_split(input_starved, counters_delta)
        if split is not None:
            gaps["input_starved_split"] = split

    per_step = None
    if denom:
        per_step = {
            "device_busy_ms": round(busy_ms / steps, 4),
            "collective_ms": round(coll_union_us / 1e3 / steps, 4),
            "idle_ms": round(idle_ms / steps, 4),
        }
    return {
        "busy_fraction": busy_fraction,
        "busy_ms": round(busy_ms, 4),
        "idle_ms": round(idle_ms, 4) if idle_ms is not None else None,
        "device_span_ms": round(span_ms, 4),
        "per_step": per_step,
        "lanes": lane_rows,
        "top_ops": top_ops,
        "collectives": {
            "union_ms": round(coll_union_us / 1e3, 4),
            "sum_ms": round(sum(s["total_us"]
                                for s in by_kind.values()) / 1e3, 4),
            "by_kind": kind_rows,
        },
        "gaps": gaps,
        "device_events": len(ops),
    }
