"""mxtpu.devicescope — measured device-timeline ground truth.

The sixth observability layer (docs/observability.md). Everything the
earlier layers say about where step time goes is *derived*: perfscope's
``device_compute`` comes from a fetch-barrier probe, commscope's
``collective`` from a ring-model estimate that is ALWAYS marked
estimated. Devicescope is the layer that **measures what the device
actually did** and keeps those estimates honest:

* **windowed capture** (:mod:`.window`) — ``devicescope.capture
  (steps=N)`` wraps a bounded N-step window of the steady train loop in
  ``jax.profiler.trace``. Off by default; ``BENCH_DEVICESCOPE=1`` arms
  one window per bench run; the artifact dir is rotated
  (``MXTPU_DEVICESCOPE_KEEP``, default 3) so repeated runs don't grow
  it unboundedly.
* **trace ingestion** (:mod:`.ingest`) — the emitted Chrome-trace
  artifact (works on XLA:CPU in tier-1, no TPU required) parses into
  per-lane device events and yields measured truth: device **busy
  fraction**, **top-K ops/fusions** by device time (joined to
  perfscope's program table by ``hlo_module`` name, so each hot fusion
  carries its roofline verdict), **collective-lane time** per kind with
  commscope mesh-axis attribution, and an **idle-gap histogram**
  classified input-starved / dispatch-serialized / host-gap from the
  ``io.*`` / ``trainloop.dispatch_ms`` counters.
* **reconciliation** (:func:`budget_overrides`) — when a completed
  window exists, perfscope's :class:`StepBudget` upgrades its
  provenance to ``measured(profile)``: measured ``device_compute`` /
  ``collective`` replace the probe/estimate numbers (which stay beside
  them in the reconciliation block), and a LOUD drift warning — counter
  + flight breadcrumb + structured event — fires when analytic and
  measured disagree by more than :data:`DRIFT_THRESHOLD` (25%): the
  signal that an estimate went stale.

Everything lands in the ``devicescope.*`` counter family,
``extra.devicescope`` in BENCH json, and ``tools/mxdiag.py device``.

Fast-path contract: the single module global ``_DS`` (the perfscope /
commscope / healthmon discipline) — every passive hook costs one
predicate when devicescope is off, and a run that never opens a window
pays nothing at all.
"""
from __future__ import annotations

import os
import threading
import warnings

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter
from . import ingest
from . import window as _window
from .ingest import summarize, device_events, union_intervals, \
    collective_kind_of, load_trace_events, find_trace_file
from .window import CaptureWindow

__all__ = ["enable", "disable", "enabled", "enable_from_env", "capture",
           "active_window", "last_window", "last_window_path",
           "window_summary", "register_program", "module_name_of",
           "program_map", "budget_overrides", "bench_extra", "reset",
           "CaptureWindow", "DRIFT_THRESHOLD", "ingest", "summarize",
           "device_events", "union_intervals", "collective_kind_of",
           "load_trace_events", "find_trace_file"]

# analytic-vs-measured relative disagreement that triggers the loud
# drift warning (the estimate-went-stale signal)
DRIFT_THRESHOLD = 0.25

# module global: None = devicescope off (THE fast-path predicate)
_DS = None

# capture state: the currently-tracing window, and the last completed
# one (what reconciliation / healthmon post-mortems read)
_ACTIVE = None
_LAST = None

# hlo_module name -> perfscope program name, recorded at compile capture
# (perfscope's analyze hooks call register_program when armed) — the
# join key between trace lanes and the roofline table
_MODULES: "dict[str, str]" = {}
_mlock = threading.Lock()


class _DeviceScope:
    """Marker object holding enable-time options (the perfscope
    module-global discipline)."""

    def __init__(self):
        pass


def enable():
    """Arm devicescope: compile sites start recording the hlo_module →
    program join map, and :func:`capture` windows feed the step budget.
    Capture itself stays explicit — arming costs nothing per step."""
    global _DS
    _DS = _DeviceScope()
    return _DS


def disable():
    global _DS, _ACTIVE, _LAST
    if _ACTIVE is not None:
        try:
            _ACTIVE.stop()
        except Exception:  # noqa: BLE001
            pass
    _DS = None
    _ACTIVE = None
    _LAST = None


def enabled() -> bool:
    return _DS is not None


def enable_from_env():
    """MXTPU_DEVICESCOPE=1 arms devicescope at import (like
    MXTPU_PERFSCOPE / MXTPU_COMMSCOPE)."""
    if os.environ.get("MXTPU_DEVICESCOPE", "") == "1":
        enable()


def reset():
    """Test hook: drop capture state and the module join map."""
    global _ACTIVE, _LAST
    _ACTIVE = None
    _LAST = None
    with _mlock:
        _MODULES.clear()


# ---------------------------------------------------------------------------
# capture surface
# ---------------------------------------------------------------------------

def capture(steps: int = 10, logdir: str | None = None) -> CaptureWindow:
    """A bounded capture window over the next ``steps`` train steps.

    Arms devicescope if it isn't already (an explicit capture IS the
    opt-in). Use as a context manager around a loop that marks its own
    steps (TrainLoop.run_chunk marks automatically), or drive
    ``start()`` / ``step()`` / ``stop()`` by hand::

        with mx.devicescope.capture(steps=10) as win:
            loop.fit(data, steps=200)      # window stops itself at 10
        print(win.summary()["busy_fraction"])
    """
    if _DS is None:
        enable()
    return CaptureWindow(steps=steps, logdir=logdir)


def _set_active(win):
    global _ACTIVE
    _ACTIVE = win


def _set_last(win):
    global _LAST
    _LAST = win


def active_window():
    """The currently-tracing window (what instrumented executors mark),
    or None."""
    return _ACTIVE


def last_window():
    """The most recently completed window object, or None."""
    return _LAST


def last_window_path():
    """Artifact dir of the last completed window — what healthmon
    attaches to stall/NaN post-mortems. None when no window completed."""
    w = _LAST
    return w.logdir if w is not None else None


def window_summary():
    """The last completed window's measured summary (ingested lazily),
    or None — the perfscope step budget's reconciliation source."""
    w = _LAST
    if w is None:
        return None
    return w.summary()


# ---------------------------------------------------------------------------
# program join map (compile-site hook)
# ---------------------------------------------------------------------------

def register_program(program_name: str, module_name) -> None:
    """Record that perfscope program ``program_name`` lowered to HLO
    module ``module_name`` — called from perfscope's analyze hooks when
    devicescope is armed. The trace's ``hlo_module`` arg joins through
    this map.

    Module names are NOT unique across programs (every hybridized
    Block jits a function named ``raw_fn``, so all of them lower to
    ``jit_raw_fn``): a module seen under two different program names is
    POISONED to None — ambiguous attribution is reported as unjoined,
    never guessed (the same rule as the collective axis join).
    Re-registering the same (module, program) pair — a batch-signature
    re-analysis — keeps the join."""
    if not module_name:
        return
    mod = str(module_name)
    with _mlock:
        if mod in _MODULES and _MODULES[mod] != str(program_name):
            _MODULES[mod] = None
        else:
            _MODULES[mod] = str(program_name)


def module_name_of(lowered):
    """The HLO module name of a lowered jax stage ("jit_step_fn"), or
    None. Never raises — the MLIR surface is backend/version-dependent."""
    try:
        attr = lowered.compiler_ir().operation.attributes["sym_name"]
        v = getattr(attr, "value", None)
        if v:
            return str(v)
        return str(attr).strip('"')
    except Exception:  # noqa: BLE001
        pass
    try:
        import re
        head = lowered.as_text()[:300]
        m = re.search(r"module @([\w.\-]+)", head)
        return m.group(1) if m else None
    except Exception:  # noqa: BLE001
        return None


def program_map() -> dict:
    with _mlock:
        return dict(_MODULES)


# ---------------------------------------------------------------------------
# step-budget reconciliation
# ---------------------------------------------------------------------------

def _drift(analytic, measured):
    """Relative disagreement, None when the analytic side is ~0 (no
    basis to reconcile against)."""
    if analytic is None or measured is None or analytic <= 1e-9:
        return None
    return abs(measured - analytic) / analytic


def budget_overrides(step_ms, device, collective, collective_source,
                     source, since=None):
    """Measured overrides for one settled step budget, or None.

    Called from :meth:`perfscope.StepBudget.finish` with the ANALYTIC
    components (probe device time, kvstore/commscope collective).
    When devicescope is armed and a completed window measured device
    activity, returns::

        {"device_compute_ms", "collective_ms", "collective_source",
         "source", "reconciliation"}

    * ``device_compute`` becomes the window's per-step busy time minus
      its measured collective share (clipped at step_ms), provenance
      ``measured(profile)``;
    * ``collective`` is overridden — and its provenance upgraded — only
      when the window actually measured collective lanes (a measured 0
      with host-side kvstore collectives would erase a real
      measurement: host collectives never appear on device lanes);
    * the reconciliation block keeps the analytic numbers BESIDE the
      measured ones and carries the drift verdict; >25% disagreement
      additionally fires the loud drift warning (counter + flight
      breadcrumb + structured event + Python warning).

    ``since``: a ``time.monotonic()`` reference (the budget's begin
    time) — a window completed BEFORE it is someone else's steady
    phase, and stale measurements must not be presented with the
    strongest provenance against a workload they never saw.

    Returns None (no override, budget falls back exactly as today) when
    devicescope is off or no usable window exists."""
    if _DS is None:
        return None
    w = _LAST
    if since is not None and w is not None \
            and (w.completed_at is None or w.completed_at < float(since)):
        return None               # stale window: predates this budget
    if w is not None and getattr(w, "workload", None) \
            not in (None, "train"):
        # workload identity, not just freshness: a window stepped by
        # the serving batcher (or by both loops — "mixed") measured
        # dispatches this TRAIN budget never issued; upgrading from it
        # would pin measured(profile) on someone else's busy time.
        # None stays accepted for steppers that predate the stamp.
        return None
    try:
        s = window_summary()
    except Exception:  # noqa: BLE001
        return None
    if not isinstance(s, dict) or not isinstance(s.get("per_step"), dict):
        return None
    per = s["per_step"]
    meas_busy = per.get("device_busy_ms")
    meas_coll = per.get("collective_ms") or 0.0
    if not isinstance(meas_busy, (int, float)) or meas_busy <= 0.0:
        return None
    step_ms = float(step_ms)
    meas_busy = float(meas_busy)
    meas_coll = float(meas_coll)
    new_coll = float(collective)
    new_coll_src = collective_source
    if meas_coll > 0.0:
        new_coll = min(meas_coll, step_ms)
        new_coll_src = "measured(profile)"
    # device = busy minus its collective share, capped so device +
    # collective never exceeds the steady per-step wall — the traced
    # window's steps pay profiler overhead, so its per-step busy time
    # can legitimately exceed the untraced steady step_ms, and the
    # budget's components must still sum to what was measured steady
    new_device = min(max(0.0, meas_busy - meas_coll),
                     max(0.0, step_ms - new_coll))
    recon = {
        "analytic": {
            "device_compute_ms": round(float(device), 4),
            "collective_ms": round(float(collective), 4),
            "collective_source": collective_source,
            "source": source,
        },
        "measured": {
            "device_compute_ms": round(new_device, 4),
            "collective_ms": round(meas_coll, 4),
            "busy_fraction": s.get("busy_fraction"),
            "window": (s.get("window") or {}).get("path"),
        },
        "drift": {
            "device_compute": _drift(float(device), new_device),
            "collective": (_drift(float(collective), meas_coll)
                           if meas_coll > 0.0 else None),
        },
        "threshold": DRIFT_THRESHOLD,
    }
    drifted = [k for k, v in recon["drift"].items()
               if v is not None and v > DRIFT_THRESHOLD]
    recon["drift_warning"] = bool(drifted)
    if drifted:
        _warn_drift(recon, drifted)
    # attach to the window summary so extra.devicescope carries it
    s["reconciliation"] = recon
    return {"device_compute_ms": new_device, "collective_ms": new_coll,
            "collective_source": new_coll_src,
            "source": "measured(profile)", "reconciliation": recon}


def _warn_drift(recon, drifted):
    """The loud estimate-went-stale signal: counter + flight breadcrumb
    + structured event + Python warning. Never raises."""
    try:
        _counter("devicescope.drift_warnings",
                 "devicescope").increment(len(drifted))
        detail = {k: {"analytic": recon["analytic"][k + "_ms"],
                      "measured": recon["measured"][k + "_ms"],
                      "drift": round(recon["drift"][k], 4)}
                  for k in drifted}
        if _flight._REC is not None:
            _flight.record("alert", "devicescope.drift",
                           dict(detail, threshold=DRIFT_THRESHOLD))
        try:
            from .. import healthmon as _hm
            if _hm._HM is not None:
                _hm._HM.events.emit(
                    "alert", "devicescope.drift",
                    args={"components": sorted(drifted),
                          "threshold": DRIFT_THRESHOLD})
        except Exception:  # noqa: BLE001
            pass
        parts = "; ".join(
            f"{k}: analytic {v['analytic']:.3f} ms vs measured "
            f"{v['measured']:.3f} ms ({v['drift']:.0%} apart)"
            for k, v in detail.items())
        warnings.warn(
            f"devicescope: analytic and measured step components "
            f"disagree by more than {DRIFT_THRESHOLD:.0%} — {parts}. "
            f"An estimate (probe / ring model / peak table) has gone "
            f"stale; trust the measured window (docs/devicescope.md)",
            stacklevel=3)
    except Exception:  # noqa: BLE001 — warning plumbing must never raise
        pass


# ---------------------------------------------------------------------------
# bench payload
# ---------------------------------------------------------------------------

def bench_extra() -> dict:
    """The ``extra.devicescope`` payload for BENCH json: the last
    window's measured summary (busy fraction, top-K ops joined to the
    roofline table, measured collectives, gap taxonomy, reconciliation),
    or the armed-but-no-window shape ``{"window": None}``."""
    s = window_summary()
    if not isinstance(s, dict):
        return {"window": None, "busy_fraction": None, "per_step": None,
                "top_ops": [], "gaps": None, "reconciliation": None}
    return dict(s)
