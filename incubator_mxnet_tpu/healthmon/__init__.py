"""mxtpu.healthmon — cross-rank training health.

The third observability pillar: :mod:`..profiler` traces one process on
demand, :mod:`..diagnostics` monitors one process always-on; healthmon
correlates ACROSS ranks and watches for the distributed failure modes
that per-process telemetry can't see — slow ranks dragging every
collective, silent NaN divergence, hangs that look like "training is
just slow". Three pieces (see docs/observability.md):

* **cross-rank collective timeline** (:mod:`.skew`) — per-rank
  step/collective EWMAs exchanged periodically over the existing
  distributed wire (allgather on sync clusters, the rank-0 TCP server
  for dist_async), yielding ``healthmon.collective_skew_ms`` and
  slowest-rank attribution in the shared counters registry;
* **training watchdogs** (:mod:`.watchdog`) — NaN/Inf sentinel on loss
  (+ opt-in every-N-steps gradient global-norm), EWMA step-time
  regression detector, and a stall thread that triggers a
  flight-recorder dump with per-rank last-known state;
* **structured event log** (:mod:`.events`) — ``mxtpu.events/2`` JSONL
  with run_id/rank/step correlation ids (+ a wall/monotonic timestamp
  pair for NTP-step-safe cross-process merges), threaded through Trainer step
  phases, kvstore collectives, serving batches, and every watchdog
  alert; merge per-rank files with ``tools/mxdiag.py merge``.

Quick start (identical on every rank)::

    import incubator_mxnet_tpu as mx
    mx.distributed.init(...)
    mx.healthmon.enable()          # events -> $MXTPU_HM_DIR/events_rank<r>.jsonl
    ...training loop with gluon.Trainer...   # hooks are automatic
    mx.healthmon.observe_loss(float(loss))   # NaN sentinel (host scalar)
    mx.healthmon.disable()

Loops that don't use Trainer call :func:`mark_step` once per step.

Env knobs: ``MXTPU_HEALTHMON=1`` auto-enables at import — note that at
import time no cluster exists yet, so on multi-process runs either
launch via tools/launch.py (which exports MXTPU_PROCESS_ID +
MXTPU_RUN_ID, giving every rank its correct identity without touching
the jax backend) or call :func:`enable` after ``mx.distributed.init()``
as in the quick start; ``MXTPU_RUN_ID`` (cross-rank correlation id —
set it from the launcher; otherwise rank 0 publishes one through the
coordination KV), ``MXTPU_HM_DIR`` (event-log
directory, default ``MXTPU_DIAG_DIR``/tmp), ``MXTPU_HM_STALL_S`` (stall
deadline, default 300, 0 = off), ``MXTPU_HM_EXCHANGE_EVERY`` (skew
exchange cadence in steps, default 10, 0 = off),
``MXTPU_HM_GRAD_NORM_EVERY`` (gradient-norm sentinel cadence, default
0 = off — it forces a device sync), ``MXTPU_HM_ON_NAN`` (``alert`` |
``raise``).
"""
from __future__ import annotations

import os
import threading
import time

from ..profiler.counters import counter as _counter, set_gauge as _set_gauge
from ..diagnostics import flight as _flight
from . import events as _events
from .events import SCHEMA as EVENTS_SCHEMA
from .skew import CollectiveTimeline
from .watchdog import NaNSentinel, StepTimeRegression, StallWatchdog

__all__ = ["HealthMonitor", "enable", "disable", "enabled", "current",
           "observe_loss", "mark_step", "enable_from_env", "status",
           "EVENTS_SCHEMA", "events", "skew", "watchdog"]

# module global: None = healthmon off (THE fast-path predicate; trainer/
# kvstore/serving guard their hooks with `if _hm._HM is not None:`)
_HM = None


def _coordination_client():
    """The jax coordination-service client IF a cluster has been formed,
    else None. Read from distributed global state, NOT via
    jax.process_count(): that call MATERIALIZES the backend, and doing
    so at import time (MXTPU_HEALTHMON=1) would make every rank's later
    mx.distributed.init() fail with 'initialize() must be called before
    any JAX computations'."""
    try:
        from jax._src import distributed as _jd
        return _jd.global_state.client
    except Exception:   # noqa: BLE001 — private surface may move
        return None


def _default_rank() -> int:
    """This process's rank without touching the backend: the launcher's
    MXTPU_PROCESS_ID wins (valid even before distributed.init), then a
    formed cluster's process_index, else 0."""
    from ..autotune.knobs import env_str
    env = env_str("MXTPU_PROCESS_ID")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    if _coordination_client() is not None:
        import jax
        return jax.process_index()
    return 0


def _resolve_run_id(rank: int) -> str:
    """One id shared by every rank of a run. Launcher-set MXTPU_RUN_ID
    wins; on a formed cluster rank 0 publishes one through the
    coordination KV (one-time traffic — the sustained-RPC segfault the
    async PS wire avoids does not apply); fallback is process-local."""
    from ..autotune.knobs import env_str
    rid = env_str("MXTPU_RUN_ID")
    if rid:
        return rid
    try:
        c = _coordination_client()
        if c is not None:
            key = "mxtpu_hm/run_id"
            if rank == 0:
                rid = f"run-{int(time.time())}-{os.getpid():x}"
                c.key_value_set_bytes(key, rid.encode(),
                                      allow_overwrite=True)
                return rid
            return c.blocking_key_value_get_bytes(key, 60_000).decode()
    except Exception:   # noqa: BLE001 — correlation id is best-effort
        pass
    return f"run-{int(time.time())}-{os.getpid()}"


def _env_float(name, default):
    # watchdog cadence knobs degrade on a typo, never crash enable()
    from ..autotune.knobs import env_float
    return float(env_float(name, default, on_error="default"))


def _devicescope_window_path():
    """Artifact dir of the last completed devicescope capture window,
    or None — attached to stall/NaN alerts so the post-mortem has the
    measured device timeline, not just host state. Never raises."""
    try:
        from .. import devicescope as _ds
        return _ds.last_window_path()
    except Exception:   # noqa: BLE001 — alerting must never crash
        return None


class HealthMonitor:
    """One per process; owns the timeline, sentinels, watchdog thread,
    and the structured event log. Constructed via :func:`enable`."""

    def __init__(self, run_id=None, rank=None, hm_dir=None,
                 events_path=None, stall_timeout_s=None,
                 exchange_every=None, grad_norm_every=None, on_nan=None,
                 regress_factor=2.0, ewma_alpha=0.3,
                 straggler_factor=2.0, stall_check_interval_s=None):
        self.rank = int(rank if rank is not None else _default_rank())
        self.run_id = run_id or _resolve_run_id(self.rank)
        from ..autotune.knobs import env_str
        self.hm_dir = hm_dir or env_str(
            "MXTPU_HM_DIR", env_str("MXTPU_DIAG_DIR", "/tmp"))
        self.exchange_every = int(
            exchange_every if exchange_every is not None
            else _env_float("MXTPU_HM_EXCHANGE_EVERY", 10))
        self.grad_norm_every = int(
            grad_norm_every if grad_norm_every is not None
            else _env_float("MXTPU_HM_GRAD_NORM_EVERY", 0))
        stall_timeout_s = (stall_timeout_s if stall_timeout_s is not None
                           else _env_float("MXTPU_HM_STALL_S", 300))
        on_nan = on_nan or env_str("MXTPU_HM_ON_NAN", "alert")

        self.step = 0                 # completed steps
        self._step_t0 = None          # perf_counter at step_begin
        self._prev_end = None         # perf_counter at previous step_end
        self._coll_ms = 0.0           # this step's collective time
        self._coll_lock = threading.Lock()

        self.timeline = CollectiveTimeline(
            rank=self.rank, alpha=ewma_alpha,
            straggler_factor=straggler_factor)
        self.nan = NaNSentinel(self._alert, on_nan=on_nan)
        self.regress = StepTimeRegression(self._alert,
                                          factor=regress_factor,
                                          alpha=ewma_alpha)
        path = events_path or os.path.join(
            self.hm_dir, f"events_rank{self.rank}.jsonl")
        self.events = _events.open_log(path, self.run_id, self.rank)
        self.watchdog = None
        if stall_timeout_s and stall_timeout_s > 0:
            self.watchdog = StallWatchdog(
                stall_timeout_s, self._on_stall,
                check_interval_s=stall_check_interval_s)
            self.watchdog.start()
        self.events.emit("lifecycle", "healthmon.enable", args={
            "stall_timeout_s": stall_timeout_s,
            "exchange_every": self.exchange_every,
            "grad_norm_every": self.grad_norm_every, "on_nan": on_nan})

    # -- alert fan-out: counter + flight breadcrumb + structured event ----
    def _alert(self, name: str, args: dict, step=None):
        if name.startswith("nan_"):
            family = "healthmon.nan_alerts"
        elif name == "stall":
            family = "healthmon.stall_alerts"
        else:
            family = "healthmon.step_time_regressions"
        if name == "stall" or name.startswith("nan_"):
            # post-mortem breadcrumb: the last completed devicescope
            # capture window (if any run made one) holds the DEVICE
            # timeline for the steps before things went wrong — the
            # host-state dump alone can't show a wedged collective lane
            p = _devicescope_window_path()
            if p:
                args = dict(args, devicescope_window=p)
        _counter(family, "healthmon").increment()
        if _flight._REC is not None:
            _flight.record("alert", "healthmon." + name, args)
        self.events.emit("alert", "healthmon." + name,
                         step=self.step if step is None else step,
                         args=args)
        # verdict → action: a registered resilience supervisor acts on
        # this alert (stall → supervised restart; docs/resilience.md).
        # One predicate when no supervisor is armed — and the recovery
        # policy's own failure must never mask the alert that fired it.
        from .. import resilience as _resilience
        if _resilience._RS is not None:
            try:
                _resilience.on_health_alert(
                    name, args, step=self.step if step is None else step)
            except SystemExit:
                raise
            except Exception as e:   # noqa: BLE001
                _counter("healthmon.recovery_hook_errors",
                         "healthmon").increment()
                self.events.emit(
                    "alert", "healthmon.recovery_hook_error",
                    step=self.step if step is None else step,
                    args={"error": f"{type(e).__name__}: {e}"[:300]})

    def _on_stall(self, age_s: float):
        """StallWatchdog callback: alert, then flush the flight ring with
        the per-rank last-known state attached (the post-mortem for a
        job that will likely be SIGKILLed shortly after)."""
        args = {"age_s": round(age_s, 1), "last_step": self.step,
                "deadline_s": self.watchdog.deadline_s}
        if self.timeline.last_table:
            args["last_known_ranks"] = self.timeline.last_table
        self._alert("stall", args)
        if _flight._REC is not None:
            path = os.path.join(self.hm_dir,
                                f"mxtpu_stall_{os.getpid()}.json")
            try:
                _flight.dump(reason="healthmon.stall", path=path)
            except Exception:   # noqa: BLE001 — alerting must not crash
                pass

    # -- hot hooks (trainer / custom loops) -------------------------------
    def step_begin(self):
        self._step_t0 = time.perf_counter()

    def step_end(self, kv=None, batch_size=None, loss=None,
                 phases=None):
        """One training step completed. Updates EWMAs/watchdogs, emits
        the step event, and — every `exchange_every` steps — runs the
        cross-rank exchange (a collective on sync clusters: every rank
        must reach the same step count, which lockstep training gives)."""
        now = time.perf_counter()
        self.step += 1
        _counter("healthmon.steps", "healthmon").increment()
        with self._coll_lock:
            coll, self._coll_ms = self._coll_ms, 0.0
        if self._prev_end is not None:
            step_ms = (now - self._prev_end) * 1e3
        elif self._step_t0 is not None:
            step_ms = (now - self._step_t0) * 1e3
        else:
            step_ms = None
        self._prev_end = now
        if loss is not None:
            self.observe_loss(loss)
        if step_ms is not None:
            self.regress.observe(step_ms, step=self.step)
            self.timeline.record_step(self.step, step_ms, coll)
        if self.watchdog is not None:
            self.watchdog.beat()
        args = {"coll_ms": round(coll, 3)}
        if step_ms is not None:
            args["step_ms"] = round(step_ms, 3)
        if batch_size is not None:
            args["batch_size"] = int(batch_size)
        if phases:
            args.update({k: round(float(v), 3) for k, v in phases.items()})
        self.events.emit("trainer", "step", step=self.step, args=args)
        if self.exchange_every > 0 and \
                self.step % self.exchange_every == 0:
            try:
                summary = self.timeline.exchange(
                    self.step, kv=kv, nan_alerts=self.nan.alerts)
            except Exception as e:  # noqa: BLE001 — telemetry exchange
                # must never take the training loop down, but its OWN
                # failure must be observable (a failed collective here
                # can leave sync ranks' collective streams misaligned —
                # the operator needs the breadcrumb that says where)
                _counter("healthmon.exchange_errors",
                         "healthmon").increment()
                err = {"error": f"{type(e).__name__}: {e}"[:300],
                       "step": self.step}
                self.events.emit("alert", "healthmon.exchange_error",
                                 step=self.step, args=err)
                if _flight._REC is not None:
                    _flight.record("alert", "healthmon.exchange_error",
                                   err)
                return
            self.events.emit("healthmon", "skew_report", step=self.step,
                             args=summary)
            if _flight._REC is not None:
                _flight.record("healthmon", "skew_report", summary)

    def record_collective(self, op: str, dur_ms: float):
        """kvstore hook: one collective-surface call took `dur_ms`."""
        with self._coll_lock:
            self._coll_ms += dur_ms
        if self.events is not None:
            self.events.emit("collective", "kvstore." + op,
                             step=self.step,
                             args={"ms": round(dur_ms, 3)})

    def observe_loss(self, value, step=None) -> bool:
        """NaN/Inf sentinel on a host-side loss scalar. Returns True when
        the alert fired (and raises instead under on_nan='raise')."""
        return self.nan.check(value, step=step if step is not None
                              else self.step, source="loss")

    def maybe_check_grad_norm(self, params) -> float | None:
        """Opt-in gradient global-norm sentinel: every
        `grad_norm_every` steps compute ||g||_2 over all dense grads
        (ONE device sync — that cost is why this defaults off), publish
        the gauge, and run the NaN sentinel on it."""
        if self.grad_norm_every <= 0 or \
                (self.step + 1) % self.grad_norm_every != 0:
            return None
        import jax.numpy as jnp
        from ..ndarray import sparse as _sparse
        total = None
        for p in params:
            g = p.grad()
            if isinstance(g, _sparse.RowSparseNDArray):
                continue            # lazy-row grads keep their own path
            s = jnp.sum(jnp.square(g._data.astype(jnp.float32)))
            total = s if total is None else total + s
        if total is None:
            return None
        norm = float(jnp.sqrt(total))
        _set_gauge("healthmon.grad_global_norm", round(norm, 6),
                            "healthmon")
        self.nan.check(norm, step=self.step + 1, source="grad_norm")
        return norm

    # -- lifecycle --------------------------------------------------------
    def close(self):
        if self.watchdog is not None:
            self.watchdog.stop()
        self.events.emit("lifecycle", "healthmon.disable",
                         args={"steps": self.step})
        # close OUR log; clear the module global only when it is ours
        # (a caller may have re-pointed the module log since)
        if _events.current_log() is self.events:
            _events.close_log()
        else:
            self.events.close()


# ---------------------------------------------------------------------------
# module surface
# ---------------------------------------------------------------------------

def enable(**kwargs) -> HealthMonitor:
    """Arm healthmon (replacing any prior monitor). Kwargs mirror
    :class:`HealthMonitor`; unset ones fall back to the env knobs."""
    global _HM
    # clear BEFORE constructing: if the new monitor fails (bad dir,
    # etc.) healthmon must read as disabled — the alternative (closing
    # the old monitor but leaving _HM pointing at it) would keep
    # enabled() True while the event log is closed and the watchdog
    # stopped, i.e. telemetry silently dead
    # mxlint: disable=thread-shared-mutation -- GIL-atomic rebind of the
    # arming global; every reader snapshots _HM once (the `_HM is None`
    # discipline), and enable() runs before any monitored thread exists
    old, _HM = _HM, None
    if old is not None:
        old.close()
    # mxlint: disable=thread-shared-mutation -- same GIL-atomic rebind
    _HM = HealthMonitor(**kwargs)
    return _HM


def disable():
    global _HM
    if _HM is not None:
        _HM.close()
        # mxlint: disable=thread-shared-mutation -- GIL-atomic rebind;
        # readers snapshot _HM once, in-flight hooks finish on the old
        # (closed-tolerant) monitor object
        _HM = None


def enabled() -> bool:
    return _HM is not None


def current():
    return _HM


def observe_loss(value, step=None) -> bool:
    """Module-level NaN sentinel (no-op False when healthmon is off)."""
    hm = _HM
    if hm is None:
        return False
    return hm.observe_loss(value, step=step)


def mark_step(kv=None, batch_size=None, loss=None):
    """Step hook for loops that don't go through gluon.Trainer (fused
    train steps, custom loops): call once per completed step."""
    hm = _HM
    if hm is not None:
        hm.step_end(kv=kv, batch_size=batch_size, loss=loss)


def status() -> dict:
    """Operator-facing health summary: watchdog/sentinel counts plus —
    because detection without action is an obituary — the resilience
    block (who acts on the verdicts: last checkpoint step, recovery
    totals, rollback-in-progress). Deep ``/healthz`` embeds this."""
    from ..profiler.counters import counters as _snap
    from .. import resilience as _resilience
    c = _snap()
    return {
        "enabled": _HM is not None,
        "steps": _HM.step if _HM is not None else None,
        "stall_alerts": c.get("healthmon/healthmon.stall_alerts", 0),
        "nan_alerts": c.get("healthmon/healthmon.nan_alerts", 0),
        "step_time_regressions": c.get(
            "healthmon/healthmon.step_time_regressions", 0),
        "resilience": _resilience.status(),
    }


def enable_from_env():
    """Honor MXTPU_HEALTHMON=1 (called from package import)."""
    if os.environ.get("MXTPU_HEALTHMON", "0") in ("1", "true", "on"):
        enable()


from . import skew, watchdog, events   # noqa: E402,F401 — re-export
