"""Structured event log — the ``mxtpu.events/2`` JSONL stream.

Flight dumps answer "what just happened in THIS process"; the event log
is the cross-rank correlation surface: every record carries the same
three correlation ids — ``run_id`` (shared by every rank of one training
run), ``rank``, and ``step`` — so per-rank files merge into one ordered
cluster timeline (``tools/mxdiag.py merge``). The pattern is Dapper's
trace/span ids collapsed to the three that matter for SPMD training,
where "one request" is "one step on every rank".

Records are newline-JSON, one self-describing object per line::

    {"schema": "mxtpu.events/2", "ts": <epoch s>, "mono": <monotonic s>,
     "run_id": "...", "rank": 0, "step": 12, "kind": "trainer",
     "name": "step", "args": {...}}

``kind`` groups the emitting subsystem (``trainer``, ``collective``,
``serving``, ``alert``, ``healthmon``, ``lifecycle``); ``step`` is null
for records outside the training loop (serving batches, watchdog fires
before the first step). Timestamps are monotone WITHIN a file (enforced
under the writer lock) so `tools/trace_check.py` can validate ordering,
and the merge tool's sort is stable across ranks.

Schema history: ``/2`` added the ``mono`` companion stamp
(``time.monotonic()``, same process-local clock fleetscope's collector
aligns) so a cross-process merge survives an NTP step — the wall clock
can jump mid-run, the monotonic clock cannot, and a merged pod
timeline orders each process's records by ``mono`` before
interleaving. ``/1`` records (wall-only) still validate: readers key
on the ``mxtpu.events/`` prefix and treat ``mono`` as optional.

Hot-path discipline mirrors diagnostics.flight: one module global
(``_LOG``) is THE fast-path predicate — subsystems guard with
``if events._LOG is not None:`` and pay nothing when the log is off.
Writes are line-buffered and flushed per record: an alert that never
reached disk is an alert that never happened, which is exactly the
failure mode a post-mortem log exists to avoid.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["SCHEMA", "EventLog", "open_log", "close_log", "emit",
           "log_enabled", "current_log"]

SCHEMA = "mxtpu.events/2"

# module global: None = log off (THE fast-path predicate)
_LOG = None


class EventLog:
    """One rank's append-only event stream."""

    def __init__(self, path: str, run_id: str, rank: int):
        self.path = path
        self.run_id = str(run_id)
        self.rank = int(rank)
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # fresh series per open (the sampler's truncate rationale): an
        # appended prior run would break the file's monotonic-ts
        # contract (each process clamps only against its OWN last ts)
        # and make validators re-judge dead runs forever. Line-buffered:
        # each record is durable at the following newline.
        self._f = open(path, "w", buffering=1)
        self._last_ts = 0.0
        self.n_emitted = 0
        self.emit("lifecycle", "events.open",
                  args={"pid": os.getpid()})

    def emit(self, kind: str, name: str, step=None, args=None):
        """Append one record. Timestamps are clamped monotone within the
        file (concurrent writers serialize on the lock; the clock is read
        inside it so ordering and timestamps agree)."""
        with self._lock:
            if self._f.closed:
                return
            ts = time.time()
            if ts < self._last_ts:
                ts = self._last_ts
            self._last_ts = ts
            # monotonic companion (schema /2): the wall clock can step
            # under NTP mid-run; cross-process merges order each
            # process's records by this stamp before interleaving
            rec = {"schema": SCHEMA, "ts": ts, "mono": time.monotonic(),
                   "run_id": self.run_id, "rank": self.rank,
                   "step": (int(step) if step is not None else None),
                   "kind": kind, "name": name}
            if args:
                rec["args"] = args
            self._f.write(json.dumps(rec) + "\n")
            self.n_emitted += 1

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()


# ---------------------------------------------------------------------------
# module surface
# ---------------------------------------------------------------------------

def open_log(path: str, run_id: str, rank: int) -> EventLog:
    """Open (or replace) the module-level event log."""
    global _LOG
    if _LOG is not None:
        _LOG.close()
    _LOG = EventLog(path, run_id, rank)
    return _LOG


def close_log():
    global _LOG
    if _LOG is not None:
        _LOG.close()
        _LOG = None


def log_enabled() -> bool:
    return _LOG is not None


def current_log():
    return _LOG


def emit(kind: str, name: str, step=None, args=None):
    """Append one record if the log is on (cheap no-op otherwise).
    Subsystems on hot paths should guard with
    ``if events._LOG is not None:`` to skip even this call."""
    log = _LOG
    if log is not None:
        log.emit(kind, name, step=step, args=args)
