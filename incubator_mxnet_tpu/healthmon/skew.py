"""Cross-rank collective timeline: straggler detection from step timing.

The MegaScale observation this module rebuilds: in synchronous SPMD
training every collective is a barrier, so a single slow rank makes the
WHOLE job slow while looking idle itself — per-rank metrics show every
rank "busy" (the fast ranks busy waiting inside the collective) and the
aggregate just reads "training got slower". The signal that actually
attributes blame is the *decomposition* of each rank's step time:

    compute_ms = step_ms - collective_ms

The straggler is the rank with the LARGEST compute time (it arrives at
the collective last, so it waits least — its sleep/GC/contention shows
up as compute); the fast ranks absorb the difference as collective wait.
``collective_skew_ms`` is max(compute) - min(compute) across ranks: the
time the collective barrier absorbs every step, i.e. the per-step cost
of the straggler.

Each rank keeps EWMAs of its own step/collective times (fed by the
Trainer/kvstore hooks) and periodically exchanges a compact fixed-width
record with every other rank:

* **sync clusters** (`dist_sync*`, lockstep steps) — one
  ``process_allgather`` of a 6-float vector, itself a collective, so it
  is only issued from the step hook where every rank reaches the same
  step count;
* **dist_async clusters** (no lockstep) — the rank-0 TCP server from
  kvstore/async_ps gains a ``health`` op: workers post their record and
  receive the server's merged table (best-effort, possibly stale —
  matching the async contract).

The merged table feeds the shared counters registry
(``healthmon.collective_skew_ms``, ``healthmon.slowest_rank``,
``healthmon.straggler_flags``) so Prometheus/JSON/flight export the
verdict with zero new wiring, and is kept as ``last_table`` for the
stall watchdog's "per-rank last-known state" crash dump.
"""
from __future__ import annotations

import numpy as np

from ..profiler.counters import counter as _counter, set_gauge as _set_gauge

__all__ = ["CollectiveTimeline", "RECORD_FIELDS"]

# fixed-width exchange record (float64): stable wire format for both the
# allgather and the async TCP paths
RECORD_FIELDS = ("rank", "step", "step_ewma_ms", "coll_ewma_ms",
                 "compute_ewma_ms", "nan_alerts")


def _gauge(name, value):
    _set_gauge("healthmon." + name, value, "healthmon")


class CollectiveTimeline:
    """Per-rank EWMA bookkeeping + the cross-rank exchange/verdict."""

    def __init__(self, rank: int = 0, alpha: float = 0.3,
                 straggler_factor: float = 2.0, min_skew_ms: float = 1.0):
        self.rank = int(rank)
        self.alpha = float(alpha)
        self.straggler_factor = float(straggler_factor)
        self.min_skew_ms = float(min_skew_ms)
        self.step_ewma = None        # full step interval, ms
        self.coll_ewma = None        # collective time inside the step, ms
        self.last_step = 0
        self.last_table = None       # {rank: {field: value}} from exchange
        self.last_summary = None

    # -- local recording --------------------------------------------------
    def _fold(self, prev, x):
        return x if prev is None else \
            self.alpha * x + (1.0 - self.alpha) * prev

    def record_step(self, step: int, step_ms: float, coll_ms: float):
        """Fold one completed step's timing into the EWMAs and publish
        the local gauges."""
        self.last_step = int(step)
        self.step_ewma = self._fold(self.step_ewma, float(step_ms))
        self.coll_ewma = self._fold(self.coll_ewma, float(coll_ms))
        _gauge("step_ms_ewma", round(self.step_ewma, 3))

    @property
    def compute_ewma(self):
        if self.step_ewma is None:
            return None
        return max(0.0, self.step_ewma - (self.coll_ewma or 0.0))

    def local_record(self, step: int, nan_alerts: int = 0) -> np.ndarray:
        return np.array([self.rank, int(step), self.step_ewma or 0.0,
                         self.coll_ewma or 0.0, self.compute_ewma or 0.0,
                         int(nan_alerts)], dtype=np.float64)

    # -- cross-rank verdict ----------------------------------------------
    def ingest_table(self, table) -> dict:
        """Compute the skew verdict from a (n_ranks, 6) record table (any
        transport). Publishes gauges/counters and returns the summary
        dict the event log records."""
        table = np.asarray(table, dtype=np.float64).reshape(-1,
                                                           len(RECORD_FIELDS))
        ranks = table[:, 0].astype(int)
        compute = table[:, 4]
        skew = float(compute.max() - compute.min()) if len(table) else 0.0
        slowest = int(ranks[int(np.argmax(compute))]) if len(table) else -1
        _gauge("collective_skew_ms", round(skew, 3))
        _gauge("slowest_rank", slowest)
        flagged = []
        if len(table) > 1 and skew >= self.min_skew_ms:
            # EWMA slow-rank flagging: a rank whose compute EWMA exceeds
            # straggler_factor x the cross-rank median is flagged (the
            # median, not the min, so one fast rank can't indict the rest)
            median = float(np.median(compute))
            floor = max(median, 1e-6) * self.straggler_factor
            flagged = [int(r) for r, c in zip(ranks, compute) if c > floor]
            if flagged:
                _counter("healthmon.straggler_flags",
                                  "healthmon").increment(len(flagged))
        self.last_table = {
            int(row[0]): {f: (int(row[i]) if f in ("rank", "step",
                                                   "nan_alerts")
                              else round(float(row[i]), 3))
                          for i, f in enumerate(RECORD_FIELDS)}
            for row in table}
        self.last_summary = {
            "skew_ms": round(skew, 3), "slowest_rank": slowest,
            "flagged_ranks": flagged, "n_ranks": len(table),
            "compute_ms": {int(r): round(float(c), 3)
                           for r, c in zip(ranks, compute)}}
        return self.last_summary

    def exchange(self, step: int, kv=None, nan_alerts: int = 0):
        """Share this rank's record with the cluster and ingest the merged
        table. Transport is chosen per the module docstring; single
        process degenerates to a local-only table (skew 0).

        SYNC-CLUSTER CONTRACT: on `dist_sync*` clusters this issues a
        collective — call it only from points every rank reaches at the
        same step count (the Trainer step hook does)."""
        rec = self.local_record(step, nan_alerts)
        table = None
        ps = None
        if kv is not None and getattr(kv, "_is_async", False):
            ps = kv._ps()           # None when single-process
        if ps is not None:
            merged = ps.health_exchange(rec.tolist())
            table = np.array([merged[r] for r in sorted(merged)],
                             dtype=np.float64)
        else:
            import jax
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                table = np.asarray(
                    multihost_utils.process_allgather(rec))
            else:
                table = rec[None]
        _counter("healthmon.exchanges", "healthmon").increment()
        return self.ingest_table(table)
