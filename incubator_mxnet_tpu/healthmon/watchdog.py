"""Training watchdogs: NaN/Inf sentinel, step-time regression, stall/hang.

Three failure modes that per-step metrics alone don't surface until the
job is already lost:

* **silent NaN divergence** — the loss (or gradient norm) goes NaN and
  training keeps "running", burning the rest of the reservation on
  garbage. :class:`NaNSentinel` checks host-side values (loss every time
  the loop hands one over; gradient global-norm opt-in every N steps
  since computing it forces a device sync) and fires an alert — counter
  + flight-ring breadcrumb + structured event — within the same step.
* **step-time regression** — a slow ramp (fragmentation, thermal
  throttle, a sick NIC) that no single threshold catches.
  :class:`StepTimeRegression` keeps an EWMA of step time and flags any
  step slower than ``factor`` x the running estimate, after a short
  warmup so compile/first-touch steps don't trip it.
* **stall/hang** — a deadlocked collective or a wedged input pipeline
  looks exactly like "training is just slow" from outside.
  :class:`StallWatchdog` is a daemon thread fed a heartbeat per
  completed step; when no step lands within the deadline it invokes the
  monitor's stall handler, which records the alert and triggers a
  flight-recorder dump carrying the per-rank last-known state (the skew
  timeline's most recent exchanged table). One fire per stall: the
  watchdog re-arms only after progress resumes, so a long hang produces
  one dump, not a dump per poll interval.

Alert plumbing is deliberately dumb: callers pass an ``alert`` callback
(the HealthMonitor's) that owns counters/events/flight, so these classes
stay testable with no global state.
"""
from __future__ import annotations

import math
import threading
import time

__all__ = ["NaNSentinel", "StepTimeRegression", "StallWatchdog"]


class NaNSentinel:
    """NaN/Inf detector over host-side scalars (loss, grad norm)."""

    def __init__(self, alert, on_nan: str = "alert"):
        if on_nan not in ("alert", "raise"):
            raise ValueError(f"on_nan must be 'alert' or 'raise', "
                            f"got {on_nan!r}")
        self._alert = alert
        self.on_nan = on_nan
        self.alerts = 0

    def check(self, value, step=None, source: str = "loss") -> bool:
        """Returns True (after alerting) when `value` is NaN/Inf.
        `value` must already be a host scalar — callers own the decision
        of when to pay the device sync."""
        v = float(value)
        if math.isfinite(v):
            return False
        self.alerts += 1
        self._alert("nan_" + source,
                    {"value": repr(v), "source": source}, step=step)
        if self.on_nan == "raise":
            raise FloatingPointError(
                f"healthmon: non-finite {source} ({v!r}) at step {step}")
        return True


class StepTimeRegression:
    """EWMA + threshold detector over per-step wall times."""

    def __init__(self, alert, factor: float = 2.0, alpha: float = 0.3,
                 warmup: int = 5):
        self._alert = alert
        self.factor = float(factor)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.ewma = None
        self.n = 0
        self.regressions = 0

    def observe(self, dur_ms: float, step=None) -> bool:
        """Fold one step time in; True when it regressed past
        factor x EWMA (checked against the PRE-update estimate so the
        spike can't mask itself)."""
        dur_ms = float(dur_ms)
        regressed = False
        if self.n >= self.warmup and self.ewma is not None \
                and dur_ms > self.factor * self.ewma:
            self.regressions += 1
            regressed = True
            self._alert("step_time_regression",
                        {"step_ms": round(dur_ms, 3),
                         "ewma_ms": round(self.ewma, 3),
                         "factor": self.factor}, step=step)
        self.ewma = dur_ms if self.ewma is None else \
            self.alpha * dur_ms + (1.0 - self.alpha) * self.ewma
        self.n += 1
        return regressed


class StallWatchdog(threading.Thread):
    """Daemon heartbeat monitor: fires `on_stall(age_s)` when no
    heartbeat lands within `deadline_s`. Re-arms on the next beat."""

    def __init__(self, deadline_s: float, on_stall,
                 check_interval_s: float | None = None):
        super().__init__(name="mxtpu-healthmon-watchdog", daemon=True)
        self.deadline_s = float(deadline_s)
        self._on_stall = on_stall
        self._interval = (check_interval_s if check_interval_s is not None
                          else max(0.05, min(5.0, self.deadline_s / 4.0)))
        self._last = time.monotonic()   # enable time counts: a job that
        self._fired = False             # hangs before step 1 still alerts
        self._stop_ev = threading.Event()
        self.stalls = 0

    def beat(self):
        self._last = time.monotonic()
        self._fired = False

    def run(self):
        while not self._stop_ev.wait(self._interval):
            age = time.monotonic() - self._last
            if not self._fired and age > self.deadline_s:
                self._fired = True
                self.stalls += 1
                try:
                    self._on_stall(age)
                except Exception:
                    pass   # the watchdog must never kill the host run

    def stop(self, timeout: float = 5.0):
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)
