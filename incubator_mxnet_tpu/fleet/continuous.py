"""ContinuousBatcher — iteration-level (Orca-style) request scheduling.

`DynamicBatcher` is Clipper-shaped: the dispatcher holds the first
request of a batch open for up to ``max_delay_ms`` hoping more arrive,
then dispatches and only *afterwards* looks at the queue again. That
coalescing hold is the right trade for sporadic traffic, but under
sustained load it is pure added latency: the device sits idle through
every hold window, and a request that arrives one microsecond after a
dispatch waits out the *entire* next window before it is even
considered.

The continuous batcher replaces the hold with iteration-level
scheduling (the Orca move, the scheduling core of every modern LLM
serving engine):

* the dispatcher never waits once work exists — each iteration it
  takes *everything* queued (up to ``max_batch``), picks the smallest
  compiled bucket that fits, and dispatches immediately;
* requests arriving **while a dispatch is in flight** are admitted
  into the queue and land in the very next iteration's slots — they
  ride the device's own execution wall instead of an artificial timer.
  Each such request's servescope span is stamped ``slotted`` (and
  ``serving.slotted_admissions`` counts them all), so mid-flight
  admission is provable per request;
* batching still emerges — it is driven by the device being busy
  (arrivals during an iteration pile up for the next one) rather than
  by a timer — and every admission-control edge of the base class is
  inherited unchanged: validation, queue-limit backpressure, deadline
  rejection before *and* after device time, drain semantics, and the
  full servescope lifecycle taxonomy.

The scheduler state this class adds on top of `DynamicBatcher` is one
flag, ``_in_flight``, only ever written under the base class's
``_cond`` lock: True from the moment an iteration's slots are taken to
the moment its last response is fulfilled.
"""
from __future__ import annotations

import time

from .. import profiler as _prof
from .. import servescope as _ss
from ..serving.batcher import DynamicBatcher

__all__ = ["ContinuousBatcher"]


def _c(name):
    return _prof.counter(name, "serving")


class ContinuousBatcher(DynamicBatcher):
    """Slot-based continuous batching over FrozenModel's buckets.

    Accepts the same constructor knobs as `DynamicBatcher` so the two
    are drop-in interchangeable from `ModelServer`; ``max_delay_ms`` is
    accepted for that symmetry but never used — this scheduler has no
    coalescing hold by construction.
    """

    def __init__(self, model, max_batch=None, max_delay_ms=0.0,
                 queue_limit=256, default_timeout_ms=1000.0):
        super().__init__(model, max_batch=max_batch,
                         max_delay_ms=max_delay_ms,
                         queue_limit=queue_limit,
                         default_timeout_ms=default_timeout_ms)
        self._in_flight = False    # written only under self._cond

    # -- admission --------------------------------------------------------
    def _on_admit(self, req):
        # called by the base submit() under self._cond, right after the
        # request landed in the queue: if an iteration is executing on
        # the device right now, this request will ride the NEXT
        # iteration's slots — the mid-flight admission the coalescing
        # scheduler cannot do
        if self._in_flight:
            _c("serving.slotted_admissions").increment()
            if req.span is not None:
                _ss.spans.mark_slotted(req.span)

    # -- dispatch loop ----------------------------------------------------
    def _gather(self):
        """Take everything queued (up to max_batch) the moment anything
        is queued — no hold window. Returns [] at shutdown."""
        with self._cond:
            while not self._q:
                if self._stopped:
                    return []
                self._cond.wait(0.05)
            gather_start = time.perf_counter()
            batch = []
            while self._q and len(batch) < self.max_batch:
                batch.append(self._q.popleft())
            _prof.set_gauge("serving.queue_depth", len(self._q), "serving")
            # slots are taken: from here until this iteration's fulfil
            # fan-out completes, arrivals are mid-flight admissions
            self._in_flight = True
            if _ss._SS is not None:
                for req in batch:
                    if req.span is not None:
                        _ss.spans.mark_gather(req.span, gather_start)
            return batch

    def _serve(self, batch):
        try:
            super()._serve(batch)
        finally:
            with self._cond:
                self._in_flight = False
