"""Replica — one serving process-in-miniature plus its fleet identity.

A `Replica` wraps one `ModelServer` with the state the router needs to
dispatch to it safely:

* **health snapshot** — the last deep ``/healthz`` body (taken over the
  real HTTP wire, the same path an external load balancer would poll),
  its status code, and a consecutive-failure count so one dropped poll
  does not flap the replica out of rotation;
* **draining flag** — the *router-side* exclusion bit used by draining
  deploys. Distinct from the server's own ``_draining``: the router
  stops sending first, the server keeps serving what it already has;
* **outstanding count** — how many router-forwarded requests are in
  flight on this replica right now (incremented before the forward,
  decremented when the response lands, under the router's lock). This
  is the ground truth a drain waits on, and the freshest half of the
  load score — the polled queue depth is at worst one poll interval
  stale.

The load score the router minimizes is ``outstanding + polled queue
depth``, with a large constant penalty when the replica's last deep
health carried a flagged resharding verdict — commscope's "accidental
all-gather on the serve path" is a per-request p99 catastrophe
(docs/commscope.md), so a layout-clean replica always wins over a
flagged one, and a flagged one still serves when it is all we have.
"""
from __future__ import annotations

import http.client
import json

__all__ = ["Replica", "RESHARD_PENALTY"]

# load-score penalty for a replica whose deep health flags resharding
# collectives on any compiled bucket: larger than any realistic queue
# depth so clean replicas always win, finite so a degraded fleet still
# serves
RESHARD_PENALTY = 1_000_000


class Replica:
    """One ModelServer + the router-facing view of it.

    Two ownership modes, one interface: in-process (``server`` is the
    `ModelServer` object — tests, single-core debug) and spawned
    (``proc`` is the worker subprocess, ``host``/``port`` from its
    readiness handshake — the scaling mode; see `fleet/worker.py`).
    The router never branches on the mode: addressing, probing and the
    load score read identically over the HTTP wire either way."""

    def __init__(self, name, server=None, proc=None, host=None,
                 port=None, diag_port=None):
        self.name = str(name)
        self.server = server
        self.proc = proc               # worker subprocess (spawn mode)
        self._host = host
        self._port = port
        self.diag_port = diag_port     # diagnostics.export HTTP port, if
        #                                the worker started one (the
        #                                fleetscope collector's pull target)
        self.cache_stats = None        # worker-reported warmup cache hits
        self.draining = False          # router-side exclusion (deploys)
        self.outstanding = 0           # router-held in-flight forwards
        self.last_health = None        # last deep /healthz body
        self.health_code = None
        self.healthy = False           # no poll yet -> not routable
        self.consecutive_failures = 0

    # -- addressing -------------------------------------------------------
    @property
    def host(self):
        return self.server.host if self.server is not None else self._host

    @property
    def port(self):
        return self.server.port if self.server is not None else self._port

    @property
    def address(self):
        return f"http://{self.host}:{self.port}"

    # -- health -----------------------------------------------------------
    def probe(self, timeout=2.0):
        """One deep ``GET /healthz`` over the wire; updates the
        snapshot and returns ``(code, body)``. Raises on transport
        errors (the router counts those as poll failures)."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = json.loads(resp.read() or b"{}")
            self.health_code = resp.status
            self.last_health = body
            self.healthy = resp.status == 200
            self.consecutive_failures = 0
            return resp.status, body
        finally:
            conn.close()

    def http_get(self, path, timeout=5.0):
        """GET a JSON document from this replica (``/stats`` mostly)."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()

    # -- routing inputs ---------------------------------------------------
    def queue_depth(self) -> int:
        """Queue depth from the last deep-health poll (0 when no poll
        has landed yet)."""
        checks = (self.last_health or {}).get("checks") or {}
        try:
            return int(checks.get("queue_depth") or 0)
        except (TypeError, ValueError):
            return 0

    def resharding_flagged(self) -> bool:
        """Did the last deep health carry a flagged resharding verdict
        on any compiled bucket?"""
        checks = (self.last_health or {}).get("checks") or {}
        resh = checks.get("resharding") or {}
        return bool(resh.get("buckets_flagged"))

    def headroom(self):
        """Memory headroom fraction from the last deep-health poll's
        memscope block (None when memscope isn't armed on the replica
        or no poll has landed) — admission/operator context, not a
        routing input: a tight replica still serves."""
        checks = (self.last_health or {}).get("checks") or {}
        ms = checks.get("memscope") or {}
        hf = ms.get("headroom_fraction")
        return float(hf) if isinstance(hf, (int, float)) \
            and not isinstance(hf, bool) else None

    def servescope_p99(self):
        """This replica's current e2e p99 (ms) from the last deep
        health's servescope brief — report-only pod context (the
        ``mxdiag.py pod`` straggler flag compares these ACROSS
        replicas; a slow replica still serves). None when servescope
        isn't armed on the replica or no poll has landed."""
        checks = (self.last_health or {}).get("checks") or {}
        brief = checks.get("servescope_p99") or {}
        p99 = brief.get("e2e_p99_ms")
        return float(p99) if isinstance(p99, (int, float)) \
            and not isinstance(p99, bool) else None

    def live_queue_depth(self) -> int:
        """The freshest queue depth available — the in-process batcher
        when we own the server object, else one probe over the wire
        (what a drain's settle condition polls)."""
        if self.server is not None:
            return self.server.batcher.queue_depth
        try:
            self.probe(timeout=2.0)
        except Exception:  # noqa: BLE001 — a dead replica queues nothing
            return 0
        return self.queue_depth()

    def load_score(self) -> int:
        """What the router minimizes: live outstanding forwards + the
        polled queue depth + the resharding penalty when flagged."""
        score = self.outstanding + self.queue_depth()
        if self.resharding_flagged():
            score += RESHARD_PENALTY
        return score

    def snapshot(self) -> dict:
        """The per-replica row /stats and mxdiag render."""
        return {
            "name": self.name,
            "address": self.address,
            "healthy": self.healthy,
            "health_code": self.health_code,
            "draining": self.draining,
            "outstanding": self.outstanding,
            "queue_depth": self.queue_depth(),
            "resharding_flagged": self.resharding_flagged(),
            "headroom": self.headroom(),
            "p99_ms": self.servescope_p99(),
            "diag_port": self.diag_port,
            "consecutive_failures": self.consecutive_failures,
            "in_process": self.server is not None,
            "pid": self.proc.pid if self.proc is not None else None,
        }

    def __repr__(self):
        return (f"Replica({self.name!r}, {self.address}, "
                f"healthy={self.healthy}, draining={self.draining})")
