"""mxtpu.fleet — continuous batching and a multi-replica serving fleet.

PR 4's `mxtpu.serving` took one model to one chip behind one HTTP
server; this package takes that server to a fleet, in three layers
(docs/serving.md has the full scheduler model and deploy runbook):

* :class:`ContinuousBatcher` (`continuous.py`) — iteration-level
  scheduling in place of the coalesce-then-dispatch hold: requests are
  admitted **mid-flight** into the next bucket dispatch, each such
  request's servescope span stamped ``slotted``;
* :class:`CompileCache` (`cache.py`) — the shared on-disk AOT
  executable cache: replica N+1 deserializes the buckets replica 0
  compiled (``FrozenModel(..., compile_cache=...)``), counted in the
  governed ``fleet`` family so a deploy can prove its warmup was a
  cache hit;
* :class:`ReplicaSet` + :class:`Router` (`replica.py`, `router.py`) —
  N replicas behind one front door doing least-loaded dispatch off
  the deep ``/healthz`` (live outstanding + polled queue depth, with
  resharding-flagged replicas penalized), plus draining deploys:
  ``Router.deploy`` rolls drain → swap → readmit with zero dropped
  requests.

The quantized/sharded half of the serving story lives where the model
does: ``FrozenModel.quantize()`` (int8 via `contrib/quantization`,
bf16 via ``compute_dtype``) and ``FrozenModel(..., mesh=...)`` with
the resharding gate — see `serving/frozen.py`.
"""
from __future__ import annotations

from .cache import CompileCache, set_shared_cache, shared_cache
from .continuous import ContinuousBatcher
from .replica import Replica
from .router import ReplicaSet, Router

__all__ = ["ContinuousBatcher", "CompileCache", "shared_cache",
           "set_shared_cache", "Replica", "ReplicaSet", "Router"]
