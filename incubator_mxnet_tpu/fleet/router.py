"""ReplicaSet + Router — least-loaded dispatch and draining deploys.

The fleet layer applies the training-side ops discipline (PR 12's
resilience, PR 5's health exchange) to the request path:

* `ReplicaSet` constructs and owns N in-process replicas of one model
  — each its own `ModelServer` on its own port with its own batcher
  (continuous by default: the fleet is the sustained-load path) — and
  threads the shared `CompileCache` through every freeze so replica
  N+1 deserializes executables instead of recompiling them;
* `Router` is the single front door: a stdlib ThreadingHTTPServer that
  forwards ``POST /predict`` to the **least-loaded admitting replica**
  and exposes aggregate ``/healthz`` + ``/stats``. "Least-loaded" is
  scored from healthmon's deep ``/healthz`` — the live outstanding
  count the router itself maintains plus the polled queue depth — with
  a large penalty when the replica's last deep health flagged a
  resharding verdict on any bucket (an accidental all-gather per
  request is a p99 catastrophe; a layout-clean replica always wins);
* **draining deploys**: ``Router.deploy(factory)`` rolls the fleet one
  replica at a time — *drain* (stop routing there, wait for its
  outstanding forwards and queue to reach zero), *swap*
  (`ModelServer.swap_model`, itself zero-downtime), *readmit* (probe,
  then route again). At least one replica serves at every instant and
  no accepted request is ever dropped; each phase lands in the flight
  recorder and ``mxtpu.events/1`` as ``fleet.drain`` /
  ``fleet.swap`` / ``fleet.readmit`` records.

Health polling runs in one daemon thread at ``MXTPU_FLEET_POLL_S``
(default 0.25 s) over the real HTTP wire — the router sees exactly what
an external load balancer would. A replica leaves rotation after
``unhealthy_after`` consecutive poll failures (one dropped poll must
not flap it) and re-enters on the first 200.

Everything is counted in the governed ``fleet`` family
(mxlint/families.py): routed / routed_errors / retries /
no_replica_available, health_polls(+errors), drains / swaps /
readmits, compile-cache traffic, replica gauges, and a
``fleet.forward_ms`` histogram.
"""
from __future__ import annotations

import http.client
import json
import os
import socket
import subprocess
import sys
import threading
import time

from .. import fleetscope as _fs
from .. import profiler as _prof
from ..diagnostics import flight as _flight
from ..healthmon import events as _events
from .replica import Replica

__all__ = ["ReplicaSet", "Router"]


def _c(name):
    return _prof.counter(name, "fleet")


def _event(name, args):
    """Drain/swap/readmit breadcrumbs on both shared surfaces."""
    if _flight._REC is not None:
        _flight.record("fleet", name, args)
    if _events._LOG is not None:
        _events.emit("fleet", name, args=args)


class ReplicaSet:
    """Construct and own N replicas of one model.

    Two modes:

    * **in-process** (default): ``model_factory`` is called once per
      replica as ``model_factory(compile_cache=<the set's cache>)`` and
      must return a `FrozenModel` (build it with ``block.freeze(...,
      compile_cache=compile_cache)``). Every replica shares the
      parent's GIL — right for tests, wrong for throughput.
    * **spawned** (``spawn=True``, or pass a spec dict instead of a
      callable): each replica runs as its own
      ``python -m incubator_mxnet_tpu.fleet.worker`` process — its own
      GIL, real multi-core scaling. The spec is `fleet/worker.py`'s
      JSON contract (model-zoo name + freeze/server arguments; a
      closure cannot cross a process boundary). Replica 0 is spawned
      first so its compile-cache stores land before the rest warm up —
      the shared cache is what lets replica N+1 (and every respawn
      deploy) skip the XLA compiles replica 0 already paid for.
    """

    def __init__(self, model_factory, n=2, name="replica",
                 batcher="continuous", compile_cache=None, host=None,
                 server_kwargs=None, spawn=None):
        if int(n) < 1:
            raise ValueError(f"a fleet needs at least one replica, got {n}")
        if spawn is None:
            spawn = isinstance(model_factory, dict)
        self.spawn = bool(spawn)
        if self.spawn and not isinstance(model_factory, dict):
            raise TypeError("spawn=True needs a worker spec dict, "
                            "not a callable (closures cannot cross a "
                            "process boundary)")
        self.model_factory = model_factory
        self.spec = dict(model_factory) if self.spawn else None
        self.n = int(n)
        self.name = str(name)
        self.batcher = batcher
        if compile_cache is None and not self.spawn:
            from .cache import shared_cache
            compile_cache = shared_cache()
        self.compile_cache = compile_cache
        self.host = host
        self.server_kwargs = dict(server_kwargs or {})
        self.replicas = []

    def _worker_spec(self):
        spec = dict(self.spec)
        spec.setdefault("batcher", self.batcher)
        if self.server_kwargs:
            server = dict(self.server_kwargs)
            server.update(spec.get("server") or {})
            spec["server"] = server
        if self.compile_cache is not None:
            path = getattr(self.compile_cache, "path", self.compile_cache)
            spec.setdefault("cache_dir", str(path))
        if self.host:
            spec.setdefault("host", self.host)
        return spec

    def _spawn_one(self, name, timeout=600.0):
        """Spawn one worker process and block on its readiness
        handshake (model freeze + warmup happen before the ready line,
        so a returned replica is immediately servable)."""
        import select
        from .worker import READY_TAG
        spec = self._worker_spec()
        # the package must be importable from the child no matter how
        # the parent put it on sys.path
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "incubator_mxnet_tpu.fleet.worker",
             "--spec", json.dumps(spec)],
            stdout=subprocess.PIPE, env=env, text=True)
        deadline = time.time() + timeout
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {name} exited rc={proc.returncode} "
                    f"before becoming ready")
            ready, _, _ = select.select([proc.stdout], [], [], 1.0)
            if not ready:
                continue
            line = proc.stdout.readline()
            if not line:
                continue
            if not line.startswith(READY_TAG):
                continue
            fields = dict(tok.split("=", 1) for tok in line.split()
                          if "=" in tok)
            rep = Replica(name, proc=proc, host=fields.get("host"),
                          port=int(fields.get("port", 0)),
                          diag_port=(int(fields["diag_port"])
                                     if "diag_port" in fields else None))
            rep.cache_stats = {
                k: int(fields.get(f"cache_{k}", 0))
                for k in ("hits", "misses", "stores")}
            return rep
        proc.kill()
        raise RuntimeError(f"fleet worker {name} not ready after "
                           f"{timeout:.0f}s")

    def start(self):
        """Freeze + start every replica; returns the replica list."""
        if self.spawn:
            # replica 0 alone first: its cache stores must land before
            # the rest warm up, or every replica pays the compile
            self.replicas.append(self._spawn_one(f"{self.name}0"))
            rest = list(range(1, self.n))
            results = {}

            def spawn_into(i):
                results[i] = self._spawn_one(f"{self.name}{i}")

            threads = [threading.Thread(target=spawn_into, args=(i,),
                                        daemon=True) for i in rest]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            missing = [i for i in rest if i not in results]
            if missing:
                self.stop(drain=False)
                raise RuntimeError(f"fleet workers {missing} failed to "
                                   f"spawn")
            self.replicas.extend(results[i] for i in rest)
        else:
            from ..serving.server import ModelServer
            for i in range(self.n):
                model = self.model_factory(
                    compile_cache=self.compile_cache)
                srv = ModelServer(model, host=self.host,
                                  batcher=self.batcher,
                                  **self.server_kwargs)
                srv.start()
                self.replicas.append(Replica(f"{self.name}{i}", srv))
        _prof.set_gauge("fleet.replicas", len(self.replicas), "fleet")
        return self.replicas

    def respawn(self, rep, spec=None):
        """Replace a spawned replica's worker process (the deploy
        primitive: replicas are cattle). Blue-green per replica: the
        fresh worker warms from the shared cache FIRST, then the old
        process is retired — the replica object keeps its fleet
        identity (name, health history slots) but points at the new
        process. The caller (Router.deploy) drains `rep` first."""
        if rep.proc is None:
            raise ValueError(f"{rep.name} is in-process — use "
                             f"ModelServer.swap_model, not respawn")
        if spec is not None:
            self.spec = dict(spec)
        fresh = self._spawn_one(rep.name)
        old = rep.proc
        rep.proc = fresh.proc
        rep._host, rep._port = fresh._host, fresh._port
        rep.diag_port = fresh.diag_port
        rep.cache_stats = fresh.cache_stats
        rep.last_health, rep.health_code = None, None
        rep.consecutive_failures = 0
        old.terminate()
        try:
            old.wait(timeout=30)
        except subprocess.TimeoutExpired:
            old.kill()
        return rep

    def stop(self, drain=True):
        for rep in self.replicas:
            if rep.server is not None:
                rep.server.stop(drain=drain)
            elif rep.proc is not None:
                # SIGTERM -> worker drains its batcher, then exits
                rep.proc.terminate()
        for rep in self.replicas:
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=30 if drain else 10)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()
        _prof.set_gauge("fleet.replicas", 0, "fleet")
        _prof.set_gauge("fleet.replicas_healthy", 0, "fleet")


class Router:
    """Least-loaded HTTP front door over a list of `Replica`s."""

    def __init__(self, replicas, host="127.0.0.1", port=0,
                 poll_interval_s=None, forward_retries=1,
                 unhealthy_after=2):
        self._rset = replicas if isinstance(replicas, ReplicaSet) else None
        if isinstance(replicas, ReplicaSet):
            replicas = replicas.replicas
        self.replicas = list(replicas)
        self.host = host
        self.port = int(port)
        from ..autotune.knobs import env_float
        self.poll_interval_s = float(
            env_float("MXTPU_FLEET_POLL_S", 0.25,
                      call_site=poll_interval_s))
        self.forward_timeout_s = float(
            env_float("MXTPU_FLEET_FORWARD_TIMEOUT_S", 60.0))
        self.forward_retries = int(forward_retries)
        self.unhealthy_after = int(unhealthy_after)
        self._lock = threading.Lock()
        self._rr = 0                      # round-robin tie-break cursor
        self._local = threading.local()   # keep-alive conns per thread
        self._stop_evt = threading.Event()
        self._poller = None
        self._httpd = None
        self._started_at = None
        self.dispatch_counts = {r.name: 0 for r in self.replicas}

    # -- health polling ---------------------------------------------------
    def _poll_once(self):
        healthy = 0
        for rep in self.replicas:
            try:
                rep.probe(timeout=2.0)
                _c("fleet.health_polls").increment()
            except Exception:  # noqa: BLE001 — a dead replica must not
                _c("fleet.health_poll_errors").increment()   # kill polling
                rep.consecutive_failures += 1
                if rep.consecutive_failures >= self.unhealthy_after:
                    rep.healthy = False
            if rep.healthy:
                healthy += 1
        _prof.set_gauge("fleet.replicas_healthy", healthy, "fleet")

    def _poll_loop(self):
        while not self._stop_evt.wait(self.poll_interval_s):
            self._poll_once()

    # -- dispatch ---------------------------------------------------------
    def _pick(self):
        """The least-loaded admitting replica (score from the deep
        health snapshot + live outstanding count; round-robin among
        ties), or None when nothing is routable."""
        with self._lock:
            cands = [(i, r) for i, r in enumerate(self.replicas)
                     if r.healthy and not r.draining]
            if not cands:
                return None
            n = len(self.replicas)
            rr = self._rr
            self._rr = rr + 1
            best = min(cands,
                       key=lambda ir: (ir[1].load_score(),
                                       (ir[0] - rr) % n))[1]
            best.outstanding += 1
            return best

    def _release(self, rep):
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - 1)

    def _forward(self, rep, body, traceparent=None):
        """One forward on this thread's keep-alive connection to `rep`;
        a stale kept-alive socket gets ONE fresh-connection retry, any
        other failure propagates to the caller's failover loop. The
        optional ``traceparent`` is the router's OWN span context — the
        replica's servescope span becomes its child."""
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        headers = {"Content-Type": "application/json"}
        if traceparent is not None:
            headers["traceparent"] = traceparent
        for attempt in (0, 1):
            conn = conns.get(rep.name)
            if conn is None:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.forward_timeout_s)
                conn.connect()
                # same delayed-ACK stall as the serving handler: the
                # forwarded reply is a small write behind a small write
                conn.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
                conns[rep.name] = conn
            try:
                conn.request("POST", "/predict", body=body,
                             headers=headers)
                resp = conn.getresponse()
                return resp.status, resp.read()
            except Exception:
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                conns.pop(rep.name, None)
                if attempt:
                    raise
        raise RuntimeError("unreachable")

    def handle_predict(self, body, traceparent=None):
        """Route one /predict body; returns ``(status, reply_dict)``.
        Tries up to ``forward_retries + 1`` distinct replicas before
        giving up — a replica that fails mid-forward is failed over,
        not surfaced to the client.

        When fleetscope is armed the router is the ROOT hop: it accepts
        the client's ``traceparent`` (or mints a fresh trace — a
        malformed header is counted and re-minted, never guessed) and
        forwards its own child span to the replica, so one request is
        one trace across processes."""
        fs = _fs._FS
        rctx = None
        if fs is not None:
            # upstream view (the client's span, or a synthesized
            # client-edge root when the header is absent/malformed);
            # the router's own span is always its child
            rctx = fs.accept(traceparent).child()
        t_start = time.perf_counter()
        tried = set()
        for attempt in range(self.forward_retries + 1):
            rep = self._pick()
            if rep is None or rep.name in tried:
                if rep is not None:
                    self._release(rep)
                break
            tried.add(rep.name)
            t0 = time.perf_counter()
            try:
                status, raw = self._forward(
                    rep, body,
                    rctx.header() if rctx is not None else None)
            except Exception:  # noqa: BLE001 — transport failure: fail over
                _c("fleet.routed_errors").increment()
                rep.consecutive_failures += 1
                if rep.consecutive_failures >= self.unhealthy_after:
                    rep.healthy = False
                continue
            finally:
                self._release(rep)
            forward_ms = (time.perf_counter() - t0) * 1e3
            _c("fleet.routed").increment()
            _prof.observe("fleet.forward_ms", forward_ms, "fleet")
            with self._lock:
                self.dispatch_counts[rep.name] = \
                    self.dispatch_counts.get(rep.name, 0) + 1
            try:
                doc = json.loads(raw or b"{}")
                if isinstance(doc, dict):
                    doc["replica"] = rep.name
            except ValueError:
                doc = {"error": "BadReplicaResponse",
                       "message": "replica returned non-JSON",
                       "replica": rep.name}
                status = 502
            if rctx is not None:
                if isinstance(doc, dict):
                    doc.setdefault("trace_id", rctx.trace_id)
                self._trace_event(rctx, rep.name, status, forward_ms,
                                  (time.perf_counter() - t_start) * 1e3)
            return status, doc
        _c("fleet.no_replica_available").increment()
        if rctx is not None:
            # the trace still records the failed route: an unjoined
            # router-side record is a datum the join rate must count
            self._trace_event(rctx, None, 503, None,
                              (time.perf_counter() - t_start) * 1e3)
        return 503, {"error": "NoReplicaAvailable",
                     "message": "no healthy admitting replica"}

    @staticmethod
    def _trace_event(rctx, replica, status, forward_ms, e2e_ms):
        """The router side of the cross-process join: one
        ``fleetscope.request`` record per routed request, carrying the
        router span + the two router-clock walls the wire-gap math
        needs (forward wall vs replica-reported e2e is a difference of
        perf_counter durations — clock-skew free)."""
        args = {"trace_id": rctx.trace_id, "span_id": rctx.span_id,
                "parent_id": rctx.parent_id, "replica": replica,
                "status": status, "e2e_ms": round(e2e_ms, 3)}
        if forward_ms is not None:
            args["forward_ms"] = round(forward_ms, 3)
        if _flight._REC is not None:
            _flight.record("fleetscope", "fleetscope.request", dict(args))
        if _events._LOG is not None:
            _events.emit("fleetscope", "fleetscope.request", args=args)

    # -- aggregate surfaces ----------------------------------------------
    def health(self):
        """(code, body): 200 while at least one replica is admitting."""
        rows = [r.snapshot() for r in self.replicas]
        admitting = sum(1 for r in rows
                        if r["healthy"] and not r["draining"])
        status = "ok" if admitting else "degraded"
        return (200 if admitting else 503), {
            "status": status, "role": "router",
            "replicas": rows, "admitting": admitting}

    def stats(self) -> dict:
        """Router counters + per-replica rows + dispatch balance."""
        snap = {k.split("/", 1)[1]: v for k, v in _prof.counters().items()
                if k.startswith("fleet/")}
        with self._lock:
            counts = dict(self.dispatch_counts)
        rows = [r.snapshot() for r in self.replicas]
        for row in rows:
            row["dispatched"] = counts.get(row["name"], 0)
        vals = list(counts.values())
        mean = (sum(vals) / len(vals)) if vals else 0.0
        snap["dispatch_counts"] = counts
        snap["dispatch_imbalance"] = (max(vals) / mean
                                      if vals and mean > 0 else 0.0)
        snap["replicas"] = rows
        if self._started_at:
            snap["uptime_s"] = round(time.time() - self._started_at, 3)
        return snap

    # -- draining deploys -------------------------------------------------
    def drain(self, rep, timeout=30.0) -> bool:
        """Stop routing to `rep`, then wait until its outstanding
        forwards AND its batcher queue are empty. Returns False on
        timeout (the replica is left draining — readmit explicitly)."""
        with self._lock:
            rep.draining = True
        _c("fleet.drains").increment()
        _event("fleet.drain", {"replica": rep.name})
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._lock:
                outstanding = rep.outstanding
            if outstanding == 0 and rep.live_queue_depth() == 0:
                return True
            time.sleep(0.01)
        return False

    def readmit(self, rep):
        """Probe, then route to `rep` again."""
        try:
            rep.probe(timeout=2.0)
        except Exception:  # noqa: BLE001 — the poller will retry
            pass
        with self._lock:
            rep.draining = False
        _c("fleet.readmits").increment()
        _event("fleet.readmit", {"replica": rep.name,
                                 "healthy": rep.healthy})

    def deploy(self, model_factory, compile_cache=None, timeout=60.0):
        """Rolling drain → swap → readmit across the fleet: at least
        one replica admits at every instant and no accepted request is
        dropped. For in-process replicas,
        ``model_factory(compile_cache=...)`` is called once per replica
        (same contract as `ReplicaSet`) and the model is hot-swapped
        via ``ModelServer.swap_model``; for spawned replicas, pass the
        new worker **spec dict** — the deploy is a rolling respawn
        (the fresh process warms from the shared cache before the old
        one is retired)."""
        for rep in self.replicas:
            self.drain(rep, timeout=timeout)
            if rep.server is not None:
                model = model_factory(compile_cache=compile_cache)
                rep.server.swap_model(model)
                desc = repr(model)
            else:
                if self._rset is None:
                    raise RuntimeError("deploying spawned replicas "
                                       "needs the owning ReplicaSet "
                                       "(construct Router with it)")
                spec = model_factory if isinstance(model_factory, dict) \
                    else None
                self._rset.respawn(rep, spec)
                desc = f"respawn pid={rep.proc.pid}"
            _c("fleet.swaps").increment()
            _event("fleet.swap", {"replica": rep.name, "model": desc})
            self.readmit(rep)

    # -- lifecycle --------------------------------------------------------
    def start(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        router = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # see serving/server.py: without TCP_NODELAY the reply's
            # header+body writes hit Nagle vs delayed-ACK (~40 ms/req)
            disable_nagle_algorithm = True

            def _reply(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path.startswith("/healthz"):
                        code, doc = router.health()
                        self._reply(code, doc)
                    elif self.path.startswith("/stats"):
                        self._reply(200, router.stats())
                    else:
                        self._reply(404, {"error": "NotFound",
                                          "message": self.path})
                except Exception as e:  # noqa: BLE001
                    self._safe_500(e)

            def do_POST(self):
                try:
                    if not self.path.startswith("/predict"):
                        self._reply(404, {"error": "NotFound",
                                          "message": self.path})
                        return
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length)
                    tp = (self.headers.get("traceparent")
                          if _fs._FS is not None else None)
                    code, doc = router.handle_predict(body, traceparent=tp)
                    self._reply(code, doc)
                except Exception as e:  # noqa: BLE001
                    self._safe_500(e)

            def _safe_500(self, e):
                try:
                    self._reply(500, {"error": type(e).__name__,
                                      "message": str(e)[:500]})
                except Exception:
                    pass

            def log_message(self, *a):   # stay quiet on stderr
                pass

        class _Server(ThreadingHTTPServer):
            # same SYN-backlog sizing rationale as ModelServer: the
            # router fronts EVERY replica's clients at once
            request_queue_size = 256

        # routing needs health data before the first request arrives
        self._poll_once()
        self._stop_evt.clear()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="mxtpu-fleet-health",
                                        daemon=True)
        self._poller.start()
        self._httpd = _Server((self.host, self.port), _Handler)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="mxtpu-fleet-router", daemon=True)
        t.start()
        self._started_at = time.time()
        _event("fleet.router_start",
               {"replicas": len(self.replicas),
                "address": f"{self.host}:{self.port}"})
        return self.host, self.port

    def stop(self):
        _event("fleet.router_stop",
               {"routed": int(_c("fleet.routed").value)})
        self._stop_evt.set()
        if self._poller is not None:
            self._poller.join(5.0)
            self._poller = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    @property
    def address(self):
        return f"http://{self.host}:{self.port}"
