"""Fleet worker — one replica as its own OS process.

The in-process `ReplicaSet` is perfect for tests and single-core debug,
but it cannot *scale*: every replica shares the parent's GIL, so the
request path's Python work (HTTP parse, JSON decode, pad/stack) is
serialized no matter how many replicas exist — measured on CPU lenet, a
2-replica in-process fleet is ~30% SLOWER than one bare server. This
module is the fix: ``ReplicaSet(spec, spawn=True)`` runs each replica
as ``python -m incubator_mxnet_tpu.fleet.worker --spec <json>`` — its
own process, its own GIL, its own metrics registry — and the shared
on-disk `CompileCache` becomes genuinely load-bearing: replica N+1
deserializes the AOT buckets replica 0 compiled, across process
boundaries.

Protocol (parent = `ReplicaSet._spawn_one`):

* the worker builds the model from the **spec** (a model-zoo name +
  freeze arguments — a closure cannot cross a process boundary), starts
  a `ModelServer`, then prints ONE readiness line to stdout::

      MXTPU_FLEET_WORKER ready host=H port=P pid=N \\
          cache_hits=H cache_misses=M cache_stores=S

  The cache numbers are the worker's own registry snapshot at ready
  time — how the parent proves replica N+1's warmup was a cache hit
  without reaching into another process's metrics.
* the worker then serves until SIGTERM/SIGINT, drains its batcher
  (`stop(drain=True)`: every queued request settles, none dropped),
  and exits 0. Deploys are rolling **respawns**: drain at the router,
  start a fresh worker (warming from the shared cache), retire the old
  process — replicas are cattle, not pets.

Spec keys: ``model`` (model-zoo name), ``classes``, ``model_kwargs``,
``input_shape`` (per-sample), ``dtype``, ``quantize``
(``int8``/``bf16``/absent), ``batcher``, ``cache_dir`` (shared
`CompileCache` directory), ``host``, ``server`` (ModelServer kwargs:
``max_delay_ms`` / ``queue_limit`` / ``default_timeout_ms``),
``events`` (``{path, run_id, rank}`` — opens this worker's own
``mxtpu.events/2`` log, mergeable with ``mxdiag.py merge``; a literal
``{pid}`` in the path is replaced with the worker's PID so replicas
sharing one spec dict never write over each other — the parent knows
each child's PID and can find the file),
``servescope`` (truthy — arm request-lifecycle spans in this worker;
``True`` samples every request, a number is the servescope sample
rate/stride), ``fleetscope`` (truthy — arm cross-process trace
propagation so forwarded ``traceparent`` headers join this worker's
servescope spans),
and ``export`` (truthy — start a ``diagnostics.export`` HTTP server on
a free port and report it as ``diag_port=P`` in the readiness line;
the fleetscope collector's pull target).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

__all__ = ["READY_TAG", "build_model", "main"]

READY_TAG = "MXTPU_FLEET_WORKER"


def build_model(spec):
    """Freeze (and optionally quantize) the spec'd model-zoo network,
    warming through the shared compile cache when one is configured."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import get_model

    net = get_model(spec["model"], classes=int(spec.get("classes", 10)),
                    **(spec.get("model_kwargs") or {}))
    net.initialize(init=mx.init.Xavier())
    cache = None
    if spec.get("cache_dir"):
        from .cache import CompileCache
        cache = CompileCache(spec["cache_dir"])
    frozen = net.freeze(input_shape=tuple(spec["input_shape"]),
                        dtype=spec.get("dtype", "float32"),
                        compile_cache=cache)
    if spec.get("quantize"):
        frozen = frozen.quantize(spec["quantize"], compile_cache=cache)
    return frozen


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.fleet.worker",
        description="one serving replica as its own process "
                    "(spawned by fleet.ReplicaSet, not run by hand)")
    ap.add_argument("--spec", required=True,
                    help="replica spec JSON, inline or @/path/to/file")
    args = ap.parse_args(argv)
    raw = args.spec
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    spec = json.loads(raw)

    from .. import profiler as _prof
    from ..healthmon import events as _events
    from ..serving.server import ModelServer

    ev = spec.get("events") or {}
    if ev.get("path"):
        # one spec dict is shared by every replica; {pid} keeps their
        # events logs apart (the parent joins back via the child PID)
        path = str(ev["path"]).replace("{pid}", str(os.getpid()))
        _events.open_log(path, run_id=ev.get("run_id", "fleet"),
                         rank=int(ev.get("rank", 0)))
    if spec.get("servescope"):
        from .. import servescope as _servescope
        sv = spec["servescope"]
        _servescope.enable(sample=None if sv is True else sv)
    if spec.get("fleetscope"):
        from .. import fleetscope as _fleetscope
        _fleetscope.enable()
    diag_port = None
    if spec.get("export"):
        # the fleetscope collector's pull target: this worker's own
        # counters/events over the diagnostics.export HTTP surface
        from ..diagnostics import export as _export
        _, diag_port = _export.start_http(port=0)

    model = build_model(spec)
    srv = ModelServer(model, host=spec.get("host") or "127.0.0.1",
                      batcher=spec.get("batcher", "continuous"),
                      **(spec.get("server") or {}))
    host, port = srv.start()

    snap = _prof.counters()

    def cache_count(name):
        return int(snap.get(f"fleet/fleet.compile_cache_{name}", 0))

    # the ONE readiness line the parent handshake parses (diag_port only
    # when the spec asked for an export server — absent means absent)
    diag = f" diag_port={diag_port}" if diag_port is not None else ""
    print(f"{READY_TAG} ready host={host} port={port} pid={os.getpid()} "
          f"cache_hits={cache_count('hits')} "
          f"cache_misses={cache_count('misses')} "
          f"cache_stores={cache_count('stores')}{diag}", flush=True)

    stop_evt = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_a: stop_evt.set())
    stop_evt.wait()
    # drain, never drop: queued requests settle before the process exits
    srv.stop(drain=True)
    _events.close_log()
    return 0


if __name__ == "__main__":
    sys.exit(main())
