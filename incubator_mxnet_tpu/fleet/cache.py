"""Shared on-disk AOT compile cache — replica N+1 skips the XLA compile.

A `FrozenModel` pays its compile cost at construction, per bucket.
That is the right trade for one replica (deploy-time, not
request-time), but a fleet multiplies it: N replicas of the *same*
model recompile the *same* executables N times, so replica N+1's
warmup costs exactly as much as replica 0's. The params are runtime
*arguments* of the raw serving function (not baked constants), so two
freezes of architecturally identical blocks lower to byte-identical
StableHLO — the compile is pure waste after the first replica.

`CompileCache` keys on ``sha256(lowered StableHLO text + jax version +
backend)`` and stores `jax.experimental.serialize_executable` payloads:

* **in-process layer** — a dict of live compiled executables (XLA
  executables are immutable and thread-safe to execute), so co-hosted
  replicas share the very same executable object;
* **on-disk layer** — the serialized payload under ``<dir>/<key>.jexec``
  (atomic tmp+rename writes, so concurrent replica processes can share
  one directory), so a *new process* — replica N+1 on another port, a
  restarted replica mid-deploy — deserializes instead of compiling.

Both ``load`` and ``store`` are total: any surprise (version skew, a
torn file, an unpicklable tree) costs one ``fleet.compile_cache_errors``
increment and falls back to a fresh compile — a cache can make a deploy
faster, never break it. Hits/misses/stores are counted in the governed
``fleet`` family so the smoke can *prove* replica 2 skipped its
compiles rather than trusting a wall-clock diff.

`FrozenModel` takes the cache as an explicit ``compile_cache=`` duck:
anything with ``load(lowered)`` / ``store(lowered, compiled)``. The
serving layer stays fleet-agnostic; `ReplicaSet` wires the shared
instance through.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import threading

import jax

from .. import profiler as _prof

__all__ = ["CompileCache", "shared_cache", "set_shared_cache"]


def _c(name):
    return _prof.counter(name, "fleet")


class CompileCache:
    """Two-layer (process dict + directory) AOT executable cache."""

    def __init__(self, path):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self._mem = {}
        self._lock = threading.Lock()

    # -- keying -----------------------------------------------------------
    @staticmethod
    def key_for(lowered) -> str:
        """Content key of one lowered bucket: the StableHLO text pins
        the program, the jax version + backend pin the serialization
        format and the runtime it must load into."""
        h = hashlib.sha256()
        h.update(lowered.as_text().encode())
        h.update(jax.__version__.encode())
        h.update(jax.default_backend().encode())
        return h.hexdigest()

    def _file_for(self, key) -> str:
        return os.path.join(self.path, key + ".jexec")

    # -- lookup -----------------------------------------------------------
    def load(self, lowered):
        """The compiled executable for this lowering, or None on miss.
        Never raises — a cache surprise costs a compile, not the
        deploy."""
        try:
            key = self.key_for(lowered)
            with self._lock:
                hit = self._mem.get(key)
            if hit is not None:
                _c("fleet.compile_cache_hits").increment()
                return hit
            path = self._file_for(key)
            if not os.path.exists(path):
                _c("fleet.compile_cache_misses").increment()
                return None
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            from jax.experimental.serialize_executable import \
                deserialize_and_load
            compiled = deserialize_and_load(payload, in_tree, out_tree)
            with self._lock:
                self._mem[key] = compiled
            _c("fleet.compile_cache_hits").increment()
            return compiled
        except Exception:  # noqa: BLE001 — total by contract
            _c("fleet.compile_cache_errors").increment()
            return None

    def store(self, lowered, compiled):
        """Serialize one freshly compiled executable into both layers
        (atomic tmp+rename so a concurrent reader never sees a torn
        file). Never raises."""
        try:
            key = self.key_for(lowered)
            with self._lock:
                self._mem[key] = compiled
            from jax.experimental.serialize_executable import serialize
            payload, in_tree, out_tree = serialize(compiled)
            fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump((payload, in_tree, out_tree), f)
                os.replace(tmp, self._file_for(key))
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            _c("fleet.compile_cache_stores").increment()
        except Exception:  # noqa: BLE001 — total by contract
            _c("fleet.compile_cache_errors").increment()

    def entries(self) -> int:
        """On-disk entry count (diagnostics only)."""
        try:
            return sum(1 for n in os.listdir(self.path)
                       if n.endswith(".jexec"))
        except OSError:
            return 0

    def __repr__(self):
        return f"CompileCache({self.path!r}, entries={self.entries()})"


# ---------------------------------------------------------------------------
# process-wide default (ReplicaSet's fallback), resolved once from the
# MXTPU_FLEET_CACHE knob
# ---------------------------------------------------------------------------

_shared_lock = threading.Lock()
_shared = {"cache": None, "resolved": False}


def shared_cache():
    """The process-wide default CompileCache, or None. Resolved once
    from ``MXTPU_FLEET_CACHE`` (a directory path; empty/unset means no
    cache) unless `set_shared_cache` installed one explicitly."""
    with _shared_lock:
        if not _shared["resolved"]:
            from ..autotune.knobs import env_str
            path = env_str("MXTPU_FLEET_CACHE", "")
            _shared["cache"] = CompileCache(path) if path else None
            _shared["resolved"] = True
        return _shared["cache"]


def set_shared_cache(cache):
    """Install (or clear, with None) the process-wide default. Accepts
    a CompileCache or a directory path."""
    if isinstance(cache, str):
        cache = CompileCache(cache)
    with _shared_lock:
        _shared["cache"] = cache
        _shared["resolved"] = True
    return cache
