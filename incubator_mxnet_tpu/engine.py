"""Execution-engine surface (parity: reference src/engine/ threaded
dependency engine; python-side mx.engine hooks).

Device compute is async-scheduled by XLA (its dispatch queue is the
reference's per-device op queue); this module exposes the NATIVE host-side
dependency engine (runtime/) with the reference's push/var semantics for
IO-grade tasks, plus the engine-selection switch:

    MXTPU_ENGINE=native   (default) C++ threaded engine, GIL-free blocking
    MXTPU_ENGINE=python   pure-Python fallback (the reference's NaiveEngine
                          analogue for debugging)
"""
from __future__ import annotations

from . import bulk as _bulk_mod
from . import profiler as _prof
from . import runtime as _rt
from . import ndarray as _nd
from .diagnostics import flight as _flight
from .runtime import engine_type, get_engine

__all__ = ["push", "new_var", "wait_for_var", "wait_all", "engine_type",
           "get_engine", "bulk", "set_bulk_size", "bulk_size"]


def new_var() -> int:
    return get_engine().new_var()


def push(fn, const_vars=(), mutable_vars=()):
    """Schedule fn once deps resolve: concurrent reads, exclusive writes."""
    if _flight._REC is not None:
        _flight.record("engine", "engine.push")
    if _prof._ACTIVE:
        with _prof.Scope("engine.push", "engine", sync=False):
            get_engine().push(fn, const_vars, mutable_vars)
        return
    get_engine().push(fn, const_vars, mutable_vars)


def wait_for_var(var: int):
    if _prof._ACTIVE:
        with _prof.Scope("engine.wait_for_var", "engine", sync=False):
            get_engine().wait_for_var(var)
        return
    get_engine().wait_for_var(var)


def wait_all():
    """Barrier on host-engine tasks AND device async work (mx.nd.waitall)."""
    if _flight._REC is not None:
        _flight.record("engine", "engine.wait_all")
    if _prof._ACTIVE:
        with _prof.Scope("engine.wait_all", "engine", sync=False):
            get_engine().wait_all()
            _nd.waitall()
        return
    get_engine().wait_all()
    _nd.waitall()


class bulk:
    """Parity: mx.engine.bulk(size) — the reference batches `size` async
    engine ops into one bulk segment to cut scheduling overhead. Here it
    is REAL: inside the scope, eager NDArray dispatches append to a
    deferred segment graph that is flushed as one jit-compiled XLA call —
    when the segment reaches `size` ops, when the scope exits, or when a
    value is read (`asnumpy`/`wait_to_read`/`item`/control flow) or a
    backward walk starts, so imperative semantics are preserved (see
    bulk.py; docs/engine.md). Compiled segments are cached by op/shape
    signature, so steady-state loops reuse one executable per segment
    shape. When profiling is running it additionally records a
    `bulk(size)` trace scope."""

    def __init__(self, size=15):
        self.size = int(size)
        self._scope = None

    def __enter__(self):
        if _prof._ACTIVE:
            self._scope = _prof.Scope("bulk(%d)" % self.size, "engine",
                                      sync=False)
            self._scope.__enter__()
        _bulk_mod.push_scope(self.size)
        return self

    def __exit__(self, *exc):
        _bulk_mod.pop_scope()     # flushes the pending segment
        if self._scope is not None:
            self._scope.__exit__(*exc)
            self._scope = None
        return False


def set_bulk_size(size: int) -> int:
    """Parity: mx.engine.set_bulk_size — opt-in AUTO-bulk: every eager
    dispatch (any thread) defers into segments of up to `size` ops without
    an explicit `bulk` scope; 0 disables (and flushes the calling thread's
    pending segment; other threads flush at their next read/barrier).
    Returns the previous size. Env default: MXTPU_AUTO_BULK=<n>."""
    return _bulk_mod.set_auto_bulk(size)


def bulk_size() -> int:
    """Current auto-bulk segment size (0 = disabled)."""
    return _bulk_mod.auto_bulk_size()
