"""Execution-engine surface (parity: reference src/engine/ threaded
dependency engine; python-side mx.engine hooks).

Device compute is async-scheduled by XLA (its dispatch queue is the
reference's per-device op queue); this module exposes the NATIVE host-side
dependency engine (runtime/) with the reference's push/var semantics for
IO-grade tasks, plus the engine-selection switch:

    MXTPU_ENGINE=native   (default) C++ threaded engine, GIL-free blocking
    MXTPU_ENGINE=python   pure-Python fallback (the reference's NaiveEngine
                          analogue for debugging)
"""
from __future__ import annotations

from . import profiler as _prof
from . import runtime as _rt
from . import ndarray as _nd
from .runtime import engine_type, get_engine

__all__ = ["push", "new_var", "wait_for_var", "wait_all", "engine_type",
           "get_engine", "bulk"]


def new_var() -> int:
    return get_engine().new_var()


def push(fn, const_vars=(), mutable_vars=()):
    """Schedule fn once deps resolve: concurrent reads, exclusive writes."""
    if _prof._ACTIVE:
        with _prof.Scope("engine.push", "engine", sync=False):
            get_engine().push(fn, const_vars, mutable_vars)
        return
    get_engine().push(fn, const_vars, mutable_vars)


def wait_for_var(var: int):
    if _prof._ACTIVE:
        with _prof.Scope("engine.wait_for_var", "engine", sync=False):
            get_engine().wait_for_var(var)
        return
    get_engine().wait_for_var(var)


def wait_all():
    """Barrier on host-engine tasks AND device async work (mx.nd.waitall)."""
    if _prof._ACTIVE:
        with _prof.Scope("engine.wait_all", "engine", sync=False):
            get_engine().wait_all()
            _nd.waitall()
        return
    get_engine().wait_all()
    _nd.waitall()


class bulk:
    """Parity: mx.engine.bulk(size) — the reference batches `size` async
    engine ops into one bulk segment to cut scheduling overhead. Here XLA
    already batches device work per dispatch (and FusedTrainStep.run_k is
    the explicit bulk form), so the context manager is semantically a
    no-op that preserves reference code shape. When profiling is running
    it records a `bulk(size)` trace scope, so reference-shaped code shows
    up in traces; off, it stays a single-predicate no-op."""

    def __init__(self, size=15):
        self.size = int(size)
        self._scope = None

    def __enter__(self):
        if _prof._ACTIVE:
            self._scope = _prof.Scope("bulk(%d)" % self.size, "engine",
                                      sync=False)
            self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        if self._scope is not None:
            self._scope.__exit__(*exc)
            self._scope = None
        return False
