"""mxtpu.servescope — request-lifecycle tracing & tail-latency
attribution for the serving path.

The seventh observability layer (docs/observability.md), and the
serving counterpart of perfscope + devicescope: PRs 7–10 taught the
*training* loop to explain its milliseconds, but the serving stack
(PR 4) still only exposes aggregate histograms — a p99 number with no
story. Servescope measures the request lifecycle end to end and
attributes the tail:

* **per-request lifecycle spans** (:mod:`.spans`) — every sampled
  request gets a ``request_id`` and monotonic marks through
  ``admitted -> queued -> coalesced(batch_id, bucket, pad_slot) ->
  dispatched -> device_done -> unpadded -> responded``, recorded into
  the shared counters registry / flight ring and emitted as
  ``serving.request`` records in ``mxtpu.events/1`` (run_id/batch_id
  correlation with the per-dispatch ``serving.batch`` records);
* **tail-latency attribution** (:mod:`.budget`) — the
  :class:`LatencyBudget` decomposes per-bucket latency into
  ``queue_wait + coalesce_delay + pad_overhead + device_exec +
  respond`` (an exact accounting identity per request), publishes
  p50/p95/p99 per component, joins each bucket's AOT executable to its
  perfscope roofline verdict and commscope resharding verdict, and —
  when a devicescope window covered serving dispatches — upgrades
  ``device_exec`` provenance to ``measured(profile)`` under PR 10's
  stale-window/drift rules. ``tools/mxdiag.py serve`` renders it as
  "p99 is 83% queue_wait at bucket 128 - raise max_batch, not the
  kernel";
* **closed-loop load harness** — ``tools/serve_load.py`` drives K
  concurrent closed-loop clients through :class:`ModelServer` over a
  ramped concurrency sweep, finds the saturation knee where p99
  inflects, and writes the full attribution into trace_check-valid
  BENCH json gated by ``tools/perf_regress.py``.

Cost model: off = one predicate per batcher hook (the
perfscope/commscope/devicescope module-global discipline). Armed, the
per-request cost is bounded by ``MXTPU_SERVESCOPE_SAMPLE``: a value in
(0, 1] is a sampling rate (0.1 = every 10th request), a value >= 1 is
the stride directly; unsampled requests pay one counter increment and a
modulo, keeping steady-state overhead inside healthmon's <5% budget.

``enable()`` arms it (bench.py's serving path and tools/serve_load.py
do, unless ``BENCH_SERVESCOPE=0``); ``MXTPU_SERVESCOPE=1`` arms at
import.
"""
from __future__ import annotations

import os

from .. import profiler as _prof
from . import budget as _budget_mod
from . import spans as _spans_mod
from .budget import (LatencyBudget, quantile_cohorts, DEFAULT_WINDOW,
                     DEVICE_EXEC_SOURCES)
from .spans import RequestSpan, COMPONENTS, components_of

__all__ = ["enable", "disable", "enabled", "enable_from_env",
           "sample_every", "attribution", "attribution_brief",
           "bench_extra", "current_budget", "LatencyBudget",
           "RequestSpan", "COMPONENTS", "components_of",
           "quantile_cohorts", "DEFAULT_WINDOW", "DEVICE_EXEC_SOURCES",
           "spans", "budget"]

# module re-exports under their documented names
spans = _spans_mod
budget = _budget_mod

# module global: None = servescope off (THE fast-path predicate; the
# batcher guards every hook with `if _ss._SS is not None:`)
_SS = None


class _ServeScope:
    """Marker object holding enable-time options (the perfscope /
    commscope / devicescope module-global discipline)."""

    def __init__(self, sample_every: int, window: int | None = None):
        self.sample_every = max(1, int(sample_every))
        self.budget = LatencyBudget(window=window)


def _resolve_sample(sample) -> int:
    """``MXTPU_SERVESCOPE_SAMPLE`` / ``enable(sample=)`` resolution:
    a rate in (0, 1] maps to a stride (0.1 -> 10), >= 1 is the stride
    itself; malformed values fall back to 1 (trace everything) — the
    hot path never raises over an env typo."""
    if sample is None:
        from ..autotune.knobs import env_str
        sample = env_str("MXTPU_SERVESCOPE_SAMPLE", "1")
    try:
        v = float(sample)
    except (TypeError, ValueError):
        return 1
    if v >= 1.0:
        return int(round(v))
    if v > 0.0:
        return max(1, int(round(1.0 / v)))
    return 1


def enable(sample=None, window: int | None = None):
    """Arm request-lifecycle tracing on the serving path. ``sample``:
    rate in (0, 1] or an explicit every-Nth stride (default: the
    ``MXTPU_SERVESCOPE_SAMPLE`` env, else every request). Re-enabling
    starts a fresh :class:`LatencyBudget` (the attribution window is
    per arm, like a devicescope capture)."""
    global _SS
    _SS = _ServeScope(_resolve_sample(sample), window=window)
    _prof.set_gauge("servescope.sample_every", _SS.sample_every,
                    "servescope")
    return _SS


def disable():
    global _SS
    _SS = None


def enabled() -> bool:
    return _SS is not None


def enable_from_env():
    """MXTPU_SERVESCOPE=1 arms servescope at import (like
    MXTPU_PERFSCOPE / MXTPU_DEVICESCOPE)."""
    if os.environ.get("MXTPU_SERVESCOPE", "") == "1":
        enable()


def sample_every() -> int:
    """The armed stride (1 when off — callers use the predicate)."""
    ss = _SS
    return ss.sample_every if ss is not None else 1


def current_budget():
    ss = _SS
    return ss.budget if ss is not None else None


def attribution() -> dict | None:
    """The settled tail-latency attribution (None when off)."""
    ss = _SS
    return ss.budget.attribution() if ss is not None else None


def attribution_brief() -> dict | None:
    """The /healthz-sized p99 summary (None when off or no traffic)."""
    ss = _SS
    return ss.budget.brief() if ss is not None else None


def bench_extra() -> dict | None:
    """The ``extra.servescope`` payload for BENCH json: the full
    attribution plus the sampling header. None when servescope is off
    (the section is simply absent, like an unarmed commscope)."""
    ss = _SS
    if ss is None:
        return None
    doc = ss.budget.attribution()
    doc["sample_every"] = ss.sample_every
    return doc
