"""Per-request lifecycle spans for the serving path.

Every sampled request gets a :class:`RequestSpan` — a handful of
monotonic (``time.perf_counter``) marks stamped by the batcher as the
request moves through

    admitted -> queued -> coalesced(batch_id, bucket, pad_slot)
             -> dispatched -> device_done -> unpadded -> responded

plus the per-phase timings :meth:`FrozenModel.predict_batch` fills in
(pad / exec / unpad). The span is pure data; :func:`components_of`
turns it into the five-way latency attribution

    e2e = queue_wait + coalesce_delay + pad_overhead + device_exec
          + respond

which sums to the request's measured end-to-end latency **exactly** (an
accounting identity over the marks, not an estimate — pinned by a
hand-computed test):

* **queue_wait** — admitted until the dispatcher began assembling the
  batch that took this request (the dispatcher was busy with an earlier
  batch, or asleep). A p99 dominated by queue_wait means the dispatch
  pipeline is saturated: raise ``max_batch`` / add replicas, don't
  touch the kernel.
* **coalesce_delay** — time inside the coalescing window (the
  dispatcher deliberately holding the batch open for more requests,
  bounded by ``max_delay_ms``) plus the host-side batch assembly.
* **pad_overhead** — the price of bucketed AOT executables: the host
  pad copy plus the share of device time spent computing junk rows,
  ``exec * (bucket - real) / bucket`` (equivalently
  ``padded_slots / real_slots x device_exec``).
* **device_exec** — the real-work share of the executable's wall,
  ``exec * real / bucket``.
* **respond** — unpad slicing, per-request result assembly, and the
  fulfil fan-out back to the waiting client.

Sampling is deterministic and cheap: request sequence numbers modulo
``sample_every`` (resolved from ``MXTPU_SERVESCOPE_SAMPLE``), so
steady-state overhead stays inside healthmon's <5% budget — an
unsampled request pays one counter increment and one modulo.

Completed spans land on three surfaces at once (the healthmon alert
discipline): the ``servescope.*`` counter family, a flight-recorder
breadcrumb, and a ``serving.request`` record in ``mxtpu.events/1``
carrying the run_id/batch_id correlation ids.
"""
from __future__ import annotations

import threading

from .. import profiler as _prof
from ..diagnostics import flight as _flight
from ..healthmon import events as _events

__all__ = ["RequestSpan", "COMPONENTS", "components_of", "begin",
           "mark_gather", "mark_slotted", "mark_batch", "finish",
           "reject"]

# the closed component taxonomy (docs/servescope.md); trace_check
# validates every published attribution against exactly this set
COMPONENTS = ("queue_wait_ms", "coalesce_delay_ms", "pad_overhead_ms",
              "device_exec_ms", "respond_ms")

# request sequence counter (sampling + request_id); one lock, touched
# once per submit only while servescope is armed
_seq_lock = threading.Lock()
_seq = [0]


class RequestSpan:
    """One sampled request's lifecycle marks. All timestamps are
    ``time.perf_counter`` seconds; ``timings`` is the pad/exec/unpad
    millisecond split :meth:`FrozenModel.predict_batch` measured."""

    __slots__ = ("request_id", "t_admit", "gather_start", "t_dispatched",
                 "t_device_done", "t_respond", "bucket", "real",
                 "batch_id", "batch_index", "timings", "status",
                 "slotted", "trace_id", "span_id", "parent_id")

    def __init__(self, request_id: int, t_admit: float):
        self.request_id = request_id
        self.t_admit = float(t_admit)
        self.gather_start = None
        self.t_dispatched = None
        self.t_device_done = None
        self.t_respond = None
        self.bucket = None
        self.real = None
        self.batch_id = None
        self.batch_index = None
        self.timings = None
        self.status = "admitted"
        self.slotted = False
        # fleetscope cross-process trace context (None unless a
        # traceparent reached the server while fleetscope was armed):
        # trace_id joins this span to the router's fleetscope.request
        # record, parent_id is the upstream hop's span
        self.trace_id = None
        self.span_id = None
        self.parent_id = None


def components_of(span: RequestSpan) -> dict:
    """The five-way attribution for one responded span (milliseconds).

    Exact accounting identity: the components sum to
    ``(t_respond - t_admit) * 1e3`` by construction. The pad/exec/unpad
    split inside the predict wall comes from the model's measured
    timings; the (tiny) call-overhead residual the three don't cover is
    folded into ``respond`` so the identity survives."""
    admit = span.t_admit
    gstart = span.gather_start if span.gather_start is not None else admit
    t_disp = span.t_dispatched
    t_done = span.t_device_done
    t_resp = span.t_respond
    e2e = (t_resp - admit) * 1e3
    queue_wait = max(0.0, (gstart - admit) * 1e3)
    coalesce = max(0.0, (t_disp - max(admit, gstart)) * 1e3)
    predict_wall = max(0.0, (t_done - t_disp) * 1e3)
    t = span.timings or {}
    exec_ms = float(t.get("exec_ms", predict_wall))
    pad_ms = float(t.get("pad_ms", 0.0))
    unpad_ms = float(t.get("unpad_ms", 0.0))
    # predict_wall >= pad + exec + unpad (the wall contains the calls);
    # clamp a torn timings dict rather than going negative
    residual = max(0.0, predict_wall - pad_ms - exec_ms - unpad_ms)
    bucket = max(1, int(span.bucket or 1))
    real = min(bucket, max(1, int(span.real or bucket)))
    device_exec = exec_ms * real / bucket
    pad_overhead = pad_ms + exec_ms * (bucket - real) / bucket
    respond = max(0.0, (t_resp - t_done) * 1e3) + unpad_ms + residual
    return {
        "e2e_ms": e2e,
        "queue_wait_ms": queue_wait,
        "coalesce_delay_ms": coalesce,
        "pad_overhead_ms": pad_overhead,
        "device_exec_ms": device_exec,
        "respond_ms": respond,
    }


# ---------------------------------------------------------------------------
# batcher-facing lifecycle hooks (callers guard with `_ss._SS is not None`)
# ---------------------------------------------------------------------------

def begin(t_admit: float, sample_every: int):
    """Sampling decision at submit: every ``sample_every``-th request
    gets a span (deterministic, no RNG on the hot path); the rest cost
    one counter increment. Returns the span or None."""
    with _seq_lock:
        _seq[0] += 1
        rid = _seq[0]
    if sample_every > 1 and rid % sample_every:
        _prof.counter("servescope.sampled_out", "servescope").increment()
        return None
    return RequestSpan(rid, t_admit)


def mark_gather(span, gather_start: float):
    span.gather_start = float(gather_start)
    span.status = "coalesced"


def mark_slotted(span):
    """Continuous-batching admission mark: this request was admitted
    while a dispatch was already in flight and landed in the NEXT
    iteration's slots (it never sat through a coalescing hold). The
    mark rides the span into the flight/events emission so mid-flight
    admission is provable per request, not just in aggregate."""
    span.slotted = True


def mark_batch(span, batch_id: int, bucket: int, real: int,
               t_dispatched: float, t_device_done: float,
               timings: dict | None):
    span.batch_id = int(batch_id)
    span.bucket = int(bucket)
    span.real = int(real)
    span.t_dispatched = float(t_dispatched)
    span.t_device_done = float(t_device_done)
    span.timings = timings
    span.status = "device_done"


def finish(span, t_respond: float, batch_index=None) -> dict:
    """Settle a responded span: compute the attribution, feed the
    budget/counters, and emit the correlation record. Returns the
    component dict (the batcher hands it to nothing else)."""
    span.t_respond = float(t_respond)
    span.batch_index = batch_index
    span.status = "responded"
    comp = components_of(span)
    _prof.counter("servescope.requests_traced", "servescope").increment()
    for key in COMPONENTS:
        _prof.observe("servescope." + key, comp[key], "servescope")
    _prof.observe("servescope.e2e_ms", comp["e2e_ms"], "servescope")
    _emit(span, comp)
    return comp


def reject(span, reason: str, t_now: float):
    """Settle a rejected span (deadline pre/post batch, drain, batch
    error): counted + emitted with the phase it reached, never fed to
    the latency budget (a rejection has no response latency)."""
    span.t_respond = float(t_now)
    span.status = reason
    _prof.counter("servescope.rejections_traced", "servescope").increment()
    _emit(span, None)


def _emit(span, comp):
    """The correlation record: flight breadcrumb + mxtpu.events/1
    ``serving.request`` (run_id comes from the event log itself;
    batch_id joins against the per-dispatch ``serving.batch`` record)."""
    args = {"request_id": span.request_id, "status": span.status,
            "bucket": span.bucket, "batch_id": span.batch_id}
    if span.slotted:
        args["slotted"] = True
    if span.trace_id is not None:
        # the cross-process join key: mxdiag.py trace / serve_load's
        # extra.fleetscope match this against the router's record
        args["trace_id"] = span.trace_id
        args["span_id"] = span.span_id
        if span.parent_id is not None:
            args["parent_id"] = span.parent_id
    if comp is not None:
        args["e2e_ms"] = round(comp["e2e_ms"], 3)
        for key in COMPONENTS:
            args[key] = round(comp[key], 3)
    elif span.t_respond is not None:
        args["age_ms"] = round((span.t_respond - span.t_admit) * 1e3, 3)
    if _flight._REC is not None:
        _flight.record("serving", "serving.request", args)
    if _events._LOG is not None:
        _events.emit("serving", "serving.request", args=args)
