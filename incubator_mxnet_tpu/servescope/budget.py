"""LatencyBudget — tail-latency attribution for the serving path.

The serving analogue of perfscope's :class:`StepBudget`: where the step
budget decomposes one steady *training* step, the latency budget
decomposes the request latency *distribution* — per compiled bucket —
into the five lifecycle components :mod:`.spans` measures, and answers
the question the aggregate p99 histogram cannot: *which part of the
pipeline IS the tail?*

Attribution is computed from a bounded reservoir of recent spans (the
last ``MXTPU_SERVESCOPE_WINDOW`` responded requests, default 4096, per
bucket and overall) rather than from histogram interpolation, so the
published numbers keep the spans' exact sum identity:

* **component distributions** — independent p50/p95/p99 of each
  component (the dashboard view; these do NOT sum to the e2e
  percentiles and are not meant to);
* **quantile-cohort attribution** — for each of p50/p95/p99, the mean
  component split over the requests whose e2e latency sits AT that
  quantile (the nearest-rank cohort). Cohort means sum exactly to the
  cohort's mean e2e, which by construction sits at the quantile — so
  "p99 is 83% queue_wait" is an accounting fact about the actual tail
  requests, not a model.

Each bucket's row joins the verdicts the other scopes already hold for
its AOT executable (both captures ride the serving compile for free):
perfscope's roofline verdict and commscope's resharding verdict — the
"accidental all-gather on the serve path" ROADMAP names as the p99
catastrophe. When a devicescope capture window completed over serving
dispatches AFTER this budget began (the PR 10 stale-window rule), the
``device_exec`` component's provenance upgrades to
``measured(profile)`` with the measured-vs-host-wall drift beside it;
otherwise it stays ``host_wall`` (the executable call is synchronous at
the host once outputs convert, so the wall is measured, not estimated —
but it includes transfer, which only a device timeline can separate).
"""
from __future__ import annotations

import collections
import threading
import time
import warnings

from .. import profiler as _prof
from .spans import COMPONENTS

__all__ = ["LatencyBudget", "quantile_cohorts", "DEFAULT_WINDOW",
           "DEVICE_EXEC_SOURCES"]

DEFAULT_WINDOW = 4096

# provenance taxonomy for the device_exec component (mirrors the step
# budget's collective_source discipline)
DEVICE_EXEC_SOURCES = ("host_wall", "measured(profile)")

# attribution quantiles and the cohort width (fraction of n) around each
_QUANTILES = (0.50, 0.95, 0.99)


def _env_window() -> int:
    from ..autotune.knobs import env_int
    return max(64, env_int("MXTPU_SERVESCOPE_WINDOW", DEFAULT_WINDOW,
                           on_error="default"))


def _nearest_rank(n: int, q: float) -> int:
    """0-based nearest-rank index of quantile q in a sorted length-n
    sequence."""
    import math
    return min(n - 1, max(0, math.ceil(q * n) - 1))


def quantile_cohorts(entries, neighborhood: float = 0.10) -> dict:
    """Per-quantile cohort attribution over a list of component dicts.

    ``entries``: dicts with ``e2e_ms`` + the five COMPONENTS. For each
    quantile the cohort is the requests sitting AT the quantile: up to
    ``max(1, n//100)`` entries starting at the nearest-rank index,
    value-capped at ``(1 + neighborhood)`` x the quantile itself — so a
    lone 10x outlier above p99, or a bimodal jump right at the
    quantile, can never smear the attribution (the cohort degrades to
    the single quantile request, whose components sum to its e2e
    exactly). Returns::

        {"p99": {"e2e_ms": <nearest-rank e2e>, "cohort": k,
                 "components": {name: mean ms}, "sum_ms": <mean e2e>,
                 "top_component": name, "top_share": 0..1}, ...}

    ``sum_ms`` equals the cohort's mean e2e exactly (the spans' sum
    identity survives the mean), and the value cap bounds
    |sum_ms - e2e_ms| / e2e_ms by ``neighborhood`` BY CONSTRUCTION —
    the acceptance criterion's 15% is structural, not statistical."""
    n = len(entries)
    if n == 0:
        return {}
    by_e2e = sorted(entries, key=lambda c: c["e2e_ms"])
    width = max(1, n // 100)
    out = {}
    for q in _QUANTILES:
        i = _nearest_rank(n, q)
        cap = by_e2e[i]["e2e_ms"] * (1.0 + neighborhood)
        cohort = [by_e2e[i]]
        for c in by_e2e[i + 1:i + width]:
            if c["e2e_ms"] > cap:
                break
            cohort.append(c)
        k = len(cohort)
        comps = {key: sum(c[key] for c in cohort) / k for key in COMPONENTS}
        total = sum(comps.values())
        top = max(comps, key=comps.get)
        out[f"p{int(q * 100)}"] = {
            "e2e_ms": round(by_e2e[i]["e2e_ms"], 4),
            "cohort": k,
            "components": {key: round(v, 4) for key, v in comps.items()},
            "sum_ms": round(total, 4),
            "top_component": top,
            "top_share": round(comps[top] / total, 4) if total > 0 else None,
        }
    return out


def _dist(values) -> dict:
    """p50/p95/p99/mean/max of a value list (nearest-rank, no
    interpolation — these are real observations)."""
    if not values:
        return {"p50": None, "p95": None, "p99": None, "mean": None,
                "max": None}
    vs = sorted(values)
    n = len(vs)
    return {"p50": round(vs[_nearest_rank(n, 0.50)], 4),
            "p95": round(vs[_nearest_rank(n, 0.95)], 4),
            "p99": round(vs[_nearest_rank(n, 0.99)], 4),
            "mean": round(sum(vs) / n, 4),
            "max": round(vs[-1], 4)}


_ADVICE = {
    "queue_wait_ms": "the dispatch pipeline is saturated - raise "
                     "max_batch or add replicas, not the kernel",
    "coalesce_delay_ms": "the batch window is the tail - lower "
                         "max_delay_ms",
    "pad_overhead_ms": "bucket padding dominates - add a bucket nearer "
                       "the typical batch size",
    "device_exec_ms": "the executable itself is the tail - see the "
                      "bucket's roofline verdict",
    "respond_ms": "the host-side response path (unpad/serialize/fulfil) "
                  "is the tail",
}


class LatencyBudget:
    """Accumulates responded spans' components and settles the
    attribution. One instance per servescope arm; the batcher's
    dispatcher thread is the only writer on the hot path, but the lock
    keeps multi-server processes honest (it is per observation, off the
    device-exec critical path)."""

    def __init__(self, window: int | None = None):
        self._window = window or _env_window()
        self._lock = threading.Lock()
        self._overall = collections.deque(maxlen=self._window)
        self._per_bucket = {}
        self._real_slots = {}
        self._count = 0
        # stale-window reference for the devicescope upgrade (PR 10's
        # rule: a window completed BEFORE this budget began measured
        # someone else's traffic)
        self._began_monotonic = time.monotonic()
        self._drift_warned = False

    def observe(self, span, comp: dict):
        """One responded span's settled components (from spans.finish)."""
        entry = {k: comp[k] for k in COMPONENTS}
        entry["e2e_ms"] = comp["e2e_ms"]
        b = int(span.bucket or 0)
        with self._lock:
            self._count += 1
            self._overall.append(entry)
            dq = self._per_bucket.get(b)
            if dq is None:
                dq = self._per_bucket[b] = collections.deque(
                    maxlen=self._window)
                self._real_slots[b] = [0, 0]     # [real, slots]
            dq.append(entry)
            rs = self._real_slots[b]
            rs[0] += int(span.real or 0)
            rs[1] += b

    # -- verdict joins -----------------------------------------------------
    @staticmethod
    def _bucket_verdicts() -> dict:
        """bucket -> {roofline verdict, resharding verdict} joined from
        the perfscope/commscope program tables by the serving compile
        site's program name (kind == "serving_bucket"). Never raises;
        an unjoined bucket reports None, never a guess."""
        out = {}
        try:
            from .. import perfscope as _ps
            for p in _ps.programs():
                if p.get("kind") == "serving_bucket" \
                        and p.get("bucket") is not None:
                    out.setdefault(int(p["bucket"]), {})["verdict"] = \
                        p.get("verdict")
        except Exception:  # noqa: BLE001
            pass
        try:
            from .. import commscope as _cs
            for p in _cs.programs():
                if p.get("kind") != "serving_bucket":
                    continue
                # commscope records carry the program name, not the
                # bucket extra — the bucket is the ":b<k>" suffix of
                # the serving compile site's name (frozen.program_name)
                b = p.get("bucket")
                if b is None:
                    name = str(p.get("name") or "")
                    if ":b" in name:
                        tail = name.rsplit(":b", 1)[1]
                        if tail.isdigit():
                            b = int(tail)
                if b is None:
                    continue
                slot = out.setdefault(int(b), {})
                slot["resharding_collectives"] = \
                    p.get("resharding_collectives")
                slot["hlo_available"] = p.get("hlo_available")
                slot["collective_count"] = \
                    (p.get("totals") or {}).get("count")
        except Exception:  # noqa: BLE001
            pass
        return out

    def _device_window(self):
        """(source, window-info) for the device_exec provenance. The
        upgrade requires devicescope armed, a completed window newer
        than this budget, and a measured per-step busy time; the
        measured-vs-host-wall drift rides along, warning once past
        devicescope's shared threshold."""
        try:
            from .. import devicescope as _ds
            if _ds._DS is None:
                return "host_wall", None
            w = _ds.last_window()
            if w is None or w.completed_at is None \
                    or w.completed_at < self._began_monotonic:
                return "host_wall", None
            # workload identity, not just freshness: a fresh window
            # stepped by the TRAIN loop (train and serve share a
            # process) measured someone else's dispatches — upgrading
            # from it would compare train-step busy time against the
            # serving exec wall and warn about phantom drift
            if getattr(w, "workload", None) != "serving":
                return "host_wall", None
            s = w.summary()
            per = (s or {}).get("per_step") or {}
            busy = per.get("device_busy_ms")
            if not isinstance(busy, (int, float)) or busy <= 0:
                return "host_wall", None
            host = (w.dispatch_ms / w.steps_done) if w.steps_done else None
            drift = (abs(busy - host) / host
                     if host and host > 1e-9 else None)
            info = {"path": w.logdir,
                    "dispatches": w.steps_done,
                    "measured_busy_ms_per_dispatch": round(busy, 4),
                    "host_wall_ms_per_dispatch":
                        round(host, 4) if host is not None else None,
                    "drift": round(drift, 4) if drift is not None else None,
                    "drift_warning": bool(
                        drift is not None
                        and drift > _ds.DRIFT_THRESHOLD)}
            if info["drift_warning"] and not self._drift_warned:
                self._drift_warned = True
                _prof.counter("servescope.device_drift_warnings",
                              "servescope").increment()
                warnings.warn(
                    f"servescope: measured device busy per dispatch "
                    f"({busy:.3f} ms) and the host exec wall "
                    f"({host:.3f} ms) disagree by more than "
                    f"{_ds.DRIFT_THRESHOLD:.0%} — the host wall is "
                    f"paying transfer/dispatch the device never saw; "
                    f"trust the measured window (docs/servescope.md)",
                    stacklevel=3)
            return "measured(profile)", info
        except Exception:  # noqa: BLE001 — measurement must never break
            return "host_wall", None

    # -- settlement --------------------------------------------------------
    def _group(self, entries, extra=None) -> dict:
        out = {"count": len(entries),
               "e2e_ms": _dist([c["e2e_ms"] for c in entries]),
               "component_dist": {k: _dist([c[k] for c in entries])
                                  for k in COMPONENTS},
               "attribution": quantile_cohorts(entries)}
        if extra:
            out.update(extra)
        return out

    def attribution(self) -> dict:
        """The settled attribution: overall + per-bucket groups, bucket
        verdicts, device_exec provenance, and the one-line advice the
        p99 cohort supports."""
        with self._lock:
            overall = list(self._overall)
            per_bucket = {b: list(dq) for b, dq in self._per_bucket.items()}
            fills = {b: (rs[0] / rs[1] if rs[1] else None)
                     for b, rs in self._real_slots.items()}
            total = self._count
        verdicts = self._bucket_verdicts()
        source, window = self._device_window()
        doc = {
            "requests": total,
            "window": self._window,
            "components": list(COMPONENTS),
            "device_exec_source": source,
            "device_window": window,
            "overall": self._group(overall),
            "per_bucket": {},
        }
        for b in sorted(per_bucket):
            v = verdicts.get(b, {})
            doc["per_bucket"][str(b)] = self._group(per_bucket[b], extra={
                "bucket": b,
                "fill": round(fills[b], 4) if fills.get(b) else None,
                "verdict": v.get("verdict"),
                "resharding_collectives": v.get("resharding_collectives"),
                "hlo_available": v.get("hlo_available"),
            })
        doc["advice"] = self._advice(doc)
        return doc

    @staticmethod
    def _advice(doc) -> str | None:
        """The mxdiag one-liner: which bucket's p99 cohort is worst,
        which component owns it, what to do about it."""
        worst = None
        for key, grp in doc["per_bucket"].items():
            att = (grp.get("attribution") or {}).get("p99")
            if not att or att.get("top_share") is None:
                continue
            if worst is None or att["e2e_ms"] > worst[1]["e2e_ms"]:
                worst = (grp.get("bucket", key), att)
        if worst is None:
            att = (doc["overall"].get("attribution") or {}).get("p99")
            if not att or att.get("top_share") is None:
                return None
            worst = (None, att)
        bucket, att = worst
        top = att["top_component"]
        where = f" at bucket {bucket}" if bucket is not None else ""
        return (f"p99 is {att['top_share']:.0%} "
                f"{top.replace('_ms', '')}{where} - "
                f"{_ADVICE.get(top, top)}")

    def brief(self) -> dict | None:
        """The /healthz-sized summary: overall p99 cohort only."""
        with self._lock:
            overall = list(self._overall)
        if not overall:
            return None
        att = quantile_cohorts(overall).get("p99")
        if not att:
            return None
        return {"e2e_p99_ms": att["e2e_ms"],
                "top_component": att["top_component"],
                "top_share": att["top_share"],
                "requests_traced": len(overall)}
