"""Custom operator registration (parity: python/mxnet/operator.py —
CustomOp / CustomOpProp / mx.operator.register, usable from both
`mx.nd.Custom` and `mx.sym.Custom`).

Reference semantics: a custom op is arbitrary Python running on the
engine's CPU worker threads, with explicit `forward`/`backward` writing
results through `assign` per the `req` mode. TPU-native realisation:

* eager (`nd.Custom`): the user op runs directly on concrete NDArrays and
  is recorded on the autograd tape as a custom-vjp node (the user's
  `backward` supplies input cotangents);
* compiled (`sym.Custom` inside a jitted Executor): the op body becomes a
  `jax.pure_callback` — XLA calls back onto the host exactly where the
  reference dispatches to its Python worker — wrapped in `jax.custom_vjp`
  so the user's `backward` runs (also as a callback) during grad. Shapes
  and dtypes come from the prop's `infer_shape`/`infer_type`, so the
  surrounding XLA computation stays statically shaped.

The op body itself is host Python (that is the contract of the reference
API — use pallas / jax ops for device-speed custom kernels instead); the
framework guarantees correctness, not MXU throughput, for this surface.

Auxiliary states (list_auxiliary_states) are supported on both surfaces:
eager aux NDArrays mutate in place; symbolic aux flows through the
executor's aux write-back protocol, with backward seeing the post-forward
values and aux receiving zero gradients.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["CustomOp", "CustomOpProp", "register", "get"]


class CustomOp:
    """Base class for user ops (parity: mx.operator.CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write `src` into `dst` honoring the req mode."""
        from .ndarray import NDArray
        if req in (None, "null"):
            return
        src_data = src._data if isinstance(src, NDArray) else jnp.asarray(src)
        if req == "add":
            dst._data = dst._data + src_data.astype(dst._data.dtype)
        else:  # 'write' / 'inplace'
            dst._data = src_data.astype(dst._data.dtype)


class CustomOpProp:
    """Base class for op metadata (parity: mx.operator.CustomOpProp).

    Subclasses override list_arguments/list_outputs/infer_shape/
    create_operator; kwargs passed to register()'d symbols arrive as
    strings in __init__, as in the reference."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = bool(need_top_grad)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError

    def need_top_grad(self):
        return self.need_top_grad_


_REGISTRY: dict[str, type] = {}


def register(reg_name):
    """@mx.operator.register("my_op") above a CustomOpProp subclass."""
    def wrap(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register() expects a CustomOpProp subclass")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls
    return wrap


def get(reg_name) -> type:
    if reg_name not in _REGISTRY:
        raise KeyError(f"no custom op registered as {reg_name!r}; "
                       f"available: {sorted(_REGISTRY)}")
    return _REGISTRY[reg_name]


# ---------------------------------------------------------------------------
# shared execution helpers
# ---------------------------------------------------------------------------

_PROP_CACHE: dict = {}


def _make_prop(op_type, attrs):
    """Build (or reuse) the user's CustomOpProp. Cached per
    (prop CLASS, kwargs): graph building consults the prop several times
    per node (n_out, aux positions, shape hints, execution) and a prop
    with a heavy __init__ shouldn't pay per consultation. Keying on the
    class (not the name) means re-registering an op_type takes effect
    immediately. The cached prop is treated as stateless METADATA —
    per-execution state belongs in the CustomOp that create_operator
    returns fresh each run, as in the reference. Falls back to a fresh
    instance when kwargs are unhashable."""
    kwargs = {k: v for k, v in attrs.items() if k != "op_type"}
    cls = get(op_type)
    try:
        key = (cls, tuple(sorted(kwargs.items())))
        prop = _PROP_CACHE.get(key)
        if prop is None:
            prop = cls(**kwargs)
            _PROP_CACHE[key] = prop
        return prop
    except TypeError:               # unhashable kwarg value
        return cls(**kwargs)


def _infer(prop, in_shapes, in_dtypes):
    """in_shapes/in_dtypes are for the DATA arguments only. Returns
    (out_shapes, out_dtypes)."""
    shp = prop.infer_shape([list(s) for s in in_shapes])
    out_s = shp[1]
    _, out_t, _ = prop.infer_type(list(in_dtypes))
    return ([tuple(s) for s in out_s], out_t)


def _n_args(prop):
    return len(prop.list_arguments())


def _n_aux(prop):
    return len(prop.list_auxiliary_states())


def _host_forward(prop, attrs, is_train, raw_inputs, raw_aux, out_shapes,
                  out_dtypes):
    """Run the user's forward on host arrays; returns (outs, new_aux) as
    tuples of np arrays — aux NDArrays the user mutated in place come back
    as updated values (the reference's in-place aux contract)."""
    from .ndarray import NDArray
    op = prop.create_operator(None, [a.shape for a in raw_inputs],
                              [a.dtype for a in raw_inputs])
    in_data = [NDArray(jnp.asarray(a)) for a in raw_inputs]
    aux = [NDArray(jnp.asarray(a)) for a in raw_aux]
    out_data = [NDArray(jnp.zeros(s, d))
                for s, d in zip(out_shapes, out_dtypes)]
    op.forward(is_train, ["write"] * len(out_data), in_data, out_data, aux)
    return (tuple(np.asarray(o._data) for o in out_data)
            + tuple(np.asarray(a._data) for a in aux))


def _host_backward(prop, attrs, raw_out_grads, raw_inputs, raw_outputs,
                   raw_aux):
    from .ndarray import NDArray
    op = prop.create_operator(None, [a.shape for a in raw_inputs],
                              [a.dtype for a in raw_inputs])
    in_data = [NDArray(jnp.asarray(a)) for a in raw_inputs]
    out_data = [NDArray(jnp.asarray(a)) for a in raw_outputs]
    out_grad = [NDArray(jnp.asarray(g)) for g in raw_out_grads]
    aux = [NDArray(jnp.asarray(a)) for a in raw_aux]
    in_grad = [NDArray(jnp.zeros(a.shape, a.dtype)) for a in raw_inputs]
    # aux mutations during backward are dropped (forward-only updates,
    # like BatchNorm moving stats; the reference applies them but no
    # training loop observes the difference before the next forward)
    op.backward(["write"] * len(in_grad), out_grad, in_data, out_data,
                in_grad, aux)
    return tuple(np.asarray(g._data) for g in in_grad)


def custom_sym_fn(rt, a, *raws):
    """The traced (rt, attrs, *raws) op fn for the symbol executor:
    pure_callback forward + custom_vjp backward. Trailing inputs beyond
    the prop's arguments are auxiliary states; their updated values are
    returned after the real outputs (the executor's aux write-back
    protocol) and they receive zero gradients."""
    prop = _make_prop(a["op_type"], a)
    n_in = _n_args(prop)
    data_raws, aux_raws = raws[:n_in], raws[n_in:]
    in_shapes = [r.shape for r in data_raws]
    in_dtypes = [r.dtype for r in data_raws]
    out_shapes, out_dtypes = _infer(prop, in_shapes, in_dtypes)
    result_avals = (
        tuple(jax.ShapeDtypeStruct(s, jnp.dtype(d))
              for s, d in zip(out_shapes, out_dtypes))
        + tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in aux_raws))
    is_train = bool(rt.is_train)
    n_out = len(out_shapes)
    n_aux = len(aux_raws)

    @jax.custom_vjp
    def run(*xs):
        return jax.pure_callback(
            lambda *hs: _host_forward(prop, a, is_train, hs[:n_in],
                                      hs[n_in:], out_shapes, out_dtypes),
            result_avals, *xs)

    def run_fwd(*xs):
        ys = run(*xs)
        return ys, (xs, ys)

    def run_bwd(res, gs):
        xs, ys = res
        data_xs, aux_xs = xs[:n_in], xs[n_in:]
        outs_only = ys[:n_out]
        # backward sees the POST-forward aux (ys tail), matching the
        # reference's in-place-updated aux and the eager path
        aux_after = ys[n_out:]
        in_avals = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype)
                         for x in data_xs)
        # flat layout: [out_grads (n_out), inputs (n_in), outputs (n_out),
        # aux (n_aux)]
        data_cots = jax.pure_callback(
            lambda *flat: _host_backward(
                prop, a, flat[:n_out],
                flat[n_out:n_out + n_in],
                flat[n_out + n_in:2 * n_out + n_in],
                flat[2 * n_out + n_in:]),
            in_avals, *gs[:n_out], *data_xs, *outs_only, *aux_after)
        aux_cots = tuple(jnp.zeros(x.shape, x.dtype) for x in aux_xs)
        return tuple(data_cots) + aux_cots

    run.defvjp(run_fwd, run_bwd)
    out = run(*raws)
    if n_aux == 0:
        return out if len(out) > 1 else out[0]
    return out        # (outs..., new_aux...): executor strips the aux tail


def custom_n_out(attrs):
    return len(_make_prop(attrs["op_type"], attrs).list_outputs())


def custom_aux_pos(attrs):
    """Aux inputs sit after the prop's declared arguments (dynamic — the
    registry's aux_pos callable form)."""
    prop = _make_prop(attrs["op_type"], attrs)
    return tuple(range(_n_args(prop), _n_args(prop) + _n_aux(prop)))


def custom_infer_hint(in_shapes, attrs):
    """Fill unknown argument/aux shapes from the prop's infer_shape, so
    simple_bind can allocate aux states (the reference's shape-inference
    pass does the same through CustomOpProp)."""
    prop = _make_prop(attrs["op_type"], attrs)
    na = _n_args(prop)
    data_shapes = in_shapes[:na]
    if any(s is None for s in data_shapes):
        return None
    shp = prop.infer_shape([list(s) for s in data_shapes])
    aux_s = shp[2] if len(shp) > 2 else []
    fills = {}
    for j, s in enumerate(aux_s):
        pos = na + j
        if pos < len(in_shapes) and in_shapes[pos] is None:
            fills[pos] = tuple(s)
    return fills


def eager_custom(inputs, attrs):
    """nd.Custom: run the user op on concrete arrays, record the user's
    backward on the autograd tape. Inputs beyond the prop's arguments are
    auxiliary states — mutated IN PLACE on the caller's NDArrays (the
    reference's aux contract) and excluded from gradients."""
    from . import autograd
    from .ndarray import NDArray

    op_type = attrs["op_type"]
    prop = _make_prop(op_type, attrs)
    n_in = _n_args(prop)
    data_in, aux_in = list(inputs[:n_in]), list(inputs[n_in:])
    in_shapes = [tuple(x.shape) for x in data_in]
    in_dtypes = [x._data.dtype for x in data_in]
    out_shapes, out_dtypes = _infer(prop, in_shapes, in_dtypes)
    op = prop.create_operator(None, in_shapes, in_dtypes)

    class _Fn(autograd.Function):
        def forward(self, *ins):
            self.save_for_backward(*ins)
            outs = [NDArray(jnp.zeros(s, d))
                    for s, d in zip(out_shapes, out_dtypes)]
            op.forward(autograd.is_training(), ["write"] * len(outs),
                       list(ins), outs, aux_in)
            self._outs = outs
            return outs if len(outs) > 1 else outs[0]

        def backward(self, *ogs):
            ins = list(self._saved)
            in_grads = [NDArray(jnp.zeros(x.shape, d))
                        for x, d in zip(ins, in_dtypes)]
            op.backward(["write"] * len(in_grads), list(ogs), ins,
                        self._outs, in_grads, aux_in)
            return in_grads if len(in_grads) > 1 else in_grads[0]

    return _Fn()(*data_in)
