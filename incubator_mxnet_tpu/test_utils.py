"""Test utilities (parity: python/mxnet/test_utils.py — the helpers the
reference's own test suite is written against)."""
from __future__ import annotations

import numpy as np

from . import context as ctx_mod
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = ["assert_almost_equal", "almost_equal", "same", "default_context",
           "set_default_context", "rand_ndarray", "rand_shape_nd",
           "default_dtype", "numeric_grad", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward"]


def _to_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b):
    return np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return np.allclose(_to_np(a), _to_np(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _to_np(a), _to_np(b)
    if not np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        err = np.abs(a_np - b_np)
        rel = err / (np.abs(b_np) + 1e-12)
        raise AssertionError(
            f"{names[0]} != {names[1]} (rtol={rtol}, atol={atol}): "
            f"max abs err {err.max():.3e}, max rel err {rel.max():.3e}")


def default_context() -> Context:
    return current_context()


def set_default_context(ctx: Context):
    ctx_mod._default_ctx = ctx


def default_dtype():
    return np.float32


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, dtype="float32", ctx=None):
    return array(np.random.uniform(-1.0, 1.0, shape).astype(dtype), ctx=ctx)


def numeric_grad(f, x, eps=1e-3):
    """Central-difference gradient of scalar-valued f at NDArray x."""
    x0 = x.asnumpy().astype(np.float64)
    g = np.zeros_like(x0)
    it = np.nditer(x0, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x0[idx]
        x0[idx] = orig + eps
        fp = float(f(NDArray(x0.astype(np.float32))).asnumpy().sum())
        x0[idx] = orig - eps
        fm = float(f(NDArray(x0.astype(np.float32))).asnumpy().sum())
        x0[idx] = orig
        g[idx] = (fp - fm) / (2 * eps)
        it.iternext()
    return g.astype(np.float32)


def check_numeric_gradient(f, inputs, rtol=1e-2, atol=1e-3, eps=1e-3):
    """Parity: mx.test_utils.check_numeric_gradient — compare the tape's
    gradients of sum(f(*inputs)) against central differences, input by
    input. `inputs` are NDArrays; each gets attach_grad()."""
    import numpy as np
    from . import autograd
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = f(*inputs)
        loss = out.sum()
    loss.backward()
    for i, x in enumerate(inputs):
        def fi(xi, i=i):
            args = list(inputs)
            args[i] = xi
            return f(*args)
        expected = numeric_grad(fi, x, eps)
        assert_almost_equal(x.grad.asnumpy(), expected, rtol=rtol, atol=atol,
                            names=(f"autograd_grad[{i}]",
                                   f"numeric_grad[{i}]"))


def check_symbolic_forward(sym, args, expected, rtol=1e-5, atol=1e-20):
    """Parity: mx.test_utils.check_symbolic_forward — bind and compare."""
    ex = sym.bind(args={k: v if isinstance(v, NDArray) else NDArray(v)
                        for k, v in args.items()}, grad_req="null")
    outs = ex.forward()
    if len(outs) != len(expected):
        raise AssertionError(f"symbol produced {len(outs)} outputs, "
                             f"expected {len(expected)}")
    for o, e in zip(outs, expected):
        assert_almost_equal(o.asnumpy(), np.asarray(e), rtol=rtol, atol=atol)
    return outs


def check_symbolic_backward(sym, args, out_grads, expected_grads,
                            rtol=1e-4, atol=1e-6):
    """Parity: mx.test_utils.check_symbolic_backward."""
    nd_args = {k: v if isinstance(v, NDArray) else NDArray(v)
               for k, v in args.items()}
    grads = {k: NDArray(np.zeros_like(v.asnumpy())) for k, v in nd_args.items()}
    ex = sym.bind(args=nd_args, args_grad=grads, grad_req="write")
    ex.forward(is_train=True)
    ex.backward([g if isinstance(g, NDArray) else NDArray(g)
                 for g in out_grads])
    for k, e in expected_grads.items():
        assert_almost_equal(ex.grad_dict[k].asnumpy(), np.asarray(e),
                            rtol=rtol, atol=atol, names=(f"grad[{k}]", "expected"))
    return ex.grad_dict
