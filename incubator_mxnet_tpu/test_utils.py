"""Test utilities (parity: python/mxnet/test_utils.py — the helpers the
reference's own test suite is written against)."""
from __future__ import annotations

import numpy as np

from . import context as ctx_mod
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = ["assert_almost_equal", "almost_equal", "same", "default_context",
           "set_default_context", "rand_ndarray", "rand_shape_nd",
           "default_dtype"]


def _to_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b):
    return np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return np.allclose(_to_np(a), _to_np(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _to_np(a), _to_np(b)
    if not np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        err = np.abs(a_np - b_np)
        rel = err / (np.abs(b_np) + 1e-12)
        raise AssertionError(
            f"{names[0]} != {names[1]} (rtol={rtol}, atol={atol}): "
            f"max abs err {err.max():.3e}, max rel err {rel.max():.3e}")


def default_context() -> Context:
    return current_context()


def set_default_context(ctx: Context):
    ctx_mod._default_ctx = ctx


def default_dtype():
    return np.float32


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, dtype="float32", ctx=None):
    return array(np.random.uniform(-1.0, 1.0, shape).astype(dtype), ctx=ctx)
