"""Device-mesh helpers."""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_spec", "replicated", "shard_batch"]


def make_mesh(axes: dict | None = None, devices=None) -> Mesh:
    """make_mesh({'dp': 4, 'tp': 2}) → Mesh over the first 8 devices.
    A single -1 axis absorbs the remaining device count (like reshape).
    A 1-device mesh is valid (annotations all no-op to replicated), so
    the same construction code runs from laptop to pod."""
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"dp": len(devices)})
    names = list(axes.keys())
    sizes = [int(s) for s in axes.values()]
    bad = [s for s in sizes if s == 0 or s < -1]
    if bad:
        raise ValueError(f"mesh axis sizes must be positive (or one -1), "
                         f"got {dict(zip(names, sizes))}")
    if sizes.count(-1) > 1:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} has more than one -1 axis; "
            f"only one axis may absorb the remaining devices")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if len(devices) % known:
            raise ValueError(
                f"mesh {dict(zip(names, sizes))}: {len(devices)} devices "
                f"do not divide evenly by the fixed axes (product {known})")
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_parallel_spec(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Batch-dim sharding along the data-parallel mesh axis."""
    return NamedSharding(mesh, P(axis))


def shard_batch(mesh: Mesh, arr, axis: str = "dp"):
    return jax.device_put(arr, data_parallel_spec(mesh, axis))
