"""Mixture-of-Experts with expert parallelism (GShard-style, TPU-native).

Dense one_hot dispatch/combine einsums (MXU-friendly, no scatter) with the
expert dim sharded over the `ep` mesh axis: under pjit/GSPMD the dispatch
einsum lowers to an all-to-all over ICI, each device runs only its resident
experts' FFNs, and the combine einsum routes tokens home. Top-1/top-2 gating
with capacity dropping and the standard load-balancing auxiliary loss.

Differentiable; compose with dp (shard tokens) and tp (shard expert hidden).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

__all__ = ["moe_gate", "moe_ffn", "MoEFFN"]


def moe_gate(x, gate_w, *, top_k=2, capacity_factor=1.25):
    """Token→expert routing. x: (B, S, D), gate_w: (D, E).

    Returns (dispatch (B,S,E,C) bool, combine (B,S,E,C) f32, aux_loss).
    C = capacity per expert = ceil(top_k * S / E * capacity_factor).
    """
    b, s, d = x.shape
    e = gate_w.shape[1]
    cap = max(1, int(top_k * s / e * capacity_factor))
    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32),
                        gate_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)

    dispatch = jnp.zeros((b, s, e, cap), bool)
    combine = jnp.zeros((b, s, e, cap), jnp.float32)
    masked = probs
    # cumulative per-expert fill across the top_k rounds
    fill = jnp.zeros((b, e), jnp.int32)
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                     # (B,S)
        sel = jax.nn.one_hot(idx, e, dtype=jnp.float32)       # (B,S,E)
        gate_val = (masked * sel).sum(-1)                     # (B,S)
        # position of each token in its expert's queue (this round)
        pos = jnp.cumsum(sel, axis=1) - sel + fill[:, None, :]  # (B,S,E)
        pos_tok = (pos * sel).sum(-1).astype(jnp.int32)       # (B,S)
        keep = pos_tok < cap
        slot = jax.nn.one_hot(pos_tok, cap, dtype=jnp.float32)  # (B,S,C)
        d_k = sel[..., None] * slot[:, :, None, :] * keep[:, :, None, None]
        dispatch = jnp.logical_or(dispatch, d_k > 0)
        combine = combine + d_k * gate_val[:, :, None, None]
        fill = fill + (sel * keep[..., None]).sum(1).astype(jnp.int32)
        masked = masked * (1.0 - sel)                         # exclude chosen
    # load-balancing loss (Switch/GShard): E * mean(frac_tokens * frac_prob)
    me = probs.mean(axis=(0, 1))
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32)
    ce = top1.mean(axis=(0, 1))
    aux = e * jnp.sum(me * ce)
    return dispatch, combine, aux


def moe_ffn(x, gate_w, w1, b1, w2, b2, *, top_k=2, capacity_factor=1.25,
            activation=jax.nn.gelu):
    """MoE FFN layer. x: (B,S,D); w1: (E,D,H); w2: (E,H,D).

    Shard w1/w2 leading dim over 'ep' (Parameter._sharding = P('ep',...)):
    GSPMD turns the dispatch/combine einsums into all-to-alls and keeps each
    expert's GEMMs local. Returns (y (B,S,D), aux_loss).
    """
    dispatch, combine, aux = moe_gate(x, gate_w, top_k=top_k,
                                      capacity_factor=capacity_factor)
    dtype = x.dtype
    # route: (B,S,E,C) x (B,S,D) -> (E, B, C, D)  [all-to-all under GSPMD]
    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(dtype), x)
    h = activation(jnp.einsum("ebcd,edh->ebch", expert_in, w1)
                   + b1[:, None, None, :])
    expert_out = jnp.einsum("ebch,ehd->ebcd", h, w2) + b2[:, None, None, :]
    # route home with gate weights
    y = jnp.einsum("bsec,ebcd->bsd", combine.astype(dtype), expert_out)
    return y, aux


class MoEFFN:
    """Gluon-flavored wrapper: owns params with ep shardings pre-annotated.

    Built at the raw-param level (not a HybridBlock) because it is meant for
    FusedTrainStep/pjit model functions; see gluon wrapper in models using it.
    """

    def __init__(self, num_experts, d_model, d_hidden, *, top_k=2,
                 capacity_factor=1.25, ep_axis="ep"):
        self.num_experts = num_experts
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis

    def init(self, key):
        e, d, h = self.num_experts, self.d_model, self.d_hidden
        kg, k1, k2 = jax.random.split(key, 3)
        s1, s2 = (2.0 / d) ** 0.5, (2.0 / h) ** 0.5
        return {
            "gate_w": jax.random.normal(kg, (d, e)) * 0.02,
            "w1": jax.random.normal(k1, (e, d, h)) * s1,
            "b1": jnp.zeros((e, h)),
            "w2": jax.random.normal(k2, (e, h, d)) * s2,
            "b2": jnp.zeros((e, d)),
        }

    def shardings(self):
        ep = self.ep_axis
        return {"gate_w": P(), "w1": P(ep, None, None), "b1": P(ep, None),
                "w2": P(ep, None, None), "b2": P(ep, None)}

    def resolve_shardings(self, mesh=None):
        """`shardings()` resolved against a concrete mesh through the
        shared registry (mesh=None → the process-global mesh): raw
        PartitionSpecs become NamedShardings; an ep axis the mesh lacks —
        or an expert count that doesn't divide it — falls back to
        replicated, same contract as parameter resolution."""
        from jax.sharding import NamedSharding
        from . import sharding as _sharding
        if mesh is None:
            mesh = _sharding.get_mesh(required=True)
        e = self.num_experts
        out = {}
        for name, spec in self.shardings().items():
            resolved = _sharding.resolve_spec(spec, mesh)
            if len(resolved) > 0 and resolved[0] is not None:
                ax = resolved[0]
                axes = ax if isinstance(ax, tuple) else (ax,)
                if e % int(np.prod([mesh.shape[a] for a in axes])):
                    resolved = P()
            out[name] = NamedSharding(mesh, resolved)
        return out

    def __call__(self, params, x):
        return moe_ffn(x, params["gate_w"], params["w1"], params["b1"],
                       params["w2"], params["b2"], top_k=self.top_k,
                       capacity_factor=self.capacity_factor)
