"""Ring attention: sequence-parallel exact attention over a mesh axis.

The TPU-native rebuild of long-context attention (reference: BytePS-era MXNet
has no equivalent; modern parity target is ring attention / context
parallelism). The sequence dim is sharded over the `sp` mesh axis; each device
keeps its Q shard resident and the K/V shards rotate around the ring via
`lax.ppermute` (one ICI hop per step, overlapped by XLA with the block
matmuls). Softmax is accumulated online (flash-attention style, f32
accumulators), so the full (L, L) score matrix never materialises and memory
stays O(L/n per device).

Differentiable end-to-end: built from `lax.scan` + `ppermute` + jnp ops, so
`jax.grad` through `shard_map` gives the ring-attention backward (KV grads
ride the reverse ring inserted by AD).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "ring_self_attention"]

_NEG = -1e30


def _block_attn(q, k, v, q_pos, k_pos, scale, causal, o, m, l):
    """One online-softmax accumulation step against a KV block (all f32)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return o_new, m_new, l_new


def _ring_body(q, k, v, *, axis_name, n_shards, scale, causal):
    """Runs per-device inside shard_map: q,k,v are (B, H, L/n, D) shards."""
    idx = lax.axis_index(axis_name)
    lq = q.shape[2]
    lk = k.shape[2]
    q_pos = idx * lq + jnp.arange(lq)
    qf = q.astype(jnp.float32)

    perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]

    def step(carry, i):
        o, m, l, kb, vb = carry
        src = (idx + i) % n_shards          # ring origin of the block we hold
        k_pos = src * lk + jnp.arange(lk)
        o, m, l = _block_attn(qf, kb.astype(jnp.float32),
                              vb.astype(jnp.float32),
                              q_pos, k_pos, scale, causal, o, m, l)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o, m, l, kb, vb), None

    b, h, _, d = q.shape
    o0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(n_shards))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _resolve_mesh_axis(mesh, axis):
    """mesh=None → the process-global registry mesh (parallel.sharding);
    axis=None → the mesh's 'sp'/'seq' axis. Shared by ring and Ulysses so
    `ring_attention(q, k, v)` works after one set_mesh call."""
    from . import sharding as _sharding
    if mesh is None:
        mesh = _sharding.get_mesh(required=True)
    if axis is None:
        for name in ("sp", "seq"):
            if name in mesh.shape:
                axis = name
                break
        else:
            raise ValueError(
                f"mesh axes {tuple(mesh.shape)} have no sequence axis "
                f"('sp'/'seq'); pass axis= explicitly")
    return mesh, axis


def ring_attention(q, k, v, mesh: Mesh | None = None, axis: str | None = None,
                   *, causal=False, scale=None,
                   batch_axis: str | None = None):
    """Sequence-parallel attention on (B, H, L, D) arrays.

    L is sharded over mesh axis `axis`; optionally B over `batch_axis` (dp).
    mesh=None resolves the process-global registry mesh, axis=None its
    'sp'/'seq' axis. Returns (B, H, L, D) with the same sharding as q.
    Exact (not approximate): equals single-device softmax attention up to
    f32 accumulation order.
    """
    mesh, axis = _resolve_mesh_axis(mesh, axis)
    n = mesh.shape[axis]
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(f"sequence length {q.shape[2]}/{k.shape[2]} not "
                         f"divisible by sp={n}")
    spec = P(batch_axis, None, axis, None)
    body = functools.partial(_ring_body, axis_name=axis, n_shards=n,
                             scale=scale, causal=causal)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def _self_attention_block(core, x, wqkv, wo, num_heads, mesh, axis, *,
                          causal=False, batch_axis=None):
    """Shared (B, L, D) self-attention choreography: local qkv GEMM, head
    split, a sequence-parallel attention `core` (ring or Ulysses), head
    merge, local output GEMM. One implementation for both schemes."""
    b, L, d = x.shape
    hd = d // num_heads
    qkv = x @ wqkv                                  # (B, L, 3D) local GEMM
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, L, num_heads, hd).transpose(0, 2, 1, 3)

    out = core(heads(q), heads(k), heads(v), mesh, axis,
               causal=causal, batch_axis=batch_axis)
    out = out.transpose(0, 2, 1, 3).reshape(b, L, d)
    return out @ wo


def ring_self_attention(x, wqkv, wo, num_heads, mesh=None, axis=None, *,
                        causal=False, batch_axis=None):
    """(B, L, D) self-attention block with ring-parallel core: qkv/out
    projections run on the local sequence shard (no collective), only the
    attention core rotates KV. mesh/axis default through the registry."""
    mesh, axis = _resolve_mesh_axis(mesh, axis)
    return _self_attention_block(ring_attention, x, wqkv, wo, num_heads,
                                 mesh, axis, causal=causal,
                                 batch_axis=batch_axis)
