"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

The rebuild of multi-machine model parallelism (the reference splits layers
across workers and moves activations via ps-lite/NCCL p2p). Here the layer
stack is split into `pp` stages; stage s lives on mesh slice s of the `pp`
axis. One `lax.scan` runs n_micro + n_stages - 1 ticks; every tick each
device applies its stage to the activation it holds and hands the result to
the next stage via `lax.ppermute` (one ICI hop). The whole pipeline —
bubbles, steady state, drain — is a single XLA computation, so AD through it
yields the standard 1F1B-shaped backward for free.

Works under `jax.grad` + `jit`; stage weights are stacked on a leading axis
sharded over `pp` (GSPMD keeps each stage's slice resident on its devices).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "spmd_pipeline"]


def spmd_pipeline(stage_fn, stage_params, x_mb, *, axis_name: str,
                  n_stages: int):
    """Run inside shard_map over `axis_name`. Per-device view:

    stage_params: this stage's params pytree (leading stage dim of size 1
                  from the sharded stack — squeezed here).
    x_mb:         (n_micro, mb, ...) full microbatched input (replicated;
                  only stage 0 reads it).
    Returns (n_micro, mb, ...) outputs (identical on every stage after the
    final psum-broadcast).
    """
    stage = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    n_micro = x_mb.shape[0]
    ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (clipped in the drain phase)
        inject = x_mb[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, inject, recv)
        y = stage_fn(params, x_in)
        # last stage records microbatch t-(n_stages-1) during steady/drain
        out_idx = t - (n_stages - 1)
        upd = lax.dynamic_update_index_in_dim(
            outputs, y.astype(outputs.dtype), jnp.clip(out_idx, 0, n_micro - 1),
            axis=0)
        take = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        outputs = jnp.where(take, upd, outputs)
        recv = lax.ppermute(y, axis_name, perm)
        return (recv, outputs), None

    mb_shape = x_mb.shape[1:]
    y_shape = jax.eval_shape(stage_fn, params,
                             jax.ShapeDtypeStruct(mb_shape, x_mb.dtype))
    if y_shape.shape != mb_shape:
        raise ValueError(f"pipeline stage must preserve activation shape "
                         f"(got {mb_shape} -> {y_shape.shape}); fold "
                         f"embed/head layers outside the pipelined stack")
    recv0 = jnp.zeros(y_shape.shape, y_shape.dtype)
    out0 = jnp.zeros((n_micro,) + y_shape.shape, y_shape.dtype)
    (_, outputs), _ = lax.scan(tick, (recv0, out0), jnp.arange(ticks))
    # broadcast the last stage's outputs to every stage
    outputs = lax.psum(jnp.where(stage == n_stages - 1, outputs,
                                 jnp.zeros((), y_shape.dtype)), axis_name)
    return outputs


def pipeline_apply(stage_fn, stacked_params, x, mesh: Mesh, *,
                   axis: str = "pp", n_micro: int | None = None,
                   microbatch_axis: int = 0):
    """Apply a pipelined layer stack to a batch.

    stage_fn:       (params, x_mb) -> y_mb, one pipeline stage (may itself
                    scan over several layers).
    stacked_params: pytree whose leaves have a leading dim = n_stages
                    (stage s slice feeds stage_fn on mesh slice s).
    x:              (batch, ...); split into n_micro microbatches.
    Returns y with the batch dim reassembled. Composes with dp/tp: pass a
    mesh carrying those axes too and shard params/batch accordingly.
    """
    n_stages = mesh.shape[axis]
    n_micro = n_micro or n_stages
    b = x.shape[microbatch_axis]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
    x_mb = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    body = functools.partial(spmd_pipeline, stage_fn, axis_name=axis,
                             n_stages=n_stages)
    stacked_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    y_mb = jax.shard_map(
        body, mesh=mesh,
        in_specs=(stacked_spec, P()), out_specs=P(),
        check_vma=False)(stacked_params, x_mb)
    return y_mb.reshape((b,) + y_mb.shape[2:])
