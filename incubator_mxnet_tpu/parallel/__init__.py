"""parallel: mesh-based distributed training (the rebuild of the reference's
kvstore dist_sync_device / NCCL+ps-lite layer, redesigned for TPU).

Instead of translating NCCL calls, the whole train step — forward, backward,
gradient aggregation, optimizer — is ONE jitted XLA computation over a
`jax.sharding.Mesh`. Sharding annotations (in_shardings + Parameter._sharding)
tell XLA where tensors live; XLA inserts the collectives (all-reduce /
all-gather / reduce-scatter) over ICI. Axes convention:

    dp  data parallel        (batch dim)
    tp  tensor parallel      (hidden/heads dims, Megatron-style)
    pp  pipeline parallel    (layer stages, lax.scan + ppermute)
    sp  sequence parallel    (sequence dim, ring attention)
    ep  expert parallel      (MoE experts)
"""
from .mesh import make_mesh, data_parallel_spec
from .trainer_step import FusedTrainStep

__all__ = ["make_mesh", "data_parallel_spec", "FusedTrainStep"]
