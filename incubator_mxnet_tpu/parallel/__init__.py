"""parallel: mesh-based distributed training (the rebuild of the reference's
kvstore dist_sync_device / NCCL+ps-lite layer, redesigned for TPU).

Instead of translating NCCL calls, the whole train step — forward, backward,
gradient aggregation, optimizer — is ONE jitted XLA computation over a
`jax.sharding.Mesh`. Sharding annotations (in_shardings + Parameter._sharding)
tell XLA where tensors live; XLA inserts the collectives (all-reduce /
all-gather / reduce-scatter) over ICI. Axes convention:

    dp  data parallel        (batch dim)
    mp  model parallel       (the documented spelling; `tp` recognized)
    tp  tensor parallel      (hidden/heads dims, Megatron-style)
    pp  pipeline parallel    (layer stages, lax.scan + ppermute)
    sp  sequence parallel    (sequence dim: ring attention or
                             Ulysses all-to-all — both exact)
    ep  expert parallel      (MoE experts)

The process-global mesh registry lives in `sharding`
(`set_mesh(make_mesh({'dp': -1, 'mp': 2}))`); every component here —
FusedTrainStep/TrainLoop, the seed helpers (tensor_parallel /
ring_attention / moe), kvstore's bucketed all-reduce — resolves against
it when no explicit mesh is passed. See docs/sharding.md.
"""
from . import fsdp, sharding
from .mesh import make_mesh, data_parallel_spec
from .sharding import (set_mesh, get_mesh, clear_mesh, use_mesh,
                       axis_rules, auto_shard)
from .trainer_step import FusedTrainStep
from .ring_attention import ring_attention, ring_self_attention
from .ulysses import ulysses_attention, ulysses_self_attention
from .pipeline import pipeline_apply, spmd_pipeline
from .moe import moe_gate, moe_ffn, MoEFFN
from .tensor_parallel import (column_parallel, row_parallel,
                              annotate_bert_tp, annotate_ffn_tp)
from .checkpoint import (save_train_step, restore_train_step, latest_step,
                         list_steps, verify_checkpoint, read_manifest,
                         CorruptCheckpointError)

__all__ = ["make_mesh", "data_parallel_spec", "FusedTrainStep",
           "sharding", "fsdp", "set_mesh", "get_mesh", "clear_mesh",
           "use_mesh", "axis_rules", "auto_shard",
           "ring_attention", "ring_self_attention",
           "ulysses_attention", "ulysses_self_attention", "pipeline_apply",
           "spmd_pipeline", "moe_gate", "moe_ffn", "MoEFFN",
           "column_parallel", "row_parallel", "annotate_bert_tp",
           "annotate_ffn_tp", "save_train_step", "restore_train_step",
           "latest_step"]
