"""Ulysses-style all-to-all sequence parallelism.

The second context-parallel scheme next to ring attention (reference
parity target: DeepSpeed-Ulysses / megatron context parallelism — the
BytePS-era reference scales sequence length with model parallel tricks;
this is the TPU-native form): activations arrive sharded over the
sequence axis; one all-to-all re-shards them over the HEAD axis so every
device runs ordinary dense attention on full-length sequences for H/n
heads; a second all-to-all restores sequence sharding.

Trade-off vs ring attention: Ulysses moves the whole hidden state twice
over ICI but runs the attention core unsharded (best when H >= n and
kernels like flash attention want full L); ring keeps data resident and
rotates KV (best at extreme L where even one full-L activation per device
is too big). Both are exact.

Implementation: `jax.shard_map` + `lax.all_to_all` (tiled over ICI by
XLA); differentiable end-to-end (all_to_all is its own transpose under
AD).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def _attn_core(q, k, v, scale, causal):
    """Dense softmax attention on (B, h_loc, L, D) with f32 accumulation."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        L, Lk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(Lk)[None, :] <= jnp.arange(L)[:, None]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


def _ulysses_body(q, k, v, *, axis_name, scale, causal):
    """Per-device body. Shards come in as (B, H, L/n, D); the first
    all-to-all trades the sequence shard dim for a head shard:
    (B, H/n, L, D). Attention runs dense, then the inverse all-to-all
    restores (B, H, L/n, D)."""
    def seq_to_head(t):
        # split_axis=1 (heads), concat_axis=2 (sequence): each device ends
        # with all L for H/n heads
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def head_to_seq(t):
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    o = _attn_core(qh, kh, vh, scale, causal)
    return head_to_seq(o)


def ulysses_attention(q, k, v, mesh: Mesh | None = None,
                      axis: str | None = None, *,
                      causal=False, scale=None,
                      batch_axis: str | None = None):
    """All-to-all sequence-parallel attention on (B, H, L, D) arrays.

    L sharded over mesh axis `axis` on input AND output; internally heads
    are sharded instead so the core is ordinary dense attention. Requires
    H % n == 0 and L % n == 0. mesh/axis default through the shared mesh
    registry (parallel.sharding), like ring_attention. Exact: equals
    single-device softmax attention up to f32 accumulation order; same
    signature as `ring_attention` so callers can switch schemes with one
    name.
    """
    from .ring_attention import _resolve_mesh_axis
    mesh, axis = _resolve_mesh_axis(mesh, axis)
    n = mesh.shape[axis]
    h, L = q.shape[1], q.shape[2]
    if h % n:
        raise ValueError(f"num_heads {h} not divisible by {axis}={n} "
                         f"(Ulysses shards heads; use ring_attention)")
    if L % n or k.shape[2] % n:
        raise ValueError(f"sequence length {L}/{k.shape[2]} not divisible "
                         f"by {axis}={n}")
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    spec = P(batch_axis, None, axis, None)
    body = functools.partial(_ulysses_body, axis_name=axis, scale=scale,
                             causal=causal)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def ulysses_self_attention(x, wqkv, wo, num_heads, mesh=None, axis=None, *,
                           causal=False, batch_axis=None):
    """(B, L, D) self-attention block with the Ulysses core: projections
    run on the local sequence shard, two all-to-alls bracket the dense
    attention (mirror of `ring_self_attention`). mesh/axis default
    through the registry."""
    from .ring_attention import (_resolve_mesh_axis,
                                 _self_attention_block)
    mesh, axis = _resolve_mesh_axis(mesh, axis)
    return _self_attention_block(ulysses_attention, x, wqkv, wo, num_heads,
                                 mesh, axis, causal=causal,
                                 batch_axis=batch_axis)
