"""FSDP / ZeRO-style parameter + optimizer-state sharding over the data
axis (the path the skip-listed zero1 checkpoint test crashed on, rebuilt
on resolved NamedShardings instead of ad-hoc per-state specs).

Semantics (docs/sharding.md "dp vs fsdp vs mp"):

* every otherwise-REPLICATED trainable parameter whose leading dim
  divides the dp degree lives sharded `P(dp, None, ...)` — 1/dp of the
  weight bytes per device;
* its optimizer state inherits the same layout (ZeRO-1/2's motivation:
  momentum/variance are the dominant optimizer memory);
* the train step's in/out shardings carry these layouts, so XLA
  all-gathers parameters IN-PROGRAM where the forward needs them and
  reduce-scatters gradients back — a pure layout change: same math,
  with only the collective's reduction order free (measured on XLA:CPU:
  losses track the replicated trainer to ~1 ulp per step, while the
  plain dp and dp×mp layouts are bit-identical;
  tests/test_sharding.py pins both);
* params that don't divide (odd leading dims, scalars) and params
  already sharded on a model axis stay as resolved — FSDP never stacks
  onto an mp annotation (that would reshard every step).

This module is layout policy only; the execution path is
parallel/trainer_step.py and the memory evidence is the
`sharding.param_bytes_per_device` / `state_bytes_per_device` gauges plus
diagnostics.reconcile()'s per-device ledger.
"""
from __future__ import annotations

import numpy as np

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import sharding as _sh

__all__ = ["fsdp_spec", "fsdp_sharding", "memory_report"]


def fsdp_spec(shape, mesh: Mesh, axis: str | None = None) -> P | None:
    """The FSDP PartitionSpec for a param of `shape`, or None when the
    shape can't shard (leading dim not divisible by the dp degree, or a
    degenerate mesh/axis)."""
    axis = axis or _sh.data_axis(mesh)
    if axis is None:
        return None
    dp = int(mesh.shape.get(axis, 1))
    if dp <= 1 or not shape:
        return None
    if int(shape[0]) % dp:
        return None
    return P(axis, *([None] * (len(shape) - 1)))


def fsdp_sharding(param, mesh: Mesh, axis: str | None = None) -> NamedSharding:
    """Resolve one Parameter under FSDP. Precedence:

    1. an annotation that RESOLVES on this mesh wins (model/tp layouts
       are never stacked with dp);
    2. an explicit replicate pin — `shard(weight=P())` or a logical
       name the active axis_rules map to None — stays replicated: the
       user said "no per-step all-gathers for this one" (the every-mode
       annotation contract);
    3. an annotation that merely DISSOLVED on this mesh (e.g.
       P('model', None) on a dp-only mesh) behaves like no annotation:
       the FSDP default applies — otherwise auto_shard'ed nets would
       silently lose the mode's whole memory saving;
    4. otherwise: leading dim over the data axis when divisible, else
       replicated."""
    raw = param._sharding
    default = fsdp_spec(param.shape, mesh, axis)
    if raw is None:
        return _sh.resolve_param(param, mesh, default_spec=default)
    resolved = _sh.resolve_param(param, mesh)
    if resolved.spec != P() or _sh.replicate_pinned(raw, mesh):
        return resolved                        # cases 1 & 2
    # case 3: dissolved annotation (fallback already counted above)
    if default is None:
        return resolved
    return NamedSharding(mesh, default)


def memory_report(step) -> dict:
    """Per-device vs logical parameter/state bytes for a built
    FusedTrainStep — the FSDP saving, measured from the live arrays'
    actual shard layouts (not the annotation):

        {"param_bytes_logical":    sum of global param bytes,
         "param_bytes_per_device": what device 0 holds,
         "state_bytes_per_device": ditto for optimizer state leaves,
         "reduction":              logical / per-device (>1 under fsdp)}
    """
    import jax

    if step.params is None:
        raise ValueError("FusedTrainStep is not built yet — run one step "
                         "before asking for its memory report")
    mesh = step.mesh
    raws = [p.data()._data for p in step.params]
    logical = sum(int(np.prod(r.shape)) * r.dtype.itemsize for r in raws)
    if mesh is None:
        return {"param_bytes_logical": logical,
                "param_bytes_per_device": logical,
                "state_bytes_per_device": None, "reduction": 1.0}
    dev0 = np.ravel(np.asarray(mesh.devices, dtype=object))[0]
    per_dev = _sh._bytes_on_device(raws, dev0)
    state_leaves = jax.tree_util.tree_leaves(step._states)
    state_dev = _sh._bytes_on_device(state_leaves, dev0)
    return {"param_bytes_logical": logical,
            "param_bytes_per_device": per_dev,
            "state_bytes_per_device": state_dev,
            "reduction": round(logical / per_dev, 3) if per_dev else None}
