"""Sharded checkpoint/resume for the fused trainer (orbax-backed).

Reference analogue: `module.save_checkpoint` + the kvstore server's state
dump (each server persists its own shard of the optimizer state).
TPU-native redesign: training state lives as sharded `jax.Array`s (FSDP/
ZeRO param+state shards over dp — `FusedTrainStep(sharding='fsdp')`, see
docs/sharding.md — or mp/tp-sharded params over the mesh), so the
checkpoint layer must write each array AS ITS SHARDS — every host saves
its local shards in parallel (orbax/TensorStore OCDBT), and restore
reassembles to the SAME shardings with no gather onto one host. A
single-chip run uses the identical API/files (CPU coverage:
tests/test_sharded_checkpoint.py's subprocess FSDP round trip).

Usage::

    step = FusedTrainStep(net, loss, opt, mesh=mesh,
                          shard_optimizer_states=True)
    step(x, y)                                  # build/compile (any batch)
    ...train...
    save_train_step(ckpt_dir, step)             # -> step_<num_update>/

    # resume in a fresh process: rebuild identically, compile once, then
    step2(x, y)                                 # junk update, overwritten:
    restore_train_step(ckpt_dir, step2)         # params/states/num_update
"""
from __future__ import annotations

import os
import re

__all__ = ["save_train_step", "restore_train_step", "latest_step"]

_STEP_RE = re.compile(r"^step_(\d+)$")


def _tree_of(step):
    if step.params is None:
        raise ValueError(
            "FusedTrainStep is not built yet — run one step (the compile "
            "you need anyway) before save/restore")
    # positional keys: gluon auto-names differ between process runs
    # (dense0 vs dense7), so identity is STRUCTURAL — the parameter order
    # of an identically built net (exactly gluon's structural
    # save_parameters contract)
    from ..ndarray import random as ndrandom
    tree = {
        "params": {f"p{i:04d}": p.data()._data
                   for i, p in enumerate(step.params)},
        "states": step._states,
        "num_update": step._num_update,
    }
    # the framework RNG key feeds every step's dropout masks; exact
    # resume for stochastic nets needs it (fresh-process keys would
    # diverge from the uninterrupted run). _ensure_global_key (not
    # _key()) so an active trace-key context can't hide the global.
    tree["rng_key"] = ndrandom._ensure_global_key()
    return tree


def save_train_step(directory, step, step_num=None):
    """Write params + optimizer states + update counter under
    ``directory/step_<n>``. Sharded arrays save shard-parallel; returns
    the checkpoint path."""
    import orbax.checkpoint as ocp
    n = step._num_update if step_num is None else int(step_num)
    path = os.path.join(os.path.abspath(directory), f"step_{n:08d}")
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _tree_of(step), force=True)
    return path


def latest_step(directory):
    """Highest step number checkpointed in `directory`, or None."""
    if not os.path.isdir(directory):
        return None
    steps = [int(m.group(1)) for f in os.listdir(directory)
             if (m := _STEP_RE.match(f))]
    return max(steps) if steps else None


def restore_train_step(directory, step, step_num=None):
    """Restore into a BUILT FusedTrainStep in place, preserving the live
    arrays' shardings (ZeRO-1/tp layouts restore as laid out). Returns
    the restored update counter."""
    import orbax.checkpoint as ocp
    n = latest_step(directory) if step_num is None else int(step_num)
    if n is None:
        raise FileNotFoundError(f"no step_* checkpoints in {directory!r}")
    path = os.path.join(os.path.abspath(directory), f"step_{n:08d}")
    from ..ndarray import random as ndrandom
    ndrandom._ensure_global_key()  # live tree must carry an rng slot
    live = _tree_of(step)
    with ocp.PyTreeCheckpointer() as ckptr:
        # consult the checkpoint's own structure (no except-and-retry: a
        # genuine restore error must not silently drop the rng_key)
        meta = ckptr.metadata(path)
        # orbax wraps the tree dict: StepMetadata.item_metadata.tree
        tree_meta = getattr(meta, "item_metadata", meta)
        tree_meta = getattr(tree_meta, "tree", tree_meta)
        saved_keys = set(tree_meta)
        if "rng_key" not in saved_keys:
            live.pop("rng_key", None)  # pre-randomness checkpoint
        restore_args = ocp.checkpoint_utils.construct_restore_args(live)
        restored = ckptr.restore(path, item=live,
                                 restore_args=restore_args)
    for i, p in enumerate(step.params):
        p._data._data = restored["params"][f"p{i:04d}"]
    if "rng_key" in restored:
        ndrandom._global_key = restored["rng_key"]
    step._states = restored["states"]
    step._num_update = int(restored["num_update"])
    step.optimizer.num_update = step._num_update
    return step._num_update
