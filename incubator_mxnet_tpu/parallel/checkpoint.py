"""Sharded checkpoint/resume for the fused trainer (orbax-backed).

Reference analogue: `module.save_checkpoint` + the kvstore server's state
dump (each server persists its own shard of the optimizer state).
TPU-native redesign: training state lives as sharded `jax.Array`s (FSDP/
ZeRO param+state shards over dp — `FusedTrainStep(sharding='fsdp')`, see
docs/sharding.md — or mp/tp-sharded params over the mesh), so the
checkpoint layer must write each array AS ITS SHARDS — every host saves
its local shards in parallel (orbax/TensorStore OCDBT), and restore
reassembles to the SAME shardings with no gather onto one host. A
single-chip run uses the identical API/files (CPU coverage:
tests/test_sharded_checkpoint.py's subprocess FSDP round trip).

Durability contract (mxtpu.resilience rides this layer —
docs/resilience.md):

* **atomic visibility** — every save writes into a dot-prefixed temp
  directory and renames it to ``step_<n>`` only after the payload AND
  its manifest are on disk, so a crash mid-save can never leave a
  directory that :func:`latest_step` would pick up. A torn write is
  never a valid checkpoint.
* **integrity manifest** — ``manifest.json`` (schema
  ``mxtpu.ckpt-manifest/1``) records every payload file's size and
  sha256 plus the step/cursor metadata. :func:`verify_checkpoint`
  re-digests the directory; :func:`restore_train_step` verifies before
  loading and, when the newest checkpoint is corrupt (bit-rot, a
  truncated shard, an operator's stray ``rm``), FALLS BACK to the
  previous good one — counted (``resilience.corrupt_checkpoints``) and
  evented, never raised-and-dead and never silently loading a partial
  tree. Pre-manifest checkpoints ("legacy") restore unverified for
  backward compatibility.

Usage::

    step = FusedTrainStep(net, loss, opt, mesh=mesh,
                          shard_optimizer_states=True)
    step(x, y)                                  # build/compile (any batch)
    ...train...
    save_train_step(ckpt_dir, step)             # -> step_<num_update>/

    # resume in a fresh process: rebuild identically, compile once, then
    step2.ensure_built(x, y)                    # compile, no junk update
    restore_train_step(ckpt_dir, step2)         # params/states/num_update
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time

__all__ = ["save_train_step", "restore_train_step", "latest_step",
           "list_steps", "verify_checkpoint", "read_manifest",
           "CorruptCheckpointError", "MANIFEST_NAME", "MANIFEST_SCHEMA"]

_STEP_RE = re.compile(r"^step_(\d+)$")

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = "mxtpu.ckpt-manifest/1"


class CorruptCheckpointError(RuntimeError):
    """An explicitly requested checkpoint failed integrity verification
    (the latest-good path never raises this while an older good
    checkpoint exists — it falls back)."""


def _tree_of(step):
    if step.params is None:
        raise ValueError(
            "FusedTrainStep is not built yet — run one step (the compile "
            "you need anyway) or ensure_built() before save/restore")
    # positional keys: gluon auto-names differ between process runs
    # (dense0 vs dense7), so identity is STRUCTURAL — the parameter order
    # of an identically built net (exactly gluon's structural
    # save_parameters contract)
    from ..ndarray import random as ndrandom
    tree = {
        "params": {f"p{i:04d}": p.data()._data
                   for i, p in enumerate(step.params)},
        "states": step._states,
        "num_update": step._num_update,
    }
    # the framework RNG key feeds every step's dropout masks; exact
    # resume for stochastic nets needs it (fresh-process keys would
    # diverge from the uninterrupted run). _ensure_global_key (not
    # _key()) so an active trace-key context can't hide the global.
    tree["rng_key"] = ndrandom._ensure_global_key()
    return tree


def _host_tree(step):
    """The boundary copy: `_tree_of` snapshotted so the worker can
    serialize it while training continues. This is the ONLY part of an
    async save the training thread pays for — after it returns, the
    live device buffers may be donated away by the next step. Every
    leaf must therefore be an OWNED copy: on the CPU backend,
    ``device_get``/``np.asarray`` of a host-resident buffer is
    zero-copy, and the donated-in-place next step would mutate the
    "snapshot" under the serializer (a checkpoint stamped step N holding
    step N+k values — or NaN ones). Single-shard arrays copy to host
    numpy; a SHARDED array snapshots as an on-device ``jnp.copy``
    (sharding preserved) so orbax still saves it shard-parallel with no
    gather onto one host — the standard async-checkpoint tradeoff of
    one transient device-side copy per sharded leaf."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def _own(x):
        if np.isscalar(x):
            return x
        if isinstance(x, np.ndarray):
            return np.array(x)
        try:
            sharded = (not x.is_fully_addressable) or len(x.devices()) > 1
        except Exception:   # noqa: BLE001 — not a jax.Array
            sharded = False
        return jnp.copy(x) if sharded else np.array(x)

    return jax.tree_util.tree_map(_own, _tree_of(step))


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------

def _sha256_file(path, bufsize=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(bufsize)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _payload_files(path):
    """Every regular file under the checkpoint dir except the manifest
    itself, as sorted relative paths."""
    out = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            rel = os.path.relpath(os.path.join(root, f), path)
            if rel != MANIFEST_NAME:
                out.append(rel)
    return sorted(out)


def _write_manifest(path, step_num, meta=None):
    files = {}
    for rel in _payload_files(path):
        p = os.path.join(path, rel)
        files[rel] = {"bytes": os.path.getsize(p),
                      "sha256": _sha256_file(p)}
    doc = {"schema": MANIFEST_SCHEMA, "step": int(step_num),
           "saved_unix": time.time(), "files": files}
    if meta:
        doc["meta"] = dict(meta)
    # manifest itself is written atomically (tmp + replace): readers of
    # a COMPLETED checkpoint dir must never see a torn manifest either
    tmp = os.path.join(path, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, MANIFEST_NAME))
    return doc


def read_manifest(path):
    """The checkpoint's manifest dict, or None (legacy/pre-manifest
    checkpoint or unreadable manifest — never raises)."""
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def verify_checkpoint(path):
    """Integrity-check one checkpoint directory against its manifest.

    Returns ``(status, errors)``: status is ``"ok"`` (every digest
    matches), ``"legacy"`` (no manifest — pre-PR-12 checkpoint, accepted
    unverified), or ``"corrupt"`` (manifest present but a payload file
    is missing, resized, or fails its sha256 — i.e. a torn or bit-rotted
    write). Never raises."""
    if not os.path.isdir(path):
        return "corrupt", [f"{path}: not a directory"]
    man = read_manifest(path)
    if man is None:
        if os.path.exists(os.path.join(path, MANIFEST_NAME)):
            return "corrupt", [f"{path}: unreadable manifest"]
        return "legacy", []
    files = man.get("files")
    if not isinstance(files, dict):
        return "corrupt", [f"{path}: manifest has no files table"]
    errors = []
    for rel, want in files.items():
        p = os.path.join(path, rel)
        if not os.path.isfile(p):
            errors.append(f"{rel}: missing")
            continue
        size = os.path.getsize(p)
        if size != want.get("bytes"):
            errors.append(f"{rel}: size {size} != manifest "
                          f"{want.get('bytes')}")
            continue
        digest = _sha256_file(p)
        if digest != want.get("sha256"):
            errors.append(f"{rel}: sha256 mismatch")
    # files that appeared after the manifest are tolerated (orbax
    # per-process temp leftovers); files that vanished are not
    return ("corrupt", errors) if errors else ("ok", [])


def _record_corrupt(path, errors):
    """Corrupt-checkpoint fan-out: counter + flight breadcrumb +
    structured event — the fallback must be observable, never silent."""
    from ..profiler.counters import counter as _counter
    _counter("resilience.corrupt_checkpoints", "resilience").increment()
    args = {"path": path, "errors": [str(e)[:200] for e in errors[:4]]}
    try:
        from ..diagnostics import flight as _flight
        if _flight._REC is not None:
            _flight.record("alert", "resilience.corrupt_checkpoint", args)
    except Exception:   # noqa: BLE001 — telemetry must not block recovery
        pass
    try:
        from ..healthmon import events as _events
        _events.emit("alert", "resilience.corrupt_checkpoint", args=args)
    except Exception:   # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# save / restore
# ---------------------------------------------------------------------------

def _step_path(directory, n):
    return os.path.join(os.path.abspath(directory), f"step_{n:08d}")


def save_tree(directory, step_num, tree, meta=None):
    """Write an already-materialized state tree (live jax arrays or the
    host copy from :func:`_host_tree`) under ``directory/step_<n>``,
    atomically: payload + manifest land in a dot-prefixed temp dir that
    is renamed into place only when complete. Returns the checkpoint
    path. This is the serialization half the async CheckpointManager
    runs in its worker thread (resilience/checkpoint.py)."""
    import orbax.checkpoint as ocp
    n = int(step_num)
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    final = _step_path(directory, n)
    tmp = os.path.join(directory,
                       f".tmp_step_{n:08d}.{os.getpid()}.{time.time_ns()}")
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tmp, tree, force=True)
        _write_manifest(tmp, n, meta=meta)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)     # atomic: same filesystem by construction
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


def save_train_step(directory, step, step_num=None, cursor=None):
    """Write params + optimizer states + update counter under
    ``directory/step_<n>`` (atomic, manifested — see module docstring).
    Sharded arrays save shard-parallel; returns the checkpoint path.
    ``cursor`` (data batches consumed so far) rides in the manifest so a
    resumed run can skip past them instead of replaying."""
    n = step._num_update if step_num is None else int(step_num)
    meta = {"num_update": int(n)}
    if cursor is not None:
        meta["cursor"] = int(cursor)
    return save_tree(directory, n, _tree_of(step), meta=meta)


def list_steps(directory):
    """Completed checkpoint step numbers in `directory`, ascending (temp
    dirs from in-flight or crashed saves are invisible by naming)."""
    if not os.path.isdir(directory):
        return []
    return sorted(int(m.group(1)) for f in os.listdir(directory)
                  if (m := _STEP_RE.match(f)))


def latest_step(directory):
    """Highest step number checkpointed in `directory`, or None."""
    steps = list_steps(directory)
    return steps[-1] if steps else None


def _restore_payload(path, step):
    """Restore one verified checkpoint dir into a BUILT step in place."""
    import orbax.checkpoint as ocp
    from ..ndarray import random as ndrandom
    ndrandom._ensure_global_key()  # live tree must carry an rng slot
    live = _tree_of(step)
    with ocp.PyTreeCheckpointer() as ckptr:
        # consult the checkpoint's own structure (no except-and-retry: a
        # genuine restore error must not silently drop the rng_key)
        meta = ckptr.metadata(path)
        # orbax wraps the tree dict: StepMetadata.item_metadata.tree
        tree_meta = getattr(meta, "item_metadata", meta)
        tree_meta = getattr(tree_meta, "tree", tree_meta)
        saved_keys = set(tree_meta)
        if "rng_key" not in saved_keys:
            live.pop("rng_key", None)  # pre-randomness checkpoint
        restore_args = ocp.checkpoint_utils.construct_restore_args(live)
        restored = ckptr.restore(path, item=live,
                                 restore_args=restore_args)
    for i, p in enumerate(step.params):
        p._data._data = restored["params"][f"p{i:04d}"]
    if "rng_key" in restored:
        ndrandom._global_key = restored["rng_key"]
    step._states = restored["states"]
    step._num_update = int(restored["num_update"])
    step.optimizer.num_update = step._num_update
    return step._num_update


def restore_train_step(directory, step, step_num=None):
    """Restore into a BUILT FusedTrainStep in place, preserving the live
    arrays' shardings (FSDP/ZeRO-1/tp layouts restore as laid out).

    With ``step_num=None`` (restart-from-last-good): candidates are
    tried newest-first; one that fails manifest verification — or whose
    unverifiable legacy payload fails to load — is counted + evented and
    SKIPPED in favor of the previous good checkpoint. An explicitly
    requested ``step_num`` that is corrupt raises
    :class:`CorruptCheckpointError` instead (the caller asked for that
    exact state; silently substituting another would be worse than
    failing). Returns the restored update counter."""
    explicit = step_num is not None
    if explicit:
        candidates = [int(step_num)]
    else:
        candidates = list(reversed(list_steps(directory)))
    if not candidates:
        raise FileNotFoundError(f"no step_* checkpoints in {directory!r}")
    tried = []
    for n in candidates:
        path = _step_path(directory, n)
        status, errors = verify_checkpoint(path)
        if status == "corrupt":
            _record_corrupt(path, errors)
            if explicit:
                raise CorruptCheckpointError(
                    f"checkpoint {path} failed verification: "
                    f"{'; '.join(errors[:3])}")
            tried.append(n)
            continue
        if status == "ok":
            # verified payload: a restore error here is a bug (schema
            # drift, wrong net), not disk corruption — propagate
            return _restore_payload(path, step)
        try:
            return _restore_payload(path, step)
        except Exception as e:     # noqa: BLE001 — legacy (unverifiable)
            # checkpoint failed to load: indistinguishable from a torn
            # pre-manifest write, so treat as corrupt and fall back
            if explicit:
                raise
            _record_corrupt(path, [f"legacy restore failed: "
                                   f"{type(e).__name__}: {e}"])
            tried.append(n)
    raise CorruptCheckpointError(
        f"every checkpoint in {directory!r} failed verification "
        f"(tried steps {tried})")
