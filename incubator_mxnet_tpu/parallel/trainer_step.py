"""FusedTrainStep: forward + backward + collective + optimizer in ONE XLA
computation.

This is the TPU replacement for the reference's hot loop (CachedOp fwd/bwd +
kvstore pushpull + per-weight optimizer kernels): everything fuses into a
single executable, gradients never round-trip to Python, and with a Mesh the
gradient all-reduce over the 'dp' axis is inserted by XLA and rides ICI —
the NCCL ring of `kvstore=dist_sync_device`, compiled away.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import autograd
from .. import perfscope as _ps
from .. import profiler as _prof
from ..gluon.parameter import _ParamTraceScope, _trace
from ..gluon.trainer import Trainer
from ..io.pipeline import TRANSFER_GATE as _TRANSFER_GATE
from ..io.pipeline import _defer_put_needed as _cpu_serial_client
from ..ndarray import NDArray
from ..ndarray import random as ndrandom
from .. import optimizer as opt_mod
from . import fsdp as _fsdp
from . import sharding as _sharding

__all__ = ["FusedTrainStep"]


def _memscope_oom(exc, program, step):
    """Attribute an escaping allocator failure before it propagates:
    when memscope is armed and ``exc`` matches the RESOURCE_EXHAUSTED
    taxonomy, the OOM post-mortem (this program's static footprint,
    watermark tail, top-K ledger buffers, resolved knobs) lands on the
    alert surfaces; the caller re-raises the original error unchanged
    either way. One predicate when memscope is off; never raises."""
    try:
        from .. import memscope as _ms
        if _ms._MS is not None:
            _ms.record_oom(exc, program=program, step=step)
    except Exception:  # noqa: BLE001 — forensics never masks the error
        pass


def _memscope_analytic(step):
    """Hand memscope the FSDP analytic per-device byte budget so its
    reconciliation can check the sharding claim (e.g. the 3.3x
    param-memory reduction) against measured watermarks. Fires once per
    built step, only under fsdp, only when memscope is armed; never
    raises."""
    try:
        from .. import memscope as _ms
        if _ms._MS is not None and step.sharding == "fsdp":
            _ms.register_analytic(_fsdp.memory_report(step))
    except Exception:  # noqa: BLE001 — telemetry never breaks the step
        pass


@contextmanager
def _donated_cache_quarantine(step):
    """Suppress persistent-compile-cache READS while a donating fused
    step may compile on XLA:CPU.

    PR 4 found this jaxlib mis-deserializes cached donated fused-step
    executables; runtime/cache_guard re-entered the cache behind a
    once-per-process canary. PR 17's flake hunt showed the corruption
    is PROBABILISTIC PER READ — one certified read proves nothing
    about the next. So donated executables never read the cache at
    all: the dispatch call that may trigger their compile runs under
    cache_guard's read quarantine (a forced cache miss — full story in
    runtime/cache_guard.py). Scoped to donate+CPU; non-donated reads
    stay canary-guarded and keep the suite's warm-start win."""
    if not (step.donate and _cpu_serial_client()):
        yield
        return
    from ..runtime.cache_guard import donated_read_quarantine
    with donated_read_quarantine():
        yield


class FusedTrainStep:
    """Compile net+loss+optimizer into one train step.

    step = FusedTrainStep(net, loss_fn, trainer, mesh=mesh)   # or optimizer
    loss = step(x, y)    # NDArray scalar; params updated in place
    """

    def __init__(self, net, loss_fn, optimizer, mesh: Mesh | None = None,
                 data_axis: str | None = None, donate: bool = True,
                 remat: bool = False, remat_policy: str | None = None,
                 shard_optimizer_states: bool = False,
                 schedule_in_program: bool = False,
                 sharding: str | None = None):
        """remat=True rematerializes the forward during backward
        (jax.checkpoint with the dots-saveable policy) — the TPU-native
        form of the reference's memonger/mirror_stage memory trade:
        activations are recomputed instead of stored, buying batch size /
        sequence length for ~1/3 extra FLOPs, with matmul outputs still
        saved so the MXU work is not repeated. remat_policy picks the
        checkpoint policy: "dots" (default — matmul outputs saved),
        "nothing" (recompute everything: max memory savings, max extra
        FLOPs), "everything" (save all: remat becomes a no-op knob for
        A/B sweeps).

        shard_optimizer_states=True shards each optimizer-state tensor's
        leading axis over the data-parallel mesh axis (ZeRO-1: momentum/
        variance live once across the dp group instead of replicated,
        cutting optimizer memory by the dp degree). Pure layout change —
        GSPMD inserts the collectives; the math is bit-identical. Needs a
        mesh; states whose leading dim doesn't divide the axis stay
        replicated.

        schedule_in_program=True compiles the optimizer's lr schedule
        INTO the k-step program (lr_scheduler.as_jax closed form) so each
        micro-step computes its own lr from the on-device step counter —
        the host never touches the scheduler inside a chunk. Falls back
        to the host-sampled per-micro-step lr table when the scheduler
        has no closed form; either way run_k matches a sequential loop
        step-for-step (the k-granularity coarsening is gone).

        sharding='dp'|'fsdp'|'auto' picks the parallelism policy
        (mxtpu.sharding, docs/sharding.md): 'dp' replicates params and
        shards the batch over the data axis; 'fsdp' additionally shards
        unannotated params AND optimizer states over the data axis
        (all-gathered in-program by XLA — zero-style; same math, losses
        within ~1 ulp/step of the replicated run since the collective's
        reduction order is the compiler's); 'auto' first applies the
        default rule table to the net
        (Dense kernels / Embedding tables onto the model axis when the
        mesh has one). Defaults: the Trainer's `sharding=` flag when one
        is passed as `optimizer`, else $MXTPU_SHARDING, else 'dp'. With
        no mesh (explicit or process-global via sharding.set_mesh) the
        mode is a single-device no-op. Explicit Parameter annotations
        (Block.shard / logical axis rules) are honored in EVERY mode."""
        self.net = net
        self.loss_fn = loss_fn
        if isinstance(optimizer, Trainer):
            if sharding is None:
                sharding = getattr(optimizer, "sharding", None)
            self.optimizer = optimizer.optimizer
        elif isinstance(optimizer, str):
            self.optimizer = opt_mod.create(optimizer)
        else:
            self.optimizer = optimizer
        if sharding is None:
            from ..autotune.knobs import env_str
            sharding = env_str("MXTPU_SHARDING", None)
        if sharding is not None and sharding not in _sharding.MODES:
            raise ValueError(f"unknown sharding mode {sharding!r}; "
                             f"expected one of {_sharding.MODES}")
        if mesh is None:
            mesh = _sharding.get_mesh()
        self.mesh = mesh
        self.sharding = (sharding or "dp") if mesh is not None else None
        if data_axis is None:
            data_axis = (_sharding.data_axis(mesh) or "dp") \
                if mesh is not None else "dp"
        self.data_axis = data_axis
        self.donate = donate
        self.remat = remat
        self.remat_policy = remat_policy
        self.schedule_in_program = schedule_in_program
        if self.sharding == "fsdp":
            # FSDP subsumes ZeRO-1: states follow their (dp-sharded)
            # weights; the zero1 flag additionally shards states of any
            # still-replicated weight
            shard_optimizer_states = True
        self.shard_optimizer_states = shard_optimizer_states and mesh is not None
        self._stats_published = False
        self._auto_specs = {}     # sharding='auto': ephemeral defaults
        self._jitted = None
        self._jitted_k = None
        self._stacked_sharding = None   # set by _build_k under a mesh
        self._lr_program = None   # traceable fn(t)->lr, set in _build_k
        self._lr_dummy = {}       # k -> cached zeros(k) placeholder table
        self._lr_const = {}       # k -> (lr, cached constant (k,) table)
        self._num_update = 0
        self.params = None      # resolved at first call (after deferred init)
        self._states = None
        self._scalar_cache = {}   # hyper name -> (float, device scalar)
        self._cost_analyzed = {}   # perfscope: name -> batch signature

    def _f32(self, name, v):
        """Device scalar for a hyperparameter, one slot per name: lr/wd/
        rescale rarely change, and re-uploading three host scalars every
        step is measurable latency through a remote dispatch relay. A
        per-step-varying scheduler just overwrites its slot (O(1) memory,
        never evicts the constant hyperparameters)."""
        v = float(v)
        hit = self._scalar_cache.get(name)
        if hit is None or hit[0] != v:
            hit = (v, jnp.float32(v))
            self._scalar_cache[name] = hit
        return hit[1]

    # -- setup ------------------------------------------------------------
    def _resolve(self, x, y):
        # persistent-compile-cache integrity canary (runtime/cache_guard):
        # this jaxlib has mis-deserialized donated fused-step executables
        # written by a previous process (PR 4); the canary validates the
        # cache READ path once per process and disables the cache on
        # corruption instead of letting the step train on garbage
        from ..runtime import cache_guard as _cg
        _cg.check()
        # 'auto' defaults (Dense kernels / Embedding tables onto the
        # model axis) are resolved EPHEMERALLY — the net's own
        # annotations are never mutated, so a later sharding='dp' build
        # of the same net stays replicated
        self._auto_specs = (_sharding.auto_specs(self.net)
                            if self.sharding == "auto" else {})
        # one eager pass completes deferred shapes
        try:
            all_params = list(self.net.collect_params().values())
            for p in all_params:
                p.data()
        except Exception:
            with autograd.pause(False):
                self.net(x)
            all_params = list(self.net.collect_params().values())
        self.params = all_params
        self.train_idx = [i for i, p in enumerate(all_params) if p.grad_req != "null"]
        self.aux_idx = [i for i, p in enumerate(all_params) if p.grad_req == "null"]
        self.lr_mults = [all_params[i].lr_mult for i in self.train_idx]
        self.wd_mults = [all_params[i].wd_mult for i in self.train_idx]
        self._states = [self.optimizer.create_state_multi_precision(
            i, all_params[i].data()._data) for i in self.train_idx]
        self._build(x, y)

    def _build(self, x, y):
        net, loss_fn, optimizer = self.net, self.loss_fn, self.optimizer
        params = self.params
        train_idx, aux_idx = self.train_idx, self.aux_idx
        lr_mults, wd_mults = self.lr_mults, self.wd_mults
        ids = [id(p) for p in params]
        aux_ids = [id(params[i]) for i in aux_idx]

        def step_fn(train_raws, aux_raws, states, key, lr, wd, t, rescale, xb, yb):
            def loss_of(train_raws_):
                sub = {}
                for j, i in enumerate(train_idx):
                    sub[ids[i]] = train_raws_[j]
                for j, i in enumerate(aux_idx):
                    sub[ids[i]] = aux_raws[j]
                with _ParamTraceScope(sub), autograd._Scope(False, True), \
                        ndrandom._TraceKeyScope(key):
                    out = net.forward(NDArray(xb))
                    loss = loss_fn(out, NDArray(yb))
                    loss_raw = jnp.mean(loss._data)
                    aux_new = [ _trace.aux_updates.get(aid, aux_raws[j])
                                for j, aid in enumerate(aux_ids)]
                return loss_raw, aux_new

            if self.remat:
                policies = {
                    None: jax.checkpoint_policies
                              .dots_with_no_batch_dims_saveable,
                    "dots": jax.checkpoint_policies
                               .dots_with_no_batch_dims_saveable,
                    "nothing": None,       # recompute everything
                    "everything": jax.checkpoint_policies.everything_saveable,
                }
                try:
                    policy = policies[self.remat_policy]
                except KeyError:
                    raise ValueError(
                        f"unknown remat_policy {self.remat_policy!r}; "
                        f"expected one of {sorted(k for k in policies if k)}"
                    ) from None
                loss_of = jax.checkpoint(loss_of, policy=policy)
            (loss, aux_new), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_raws)
            new_train, new_states = [], []
            for j in range(len(train_raws)):
                nw, ns = optimizer.update_step(
                    train_raws[j], grads[j], states[j],
                    lr * lr_mults[j], wd * wd_mults[j], t,
                    rescale=rescale,
                    clip=optimizer.clip_gradient)
                new_train.append(nw)
                new_states.append(ns)
            return loss, new_train, aux_new, new_states

        self._step_fn = step_fn
        kwargs = {}
        self._sharding_info = None
        if self.mesh is not None:
            # batch over the data axis (replicated on a pure-mp mesh)
            batch_spec = (P(self.data_axis)
                          if self.data_axis in self.mesh.shape else P())
            batch_sharding = NamedSharding(self.mesh, batch_spec)
            repl = NamedSharding(self.mesh, P())

            # annotation resolution moved to mxtpu.sharding: logical axis
            # names map through the active rule table, and a dim that
            # doesn't divide the mesh axis (e.g. unpadded vocab under mp)
            # falls back to replicated — a layout hint, never a
            # correctness constraint. Under FSDP, unannotated trainable
            # params shard their leading dim over the data axis instead
            # of replicating (all-gathered in-program by XLA).
            if self.sharding == "fsdp":
                def pspec(p):
                    return _fsdp.fsdp_sharding(p, self.mesh, self.data_axis)
            else:
                def pspec(p):
                    return _sharding.resolve_param(
                        p, self.mesh,
                        default_spec=self._auto_specs.get(id(p)))

            train_sh = [pspec(params[i]) for i in self.train_idx]
            # aux state (BatchNorm running stats) never FSDP-shards —
            # explicit annotations only
            aux_sh = [_sharding.resolve_param(params[i], self.mesh)
                      for i in self.aux_idx]
            # optimizer state inherits its weight's sharding — or, under
            # ZeRO-1, shards its leading axis over the dp group
            def state_spec(j, leaf):
                # only ZeRO-shard states of otherwise-replicated weights:
                # tp/sp-sharded weights already split their state, and
                # stacking dp on top would reshard every step. The
                # leading-dim-over-dp policy is fsdp_spec — ONE place
                # for the divisibility/fallback rule.
                if (self.shard_optimizer_states
                        and train_sh[j].spec == P()):
                    spec = _fsdp.fsdp_spec(np.shape(leaf), self.mesh,
                                           self.data_axis)
                    if spec is not None:
                        return NamedSharding(self.mesh, spec)
                return train_sh[j]

            state_sh = [jax.tree_util.tree_map(
                lambda leaf, j=j: state_spec(j, leaf), self._states[j])
                for j in range(len(self._states))]
            kwargs["in_shardings"] = (train_sh, aux_sh, state_sh, repl, repl,
                                      repl, repl, repl,
                                      batch_sharding, batch_sharding)
            kwargs["out_shardings"] = (repl, train_sh, aux_sh, state_sh)
            self._sharding_info = (train_sh, aux_sh, state_sh, repl,
                                   batch_sharding)
        if self.donate:
            kwargs["donate_argnums"] = (0, 1, 2)
        self._jitted = jax.jit(step_fn, **kwargs)

    def _build_k(self):
        """Wrap the same step_fn in a lax.scan over a leading micro-step
        axis: k fwd+bwd+collective+update iterations inside ONE XLA
        program. Through a remote dispatch relay (or any host-limited
        launch path) this amortizes per-step latency by k — the chip runs
        micro-steps back-to-back instead of idling between dispatches.

        lr is PER MICRO-STEP: either computed in-program from the step
        counter t (schedule_in_program + a closed-form scheduler) or
        scanned from a host-sampled (k,) table — both match a sequential
        loop step-for-step; the old chunk-granularity lr is gone."""
        step_fn = self._step_fn
        self._lr_program = None
        if self.schedule_in_program:
            sched = getattr(self.optimizer, "lr_scheduler", None)
            if sched is not None:
                self._lr_program = sched.as_jax()
        lr_program = self._lr_program

        def scan_fn(train_raws, aux_raws, states, key, lrs, wd, t0, rescale,
                    xs, ys):
            def one(carry, xy):
                tr, ax, st, k, t = carry
                k, sub = jax.random.split(k)
                xb, yb, lr_t = xy
                if lr_program is not None:
                    lr_t = lr_program(t)        # in-program schedule
                loss, ntr, nax, nst = step_fn(
                    tr, ax, st, sub, lr_t, wd, t, rescale, xb, yb)
                return (ntr, nax, nst, k, t + 1), loss

            (tr, ax, st, _, _), losses = jax.lax.scan(
                one, (train_raws, aux_raws, states, key, t0), (xs, ys, lrs))
            return losses, tr, ax, st

        kwargs = {}
        self._stacked_sharding = None
        if self._sharding_info is not None:
            train_sh, aux_sh, state_sh, repl, batch_sh = self._sharding_info
            stacked = NamedSharding(
                self.mesh, P(None, *batch_sh.spec))  # k axis unsharded
            self._stacked_sharding = stacked   # single source for run_k
            kwargs["in_shardings"] = (train_sh, aux_sh, state_sh, repl, repl,
                                      repl, repl, repl, stacked, stacked)
            kwargs["out_shardings"] = (repl, train_sh, aux_sh, state_sh)
        if self.donate:
            kwargs["donate_argnums"] = (0, 1, 2)
        self._jitted_k = jax.jit(scan_fn, **kwargs)

    def _chunk_lrs(self, k):
        """The (k,) per-micro-step lr values for the NEXT k updates.

        Host-table mode samples the scheduler at each t exactly as a
        sequential loop would (stateful schedulers advance identically —
        t is monotone). In-program mode returns a cached zero placeholder
        (threaded through the scan signature, dead-code-eliminated by
        XLA) and leaves the scheduler object untouched."""
        if self._lr_program is not None:
            tab = self._lr_dummy.get(k)
            if tab is None:
                tab = jnp.zeros((k,), jnp.float32)
                self._lr_dummy[k] = tab
            return tab
        if getattr(self.optimizer, "lr_scheduler", None) is None:
            # constant lr: one cached device table per (k, lr) — no
            # per-chunk host upload (the _f32 scalar-cache discipline)
            lr = float(self.optimizer.learning_rate)
            hit = self._lr_const.get(k)
            if hit is None or hit[0] != lr:
                hit = (lr, jnp.full((k,), lr, jnp.float32))
                self._lr_const[k] = hit
            return hit[1]
        vals = np.empty((k,), np.float32)
        for i in range(k):
            self.optimizer.num_update = self._num_update + 1 + i
            vals[i] = self.optimizer.learning_rate
        return jnp.asarray(vals)

    def ensure_built(self, x, y):
        """Resolve parameters and compile from a shape probe WITHOUT
        consuming an optimizer update. The restore path needs a BUILT
        step (params resolved, states allocated); the old recipe — run
        one junk update and let restore overwrite it — advanced
        num_update and burned an RNG split, which a resumed stochastic
        net would notice. Idempotent; returns self."""
        if not isinstance(x, NDArray):
            x = NDArray(x)
        if not isinstance(y, NDArray):
            y = NDArray(y)
        if self._jitted is None:
            self._resolve(x, y)
        return self

    # -- execution --------------------------------------------------------
    def __call__(self, x, y):
        if not isinstance(x, NDArray):
            x = NDArray(x)
        if not isinstance(y, NDArray):
            y = NDArray(y)
        if self._jitted is None:
            self._resolve(x, y)
        self._num_update += 1
        self.optimizer.num_update = self._num_update
        lr = self._f32("lr", self.optimizer.learning_rate)
        wd = self._f32("wd", self.optimizer.wd)
        t = jnp.int32(self._num_update)
        key = ndrandom._key()
        xb, yb = x._data, y._data
        if self._sharding_info is not None:
            batch_sharding = self._sharding_info[4]   # resolved in _build
            with _TRANSFER_GATE:
                xb = jax.device_put(xb, batch_sharding)
                yb = jax.device_put(yb, batch_sharding)
        train_raws = [self.params[i].data()._data for i in self.train_idx]
        aux_raws = [self.params[i].data()._data for i in self.aux_idx]
        rescale = self._f32("rescale", self.optimizer.rescale_grad)
        sig = (tuple(xb.shape), str(xb.dtype), tuple(yb.shape),
               str(yb.dtype))
        if _ps._PS is not None and \
                self._cost_analyzed.get("fused_step") != sig:
            # roofline capture BEFORE dispatch: analyze_jit only reads
            # shapes/dtypes, so it is safe against the donated buffers.
            # Keyed on the batch signature: a shape-driven recompile gets
            # re-analyzed so the table describes the program being timed.
            # mesh/mode flow through to commscope, which (when armed)
            # walks the compiled HLO for the program's collective
            # inventory — the thing the step budget's `collective`
            # component is estimated from under GSPMD (docs/commscope.md)
            self._cost_analyzed["fused_step"] = sig
            _ps.analyze_jit(
                self._jitted,
                (train_raws, aux_raws, self._states, key, lr, wd, t,
                 rescale, xb, yb),
                name="fused_step", dtype=xb.dtype, kind="train_step",
                mesh=self.mesh, mode=self.sharding)
        # the donating dispatch ENQUEUE is serialized against any
        # in-flight prefetcher device_put (io.pipeline.TRANSFER_GATE) —
        # the enqueue-ordering half of the PR 14 flake fix; the other
        # half is the pipeline's consumer-thread put on XLA:CPU. The
        # guarded region is the async enqueue, not the step execution.
        try:
            with _TRANSFER_GATE, _donated_cache_quarantine(self):
                loss, new_train, new_aux, new_states = self._jitted(
                    train_raws, aux_raws, self._states, key, lr, wd, t,
                    rescale, xb, yb)
                if _cpu_serial_client():
                    # XLA:CPU (io/pipeline.py safety model): retire the
                    # donating execution before ANY other client call —
                    # this client races the donated-buffer handoff of a
                    # still-running execution against concurrent client
                    # work regardless of which Python thread issues it.
                    # INSIDE the gate: the donation window and the gate
                    # window coincide, so gate holders (async checkpoint
                    # saves, prefetcher puts) are mutually excluded from
                    # it. Compute∥decode overlap is unaffected (the decode
                    # pool is host-side); only async dispatch depth is
                    # forfeited, on the backend where it buys nothing.
                    jax.block_until_ready(
                        (loss, new_train, new_aux, new_states))
        except Exception as e:  # noqa: BLE001 — re-raised unchanged
            _memscope_oom(e, "fused_step", self._num_update)
            raise
        for j, i in enumerate(self.train_idx):
            self.params[i]._data._data = new_train[j]
        for j, i in enumerate(self.aux_idx):
            self.params[i]._data._data = new_aux[j]
        self._states = new_states
        if not self._stats_published and self.mesh is not None:
            # one-time layout telemetry: the params now carry the
            # shardings the compiled program actually produced
            self._stats_published = True
            _sharding.publish_param_stats(self.params, self._states,
                                          self.mesh, self.sharding)
            _memscope_analytic(self)
        # fully-fused path: forward+backward+collective+update is ONE XLA
        # dispatch per step (bench.py surfaces this in BENCH_*.json)
        _prof.set_gauge("trainer.dispatches_per_step", 1)
        return NDArray(loss)

    def run_k(self, xs, ys):
        """Run k optimizer micro-steps as ONE compiled XLA program (a
        lax.scan over the leading axis) — k× fewer host dispatches, so a
        slow launch path (e.g. a remote device relay) no longer bounds
        step time. xs/ys: stacked (k, batch, ...) arrays, or lists of k
        per-step batches. lr is per micro-step (host-sampled table, or
        computed in-program under schedule_in_program), so schedulers
        advance step-for-step exactly like a sequential loop. Returns the
        k per-step losses as an NDArray of shape (k,).

        Reference contrast: the reference's engine pipelines k steps by
        async dependency tracking; here the compiler gets all k steps in
        one program, which also lets XLA overlap grad collectives of step
        t with compute of step t+1."""
        def to_stacked(seq):
            if isinstance(seq, (list, tuple)):
                # stay on device: no host round-trip for NDArray batches
                return jnp.stack([b._data if isinstance(b, NDArray)
                                  else jnp.asarray(b) for b in seq])
            return seq._data if isinstance(seq, NDArray) else jnp.asarray(seq)

        xs, ys = to_stacked(xs), to_stacked(ys)
        k = int(xs.shape[0])
        if self._jitted is None:
            self._resolve(NDArray(xs[0]), NDArray(ys[0]))
        if self._jitted_k is None:
            self._build_k()
        lrs = self._chunk_lrs(k)
        wd = self._f32("wd", self.optimizer.wd)
        t0 = jnp.int32(self._num_update + 1)
        key = ndrandom._key()
        if self._stacked_sharding is not None:
            with _TRANSFER_GATE:
                xs = jax.device_put(xs, self._stacked_sharding)
                ys = jax.device_put(ys, self._stacked_sharding)
        train_raws = [self.params[i].data()._data for i in self.train_idx]
        aux_raws = [self.params[i].data()._data for i in self.aux_idx]
        rescale = self._f32("rescale", self.optimizer.rescale_grad)
        sig = (tuple(xs.shape), str(xs.dtype), tuple(ys.shape),
               str(ys.dtype))
        if _ps._PS is not None and \
                self._cost_analyzed.get(f"fused_step_k{k}") != sig:
            self._cost_analyzed[f"fused_step_k{k}"] = sig
            _ps.analyze_jit(
                self._jitted_k,
                (train_raws, aux_raws, self._states, key, lrs, wd, t0,
                 rescale, xs, ys),
                name=f"fused_step_k{k}", dtype=xs.dtype, kind="train_step",
                extra={"k": k}, mesh=self.mesh, mode=self.sharding)
        # donation-vs-transfer serialization, same contract as __call__
        try:
            with _TRANSFER_GATE, _donated_cache_quarantine(self):
                losses, new_train, new_aux, new_states = self._jitted_k(
                    train_raws, aux_raws, self._states, key, lrs, wd, t0,
                    rescale, xs, ys)
                if _cpu_serial_client():
                    # XLA:CPU donating dispatch retires inside the gate —
                    # see the matching __call__ block and io/pipeline.py
                    jax.block_until_ready((losses, new_train, new_aux,
                                           new_states))
        except Exception as e:  # noqa: BLE001 — re-raised unchanged
            _memscope_oom(e, f"fused_step_k{k}", self._num_update)
            raise
        self._num_update += k
        self.optimizer.num_update = self._num_update
        for j, i in enumerate(self.train_idx):
            self.params[i]._data._data = new_train[j]
        for j, i in enumerate(self.aux_idx):
            self.params[i]._data._data = new_aux[j]
        self._states = new_states
        if not self._stats_published and self.mesh is not None:
            self._stats_published = True
            _sharding.publish_param_stats(self.params, self._states,
                                          self.mesh, self.sharding)
            _memscope_analytic(self)
        # one dispatch drives k micro-steps
        _prof.set_gauge("trainer.dispatches_per_step", round(1.0 / k, 4))
        return NDArray(losses)
