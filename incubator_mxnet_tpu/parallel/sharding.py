"""mxtpu.sharding — mesh-native GSPMD parallelism through Gluon.

The reference's distributed story is kvstore RPC (ps-lite) or NCCL rings;
PAPER.md §1 maps it onto `jax.sharding.Mesh` + GSPMD instead: annotate
where every tensor LIVES and let XLA insert the collectives. This module
is the annotation/resolution layer that makes that work through Gluon:

* **process-global named mesh** — `set_mesh(make_mesh({'dp': -1,
  'mp': 2}))` registers THE mesh every sharded component resolves
  against (Trainer/TrainLoop/FusedTrainStep pick it up without plumbing
  a mesh argument through user code; `use_mesh` scopes it);
* **logical axis rules** — parameter annotations may name LOGICAL axes
  (``'model'``, ``'batch'``, ``'expert'``, …) that resolve to whatever
  mesh axis the rule table maps them to (``('model', 'mp')``), so the
  same annotated net runs on a ``(dp,)``, ``(dp, mp)`` or ``(dp, tp)``
  mesh without re-annotation — the SNIPPETS.md exemplar's "8-chip v4 to
  6000-chip v5p without changing application code" contract;
* **per-Block annotation** — `Block.shard(spec)` (gluon/block.py)
  attaches specs to Gluon parameters; `auto_shard(net)` applies the
  default rule table (Dense kernels and Embedding tables on the model
  axis, biases/norms replicated, everything else data-parallel);
* **resolution** — `resolve_param(param, mesh)` turns an annotation into
  a concrete `NamedSharding`, mapping logical axes through the active
  rules and falling back to replicated when a dim doesn't divide the
  mesh axis (annotation is a layout hint, never a correctness
  constraint — the fallback is counted, not silent);
* **telemetry** — the `sharding.*` counter family (enforced by
  tools/trace_check.py) publishes mesh shape, per-param spec counts and
  per-device parameter/optimizer-state bytes through the shared
  registry, so every exporter (Prometheus, flight, BENCH json) sees the
  layout actually compiled.

The execution side lives in parallel/trainer_step.py (the one-jit
fwd+bwd+optimizer program whose in/out shardings carry these
resolutions) and parallel/fsdp.py (zero-style parameter/optimizer-state
sharding). docs/sharding.md has the axis-rule table and the dp vs fsdp
vs mp decision guide.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import profiler as _prof

__all__ = ["set_mesh", "get_mesh", "clear_mesh", "use_mesh",
           "data_axis", "model_axis", "axis_rules", "current_rules",
           "resolve_axis", "resolve_spec", "resolve_param", "auto_shard",
           "publish_param_stats", "summary", "MODES", "DEFAULT_RULES"]

# Trainer/TrainLoop/FusedTrainStep sharding modes (docs/sharding.md):
#   dp    pure data parallel — params replicated, batch sharded over the
#         data axis, XLA's psum is the gradient all-reduce
#   fsdp  dp + zero-style: unannotated params AND optimizer states live
#         sharded over the data axis, all-gathered in-program
#   auto  dp + the default rule table applied to the net (Dense kernels /
#         Embedding tables on the model axis when the mesh has one)
MODES = ("dp", "fsdp", "auto")

# Mesh-axis name conventions, in detection-priority order. `dp`/`mp` are
# the documented spellings; `tp` is the seed helpers' tensor-parallel
# name and stays recognized so existing annotations keep working.
DATA_AXES = ("dp", "data", "batch")
MODEL_AXES = ("mp", "tp", "model")

# Logical-axis rule table: (logical name, mesh axis), first pair whose
# mesh axis exists in the active mesh wins. Users prepend overrides with
# `axis_rules`. Unmatched logical names resolve to None (replicated dim).
DEFAULT_RULES = (
    ("model", "mp"), ("model", "tp"),
    ("batch", "dp"), ("batch", "data"),
    ("hidden", "mp"), ("hidden", "tp"),
    ("vocab", "mp"), ("vocab", "tp"),
    ("heads", "mp"), ("heads", "tp"),
    ("expert", "ep"),
    ("seq", "sp"),
)

_lock = threading.Lock()
_MESH: Mesh | None = None


class _RulesState(threading.local):
    """The axis-rule overlay is THREAD-LOCAL (like jax's own config
    scopes): two threads' `with axis_rules(...)` blocks can never
    corrupt each other's restore path. None means DEFAULT_RULES."""

    def __init__(self):
        self.rules = None


_rules_state = _RulesState()
# last published layout stats — bench.py's extra.sharding reads this
_LAST: dict = {}


# --------------------------------------------------------------------------
# mesh registry
# --------------------------------------------------------------------------

def _publish_mesh_gauges(mesh: Mesh | None) -> None:
    """Keep the layout gauges truthful in BOTH directions: a cleared
    registry must read 0 devices, not the last mesh's shape."""
    if mesh is None:
        for g in ("mesh_devices", "mesh_dp", "mesh_mp"):
            _prof.set_gauge("sharding." + g, 0, "sharding")
        return
    _prof.set_gauge("sharding.mesh_devices", int(mesh.size), "sharding")
    _prof.set_gauge("sharding.mesh_dp",
                    int(mesh.shape.get(data_axis(mesh) or "", 1)),
                    "sharding")
    _prof.set_gauge("sharding.mesh_mp",
                    int(mesh.shape.get(model_axis(mesh) or "", 1)),
                    "sharding")


def set_mesh(mesh: Mesh | None) -> Mesh | None:
    """Register the process-global mesh every sharded component resolves
    against. Returns the mesh. `set_mesh(None)` clears (== clear_mesh)."""
    global _MESH
    with _lock:
        _MESH = mesh
    _publish_mesh_gauges(mesh)
    return mesh


def get_mesh(required: bool = False) -> Mesh | None:
    """The process-global mesh, or None. required=True raises instead."""
    if required and _MESH is None:
        raise RuntimeError(
            "no global mesh registered; call "
            "sharding.set_mesh(make_mesh({'dp': -1})) first")
    return _MESH


def clear_mesh() -> None:
    global _MESH
    with _lock:
        _MESH = None
    _publish_mesh_gauges(None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Scope the global mesh: `with sharding.use_mesh(mesh): ...`."""
    prev = _MESH
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


def _first_present(names, mesh: Mesh | None) -> str | None:
    if mesh is None:
        return None
    for n in names:
        if n in mesh.shape:
            return n
    return None


def data_axis(mesh: Mesh | None = None) -> str | None:
    """The mesh's data-parallel axis name ('dp'/'data'/'batch'), or None."""
    return _first_present(DATA_AXES, mesh if mesh is not None else _MESH)


def model_axis(mesh: Mesh | None = None) -> str | None:
    """The mesh's model-parallel axis name ('mp'/'tp'/'model'), or None."""
    return _first_present(MODEL_AXES, mesh if mesh is not None else _MESH)


# --------------------------------------------------------------------------
# logical axis rules
# --------------------------------------------------------------------------

@contextlib.contextmanager
def axis_rules(*pairs):
    """Prepend logical-axis rules for the scope:

        with sharding.axis_rules(("hidden", "mp"), ("vocab", None)):
            net.shard(P("hidden", None))

    Each pair is (logical_name, mesh_axis_or_None); user pairs take
    priority over DEFAULT_RULES. Mapping a logical name to None pins it
    replicated even if a default rule would shard it. The overlay is
    thread-local — resolve on the thread that entered the scope."""
    for p in pairs:
        if (not isinstance(p, (tuple, list)) or len(p) != 2
                or not isinstance(p[0], str)):
            raise ValueError(
                f"axis_rules pairs must be (logical, mesh_axis) 2-tuples, "
                f"got {p!r}")
    prev = _rules_state.rules
    _rules_state.rules = tuple(tuple(p) for p in pairs) + current_rules()
    try:
        yield
    finally:
        _rules_state.rules = prev


def current_rules() -> tuple:
    return _rules_state.rules if _rules_state.rules is not None \
        else DEFAULT_RULES


def resolve_axis(name, mesh: Mesh | None = None):
    """One spec entry → mesh axis (or None → replicated dim). Mesh axis
    names pass through; logical names map through the active rules; a
    name matching neither replicates (never errors — portability)."""
    mesh = mesh if mesh is not None else _MESH
    if name is None or mesh is None:
        return None
    if name in mesh.shape:
        return name
    for logical, ax in current_rules():
        if logical == name:
            if ax is None:
                return None
            if ax in mesh.shape:
                return ax
    return None


def resolve_spec(spec, mesh: Mesh | None = None) -> P:
    """PartitionSpec with logical names → PartitionSpec of mesh axes."""
    mesh = mesh if mesh is not None else _MESH
    if spec is None:
        return P()
    out = []
    for entry in spec:
        if isinstance(entry, (tuple, list)):
            axes = [resolve_axis(a, mesh) for a in entry]
            axes = [a for a in axes if a is not None]
            out.append(tuple(axes) if len(axes) > 1
                       else (axes[0] if axes else None))
        else:
            out.append(resolve_axis(entry, mesh))
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def _divides(shape, spec: P, mesh: Mesh) -> bool:
    for d, ax in enumerate(spec):
        if ax is None:
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        if d >= len(shape) or shape[d] % size:
            return False
    return True


def _spec_names(spec):
    """The axis names a raw annotation mentions (flattened, None-free)."""
    if spec is None:
        return []
    return [a for e in spec if e is not None
            for a in (e if isinstance(e, (tuple, list)) else (e,))]


def replicate_pinned(spec, mesh: Mesh | None = None) -> bool:
    """True when an annotation EXPLICITLY asks for replication under the
    active rules: `P()` / all-None entries, or a named entry the rules
    map to None before any mesh-resolvable mapping (an axis_rules pin).
    An annotation whose names merely don't exist on this mesh (e.g.
    P('model', None) on a dp-only mesh) is NOT a pin — it dissolved,
    and callers with a default (FSDP) may still apply it."""
    if spec is None:
        return False
    names = _spec_names(spec)
    if not names:
        return True                      # P() / P(None, ...)
    mesh = mesh if mesh is not None else _MESH
    for name in names:
        if mesh is not None and name in mesh.shape:
            return False
        for logical, ax in current_rules():
            if logical == name:
                if ax is None:
                    return True          # explicit (name, None) pin
                if mesh is not None and ax in mesh.shape:
                    return False
    return False


def resolve_param(param, mesh: Mesh | None = None,
                  default_spec=None) -> NamedSharding:
    """A Parameter's annotation → concrete NamedSharding on `mesh`.

    Logical axes map through the active rules; a spec that dissolves
    (names missing from this mesh) or whose sharded dims don't divide
    the mesh axes falls back to replicated — counted in
    `sharding.fallback_replicated`, never silent. `default_spec`
    applies when the param carries no annotation (the FSDP path passes
    its dp-leading spec here)."""
    mesh = mesh if mesh is not None else get_mesh(required=True)
    _prof.counter("sharding.resolves", "sharding").increment()
    raw = param._sharding if param._sharding is not None else default_spec
    spec = resolve_spec(raw, mesh)
    if spec == P():
        if _spec_names(raw) and not replicate_pinned(raw, mesh):
            # a real annotation dissolved on this mesh — the counted
            # fallback, same as the non-dividing case below
            _prof.counter("sharding.fallback_replicated",
                          "sharding").increment()
        return NamedSharding(mesh, P())
    shape = param.shape
    if shape is None or not _divides(shape, spec, mesh):
        _prof.counter("sharding.fallback_replicated", "sharding").increment()
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, spec)


# --------------------------------------------------------------------------
# per-Block defaults (the axis-rule table's "auto" column)
# --------------------------------------------------------------------------

# Block classes whose 2-D `weight` defaults onto the model axis: Dense
# kernels are (units, in_units) — sharding dim 0 is Megatron
# column-parallel; Embedding tables are (vocab, dim) — sharding dim 0
# splits the vocab. Biases/norm scales are 1-D and stay replicated, as
# do conv kernels (spatial dims rarely divide, and dp is the win there).
_AUTO_MODEL_BLOCKS = ("Dense", "Embedding")


def auto_shard(net, mesh: Mesh | None = None, overwrite: bool = False):
    """Apply the default rule table to a Gluon block tree: every Dense /
    Embedding `weight` gets the logical P('model', None) annotation
    (resolved to the mesh's mp/tp axis at build, replicated if the mesh
    has none). Existing annotations are kept unless overwrite=True.
    Returns `net` for chaining.

    This WRITES annotations (visible, clearable with net.shard(None)) —
    the explicit form. The executor's sharding='auto' mode instead uses
    :func:`auto_specs`, which leaves the net untouched so a later
    sharding='dp' build of the same net is not silently model-sharded."""
    def visit(blk):
        if type(blk).__name__ in _AUTO_MODEL_BLOCKS:
            w = getattr(blk, "weight", None)
            if w is not None and (overwrite or w._sharding is None):
                w._sharding = P("model", None)
        for child in getattr(blk, "_children", {}).values():
            visit(child)
    visit(net)
    return net


def auto_specs(net) -> dict:
    """Non-mutating auto_shard: the default-rule annotations as an
    ephemeral {id(Parameter): PartitionSpec} map for unannotated Dense /
    Embedding weights, consumed as resolve_param's default_spec by the
    executor's 'auto' mode. User annotations always win (absent here)."""
    out = {}

    def visit(blk):
        if type(blk).__name__ in _AUTO_MODEL_BLOCKS:
            w = getattr(blk, "weight", None)
            if w is not None and w._sharding is None:
                out[id(w)] = P("model", None)
        for child in getattr(blk, "_children", {}).values():
            visit(child)
    visit(net)
    return out


# --------------------------------------------------------------------------
# telemetry
# --------------------------------------------------------------------------

def _bytes_on_device(raws, device) -> int:
    """Physical bytes the given device holds for these arrays — the
    per-device cost a sharded layout actually pays (a replicated array
    costs its full size; an FSDP shard 1/dp of it). Delegates to the
    diagnostics ledger's shard walker so the gauge and the reconcile
    census can never disagree. Shardless host buffers (key None) count
    toward the queried device."""
    from ..diagnostics.memory import shard_bytes_by_device
    by_dev = shard_bytes_by_device(raws)
    return by_dev.get(device, 0) + by_dev.get(None, 0)


def publish_param_stats(params, states=None, mesh: Mesh | None = None,
                        mode: str | None = None) -> dict:
    """Count the resolved layout and publish the sharding.* gauges.

    Called by FusedTrainStep after its first dispatch (params are live,
    concrete jax.Arrays then). Returns — and caches for `summary()` —
    the dict bench.py embeds as `extra.sharding.params`."""
    mesh = mesh if mesh is not None else _MESH
    d_ax, m_ax = data_axis(mesh), model_axis(mesh)
    n_model = n_data = n_repl = 0
    raws = []
    for p in params:
        raw = p.data()._data
        raws.append(raw)
        spec = getattr(getattr(raw, "sharding", None), "spec", None)
        flat = [a for e in (spec or ()) if e is not None
                for a in (e if isinstance(e, tuple) else (e,))]
        if m_ax is not None and m_ax in flat:
            n_model += 1
        elif d_ax is not None and d_ax in flat:
            n_data += 1
        else:
            n_repl += 1
    stats = {
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "mode": mode,
        "fsdp": mode == "fsdp",
        "params_total": len(list(params)),
        "params_model_sharded": n_model,
        "params_data_sharded": n_data,
        "params_replicated": n_repl,
    }
    _prof.set_gauge("sharding.params_total", stats["params_total"],
                    "sharding")
    _prof.set_gauge("sharding.params_model_sharded", n_model, "sharding")
    _prof.set_gauge("sharding.params_data_sharded", n_data, "sharding")
    _prof.set_gauge("sharding.params_replicated", n_repl, "sharding")
    _prof.set_gauge("sharding.fsdp", int(mode == "fsdp"), "sharding")
    if mesh is not None:
        dev0 = np.ravel(np.asarray(mesh.devices, dtype=object))[0]
        pb = _bytes_on_device(raws, dev0)
        stats["param_bytes_per_device"] = pb
        _prof.set_gauge("sharding.param_bytes_per_device", pb, "sharding")
        if states is not None:
            import jax
            sb = _bytes_on_device(
                [leaf for leaf in jax.tree_util.tree_leaves(states)], dev0)
            stats["state_bytes_per_device"] = sb
            _prof.set_gauge("sharding.state_bytes_per_device", sb,
                            "sharding")
    _LAST.clear()
    _LAST.update(stats)
    return stats


def summary() -> dict:
    """The last published layout (mesh shape, mode, spec counts,
    per-device bytes) — what bench.py records as `extra.sharding`."""
    return dict(_LAST)
