"""Tensor (Megatron-style) parallelism via GSPMD sharding annotations.

The reference has no TP (its model parallelism is ps-lite placement); the
TPU-native design gets TP "for free" from XLA: annotate each weight's
PartitionSpec (Parameter._sharding, consumed by FusedTrainStep /
pjit in_shardings) and GSPMD partitions the GEMMs and inserts the
all-reduces over the `tp` ICI axis — the f/g collectives of Megatron,
derived by the compiler instead of hand-written.

Convention for gluon Dense (weight shape = (units, in_units)):
  column-parallel: split the output dim  -> P(tp, None), bias P(tp)
  row-parallel:    split the input dim   -> P(None, tp), bias P() (replicated)
A column->row pair (e.g. ffn_1 -> ffn_2, qkv -> proj) needs exactly one
all-reduce at the pair's end, which XLA places automatically.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from . import sharding as _sharding

__all__ = ["column_parallel", "row_parallel", "annotate_bert_tp",
           "annotate_ffn_tp"]


def _model_axis(axis):
    """axis=None resolves through the shared mesh registry: the global
    mesh's model axis when one is set, else the LOGICAL 'model' name —
    which the rule table maps to mp/tp at build, so annotations written
    without a mesh still land on whatever mesh the run registers."""
    if axis is not None:
        return axis
    return _sharding.model_axis() or "model"


def column_parallel(dense, axis: str | None = None):
    """Split a gluon Dense over its output (units) dim. axis=None uses
    the registry's model axis (see _model_axis)."""
    axis = _model_axis(axis)
    dense.weight._sharding = P(axis, None)
    if dense.bias is not None:
        dense.bias._sharding = P(axis)
    return dense


def row_parallel(dense, axis: str | None = None):
    """Split a gluon Dense over its input dim; output is partial-summed by an
    XLA all-reduce. axis=None uses the registry's model axis."""
    axis = _model_axis(axis)
    dense.weight._sharding = P(None, axis)
    if dense.bias is not None:
        dense.bias._sharding = P()
    return dense


def annotate_ffn_tp(ffn, axis: str | None = None):
    """PositionwiseFFN: ffn_1 column-parallel, ffn_2 row-parallel."""
    axis = _model_axis(axis)
    column_parallel(ffn.ffn_1, axis)
    row_parallel(ffn.ffn_2, axis)
    return ffn


def annotate_bert_tp(bert_model, axis: str | None = None):
    """Annotate a models.bert.BERTModel for tensor parallelism.

    Per encoder cell: fused qkv column-parallel (heads split over tp), output
    proj row-parallel, FFN column->row. Embeddings: vocab dim split (the
    gather's all-reduce is inserted by XLA). LayerNorms stay replicated.
    """
    axis = _model_axis(axis)
    bert_model.word_embed.weight._sharding = P(axis, None)
    for cell in bert_model.encoder.cells:
        column_parallel(cell.attention.qkv, axis)
        row_parallel(cell.attention.proj, axis)
        annotate_ffn_tp(cell.ffn, axis)
    return bert_model
