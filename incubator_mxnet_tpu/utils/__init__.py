"""Aux runtime utilities (SURVEY.md §2.25-26): profiler, checkpoint helpers
re-exported from module/, misc device info."""
from . import profiler
from ..module import save_checkpoint, load_checkpoint

__all__ = ["profiler", "save_checkpoint", "load_checkpoint"]
