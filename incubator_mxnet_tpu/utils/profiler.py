"""Profiler (parity: python/mxnet/profiler.py — set_config / set_state /
scope / dump / dumps, op-level timing, memory stats).

Two layers:
- Op/scope timing: a hook on the ndarray `_apply` funnel times each eager op
  (synchronizing on the outputs, so times are device-compute times, not
  dispatch times) and `scope(name)` times user regions. `dumps()` prints the
  reference-style aggregate table; `dump()` writes a Chrome trace JSON.
- Device view: `device_memory_stats()` surfaces the XLA allocator counters
  (the reference's GPU memory profile equivalent), and `set_config(
  profile_xla=True)` additionally drives `jax.profiler` for a full XLA/TPU
  trace viewable in TensorBoard/Perfetto.
"""
from __future__ import annotations

import json
import time
from contextlib import contextmanager

import jax

from .. import ndarray as _nd

__all__ = ["set_config", "set_state", "pause", "resume", "scope", "dump",
           "dumps", "reset", "device_memory_stats"]

_config = {"filename": "profile.json", "aggregate_stats": True,
           "profile_xla": False, "xla_logdir": "/tmp/mxtpu_xla_trace"}
_state = {"running": False, "paused": False}
_records: list[dict] = []
_t0 = time.perf_counter()


def set_config(**kwargs):
    """set_config(filename=..., aggregate_stats=..., profile_xla=...).
    Unknown reference kwargs (profile_symbolic etc.) are accepted and
    ignored — everything here runs through the same eager/jit funnel."""
    for k, v in kwargs.items():
        if k in _config:
            _config[k] = v


def _op_hook(fn, raws, name):
    if any(isinstance(r, jax.core.Tracer) for r in raws):
        # inside a jit/eval_shape trace of a hybridized block: not a device
        # execution, don't record (times would be Python tracing time)
        return fn(*raws)
    start = time.perf_counter()
    outs = fn(*raws)
    jax.block_until_ready(outs)
    dur = time.perf_counter() - start
    _records.append({"name": name or getattr(fn, "__name__", "op"),
                     "cat": "operator",
                     "ts": (start - _t0) * 1e6, "dur": dur * 1e6})
    return outs


def set_state(state="stop"):
    """'run' starts collection (installs the op hook), 'stop' ends it.
    Idempotent: repeating the current state is a no-op."""
    assert state in ("run", "stop")
    was_running = _state["running"]
    _state["running"] = state == "run"
    _state["paused"] = False
    _nd._op_hook = _op_hook if _state["running"] else None
    if _config["profile_xla"] and was_running != _state["running"]:
        if state == "run":
            jax.profiler.start_trace(_config["xla_logdir"])
        else:
            try:
                jax.profiler.stop_trace()
            except RuntimeError:
                pass


def pause():
    if _state["running"]:
        _state["paused"] = True
        _nd._op_hook = None


def resume():
    if _state["running"]:
        _state["paused"] = False
        _nd._op_hook = _op_hook


@contextmanager
def scope(name="<unk>"):
    """Time a user region (reference: profiler scopes / frame markers).
    Free when profiling is off: no sync, no record — scopes can stay in
    production training loops."""
    active = _state["running"] and not _state["paused"]
    start = time.perf_counter()
    try:
        yield
    finally:
        if active:
            _nd.waitall()
            dur = time.perf_counter() - start
            _records.append({"name": name, "cat": "scope",
                             "ts": (start - _t0) * 1e6, "dur": dur * 1e6})


def reset():
    _records.clear()


def dump(finished=True):
    """Write a Chrome trace-event JSON to `filename`."""
    events = [{"name": r["name"], "cat": r["cat"], "ph": "X", "pid": 0,
               "tid": 0, "ts": r["ts"], "dur": r["dur"]} for r in _records]
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": events}, f)


def dumps(reset=False):
    """Aggregate-stats table (reference `profiler.dumps()` format)."""
    agg: dict[str, list[float]] = {}
    for r in _records:
        agg.setdefault(r["name"], []).append(r["dur"])
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(us)':>12}"
             f"{'Max(us)':>12}"]
    for name, durs in sorted(agg.items(), key=lambda kv: -sum(kv[1])):
        lines.append(f"{name[:39]:<40}{len(durs):>8}"
                     f"{sum(durs) / 1e3:>12.3f}"
                     f"{sum(durs) / len(durs):>12.1f}"
                     f"{max(durs):>12.1f}")
    out = "\n".join(lines)
    if reset:
        _records.clear()
    return out


def device_memory_stats(device=None):
    """XLA allocator counters for a device (bytes_in_use, peak_bytes_in_use,
    ...). Reference analogue: gpu memory profile / storage stats."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats()
    return dict(stats) if stats else {}
