"""Back-compat shim: the profiler grew into the
:mod:`incubator_mxnet_tpu.profiler` subsystem. `utils.profiler` stays
importable and IS that module (one code path, one state)."""
import sys as _sys

from .. import profiler as _profiler

_sys.modules[__name__] = _profiler
