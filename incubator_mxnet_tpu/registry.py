"""Generic class registries (reference parity: python/mxnet/registry.py).

The reference builds per-kind register/alias/create functions that
optimizer/initializer/metric wire up; here those subsystems each own a
`base._Registry`, and this module exposes the same factory surface over
them for user code that extends the framework.
"""
from __future__ import annotations

import json

from .base import _Registry

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_KINDS: dict[tuple[type, str], _Registry] = {}


def _builtin_registry(base_class, nickname):
    """The subsystem registry for a known kind — only when base_class is
    that subsystem's own base, so an unrelated class with a colliding
    nickname gets its own registry."""
    if nickname == "optimizer":
        from . import optimizer as _m
        if base_class is _m.Optimizer:
            return _m.registry
    elif nickname == "initializer":
        from . import initializer as _m
        if base_class is _m.Initializer:
            return _m.registry
    elif nickname == "metric":
        from . import metric as _m
        if base_class is _m.EvalMetric:
            return _m.registry
    return None


def _registry_for(base_class, nickname):
    key = (base_class, nickname)
    reg = _KINDS.get(key)
    if reg is None:
        # known kinds share state with their subsystem's registry, like the
        # reference where mx.registry factories back the built-in ones
        reg = _builtin_registry(base_class, nickname) or _Registry(nickname)
        _KINDS[key] = reg
    return reg


def get_register_func(base_class, nickname):
    """Return register(cls, name=None) for the kind (reference
    registry.get_register_func)."""
    reg = _registry_for(base_class, nickname)

    def register(klass, name=None):
        assert issubclass(klass, base_class), (
            f"can only register subclasses of {base_class.__name__}")
        reg.register(name or klass.__name__)(klass)
        return klass

    register.__name__ = f"register_{nickname}"
    return register


def get_alias_func(base_class, nickname):
    """Return alias(*aliases) decorator (reference registry.get_alias_func)."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg

    alias.__name__ = f"alias_{nickname}"
    return alias


def get_create_func(base_class, nickname):
    """Return create(name_or_instance, **kwargs) (reference
    registry.get_create_func). Accepts an instance, a name, or the
    reference's json string form '{"name": ..., "params": {...}}'."""
    reg = _registry_for(base_class, nickname)

    def create(*args, **kwargs):
        if args and isinstance(args[0], str):
            if args[0].startswith("{"):
                spec = json.loads(args[0])
                return reg.create(spec["name"], **spec.get("params", {}))
            return reg.create(args[0], *args[1:], **kwargs)
        if args and isinstance(args[0], base_class):
            assert not kwargs and len(args) == 1
            return args[0]
        if nickname not in kwargs:
            raise ValueError(
                f"create_{nickname} needs a name: pass a registered name, "
                f"a json spec string, an instance, or {nickname}=<name>")
        return reg.create(kwargs.pop(nickname), **kwargs)

    create.__name__ = f"create_{nickname}"
    return create
