"""AMP — automatic mixed precision (parity: python/mxnet/contrib/amp/amp.py).

TPU-native stance: bf16 is the native MXU dtype and has fp32's exponent
range, so the default `target_dtype='bfloat16'` usually needs NO loss
scaling — `net.cast('bfloat16')` + a multi_precision optimizer is the whole
recipe, and norm statistics stay f32 inside the norm kernels (ops/_raw.py).
The fp16-style loss-scaling machinery (static + dynamic with overflow
backoff — the reference's 'race/fault guard' of mixed precision,
SURVEY.md §5) is provided for API parity and for fp16 checkpoints.

Usage (reference API):
    amp.init()                       # set default target dtype
    net.cast(amp.target_dtype())     # bf16/fp16 params + compute
    trainer = gluon.Trainer(..., optimizer_params={'multi_precision': True})
    amp.init_trainer(trainer)        # attach dynamic loss scaler
    with autograd.record():
        loss = L(net(x), y)
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
    trainer.step(batch)              # unscales; skips + backs off on overflow
"""
from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp
import numpy as np

__all__ = ["init", "target_dtype", "init_trainer", "scale_loss",
           "LossScaler", "DynamicLossScaler", "unscale"]

_state = {"initialized": False, "target_dtype": "bfloat16"}


def init(target_dtype="bfloat16"):
    """Enable AMP defaults. bfloat16 (TPU-native) or float16."""
    assert target_dtype in ("bfloat16", "float16")
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype


def target_dtype():
    return _state["target_dtype"]


class LossScaler:
    """Static loss scale."""

    def __init__(self, init_scale=2.0 ** 10):
        self.loss_scale = float(init_scale)

    def update(self, overflow: bool):
        pass


class DynamicLossScaler(LossScaler):
    """Dynamic scaling: halve on overflow (and skip the update), double
    after `growth_interval` clean steps — the reference's overflow-detection
    guard.

    The scale and the clean-step counter live ON DEVICE: the per-step
    found-inf decision never syncs the host (VERDICT r1 weak #6). The
    optimizer applies a `jnp.where(found_inf, old, new)` select inside its
    compiled update, and `_device_update` advances (scale, counter) in the
    same async stream. Reading `.loss_scale` (user inspection) is the only
    sync point."""

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000):
        super().__init__(init_scale)
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self._unskipped = 0
        self._scale_dev = None      # lazily device-resident (f32, i32)
        self._unskipped_dev = None

    # -- host API (parity + tests) ---------------------------------------
    @property
    def loss_scale(self):
        if self._scale_dev is not None:
            return float(np.asarray(self._scale_dev))
        return self._loss_scale_host

    @loss_scale.setter
    def loss_scale(self, v):
        self._loss_scale_host = float(v)
        if getattr(self, "_scale_dev", None) is not None:
            self._scale_dev = jnp.float32(v)

    def update(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale * self.backoff_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.growth_interval:
                self.loss_scale *= self.growth_factor
                self._unskipped = 0
        if self._scale_dev is not None:
            self._scale_dev = jnp.float32(self._loss_scale_host)
            self._unskipped_dev = jnp.int32(self._unskipped)

    # -- device path ------------------------------------------------------
    def _ensure_device(self):
        if self._scale_dev is None:
            self._scale_dev = jnp.float32(self.loss_scale)
            self._unskipped_dev = jnp.int32(self._unskipped)

    def _device_update(self, finite):
        """scale/counter transition as one tiny jitted computation riding
        the async dispatch stream — no host round-trip."""
        import jax

        def trans(scale, unskipped, fin):
            grown = unskipped + 1 >= self.growth_interval
            new_scale = jnp.where(
                fin,
                jnp.where(grown, scale * self.growth_factor, scale),
                jnp.maximum(scale * self.backoff_factor, 1.0))
            new_unskipped = jnp.where(
                fin, jnp.where(grown, 0, unskipped + 1), 0)
            return new_scale, new_unskipped

        key = (self.growth_factor, self.backoff_factor, self.growth_interval)
        fn = _scaler_jits.get(key)
        if fn is None:
            fn = jax.jit(trans)
            _scaler_jits[key] = fn
        self._scale_dev, self._unskipped_dev = fn(
            self._scale_dev, self._unskipped_dev, finite)


_scaler_jits = {}
_finite_fns = {}


def _grads_finite_device(params):
    """One fused finiteness kernel over every gradient; returns the
    ON-DEVICE bool (no host fetch — callers thread it into the compiled
    optimizer select). Stale/missing grads are skipped, matching
    ignore_stale_grad."""
    import jax
    grads = []
    for p in params:
        try:
            g = p.grad()
        except RuntimeError:        # no gradient this step (stale/unused)
            continue
        grads.append(g._data)
    if not grads:
        return jnp.bool_(True)
    key = tuple((g.shape, str(g.dtype)) for g in grads)
    fn = _finite_fns.get(key)
    if fn is None:
        fn = jax.jit(lambda gs: jnp.all(jnp.stack(
            [jnp.isfinite(jnp.sum(g.astype(jnp.float32))) for g in gs])))
        _finite_fns[key] = fn
    return fn(grads)


def _grads_finite(params) -> bool:
    return bool(np.asarray(_grads_finite_device(params)))


def init_trainer(trainer, scaler: LossScaler | None = None):
    """Attach a loss scaler and wrap trainer.step with unscale + overflow
    skip/backoff (the reference patches the trainer the same way).

    With a DynamicLossScaler the whole sequence — found-inf check, skip-on-
    overflow, scale backoff/growth — executes on device; python never
    blocks on the flag."""
    scaler = scaler or DynamicLossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_unscaled = False

    dynamic = isinstance(scaler, DynamicLossScaler)

    def wrap(orig):
        def amp_call(batch_size, ignore_stale_grad=False):
            if dynamic:
                scaler._ensure_device()
                finite = _grads_finite_device(trainer._params)
                already = trainer._amp_unscaled
                trainer._amp_skip = jnp.logical_not(finite)
                trainer._scale = (jnp.float32(1.0) if already
                                  else 1.0 / scaler._scale_dev)
                try:
                    orig(batch_size, ignore_stale_grad)
                finally:
                    trainer._scale = 1.0
                    trainer._amp_skip = None
                trainer._amp_unscaled = False
                scaler._device_update(finite)
                return
            overflow = not _grads_finite(trainer._params)
            if not overflow:
                already = trainer._amp_unscaled  # amp.unscale() ran this step
                trainer._scale = 1.0 if already else 1.0 / scaler.loss_scale
                try:
                    orig(batch_size, ignore_stale_grad)
                finally:
                    trainer._scale = 1.0
            trainer._amp_unscaled = False
            scaler.update(overflow)
        return amp_call

    trainer.step = wrap(trainer.step)
    trainer.update = wrap(trainer.update)
    return trainer


@contextmanager
def scale_loss(loss, trainer):
    """Yield `loss * scale`; trainer.step (wrapped by init_trainer) divides
    gradients back by the scale."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise ValueError("call amp.init_trainer(trainer) first")
    # use the device-resident scale when present — no host sync per step
    scale = getattr(scaler, "_scale_dev", None)
    if scale is None:
        scale = scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield type(loss)(l * scale for l in loss)
    else:
        yield loss * scale


def unscale(trainer):
    """Explicitly divide the current grads by the loss scale (for grad
    clipping between backward and step, reference amp.unscale). The
    following trainer.step()/update() skips its own unscale; the scaler's
    scale/state are untouched."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise ValueError("call amp.init_trainer(trainer) first")
    scale = getattr(scaler, "_scale_dev", None)
    inv = (1.0 / scale) if scale is not None else (1.0 / scaler.loss_scale)
    for p in trainer._params:
        try:
            g = p.grad()
        except RuntimeError:        # no gradient this step
            continue
        g._data = (g._data.astype(jnp.float32) * inv).astype(g._data.dtype)
    trainer._amp_unscaled = True
