"""AMP — automatic mixed precision (parity: python/mxnet/contrib/amp/amp.py).

TPU-native stance: bf16 is the native MXU dtype and has fp32's exponent
range, so the default `target_dtype='bfloat16'` usually needs NO loss
scaling — `net.cast('bfloat16')` + a multi_precision optimizer is the whole
recipe, and norm statistics stay f32 inside the norm kernels (ops/_raw.py).
The fp16-style loss-scaling machinery (static + dynamic with overflow
backoff — the reference's 'race/fault guard' of mixed precision,
SURVEY.md §5) is provided for API parity and for fp16 checkpoints.

Usage (reference API):
    amp.init()                       # set default target dtype
    net.cast(amp.target_dtype())     # bf16/fp16 params + compute
    trainer = gluon.Trainer(..., optimizer_params={'multi_precision': True})
    amp.init_trainer(trainer)        # attach dynamic loss scaler
    with autograd.record():
        loss = L(net(x), y)
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
    trainer.step(batch)              # unscales; skips + backs off on overflow
"""
from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp
import numpy as np

__all__ = ["init", "target_dtype", "init_trainer", "scale_loss",
           "LossScaler", "DynamicLossScaler", "unscale"]

_state = {"initialized": False, "target_dtype": "bfloat16"}


def init(target_dtype="bfloat16"):
    """Enable AMP defaults. bfloat16 (TPU-native) or float16."""
    assert target_dtype in ("bfloat16", "float16")
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype


def target_dtype():
    return _state["target_dtype"]


class LossScaler:
    """Static loss scale."""

    def __init__(self, init_scale=2.0 ** 10):
        self.loss_scale = float(init_scale)

    def update(self, overflow: bool):
        pass


class DynamicLossScaler(LossScaler):
    """Dynamic scaling: halve on overflow (and skip the update), double
    after `growth_interval` clean steps — the reference's overflow-detection
    guard."""

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000):
        super().__init__(init_scale)
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self._unskipped = 0

    def update(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale * self.backoff_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.growth_interval:
                self.loss_scale *= self.growth_factor
                self._unskipped = 0


_finite_fns = {}


def _grads_finite(params) -> bool:
    """One fused finiteness kernel over every gradient, one host fetch —
    the unavoidable found-inf sync of dynamic loss scaling (stale/missing
    grads are skipped, matching ignore_stale_grad)."""
    import jax
    grads = []
    for p in params:
        try:
            g = p.grad()
        except RuntimeError:        # no gradient this step (stale/unused)
            continue
        grads.append(g._data)
    if not grads:
        return True
    key = tuple((g.shape, str(g.dtype)) for g in grads)
    fn = _finite_fns.get(key)
    if fn is None:
        fn = jax.jit(lambda gs: jnp.all(jnp.stack(
            [jnp.isfinite(jnp.sum(g.astype(jnp.float32))) for g in gs])))
        _finite_fns[key] = fn
    return bool(np.asarray(fn(grads)))


def init_trainer(trainer, scaler: LossScaler | None = None):
    """Attach a loss scaler and wrap trainer.step with unscale + overflow
    skip/backoff (the reference patches the trainer the same way)."""
    scaler = scaler or DynamicLossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_unscaled = False

    def wrap(orig):
        def amp_call(batch_size, ignore_stale_grad=False):
            overflow = not _grads_finite(trainer._params)
            if not overflow:
                already = trainer._amp_unscaled  # amp.unscale() ran this step
                trainer._scale = 1.0 if already else 1.0 / scaler.loss_scale
                try:
                    orig(batch_size, ignore_stale_grad)
                finally:
                    trainer._scale = 1.0
            trainer._amp_unscaled = False
            scaler.update(overflow)
        return amp_call

    trainer.step = wrap(trainer.step)
    trainer.update = wrap(trainer.update)
    return trainer


@contextmanager
def scale_loss(loss, trainer):
    """Yield `loss * scale`; trainer.step (wrapped by init_trainer) divides
    gradients back by the scale."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise ValueError("call amp.init_trainer(trainer) first")
    if isinstance(loss, (list, tuple)):
        yield type(loss)(l * scaler.loss_scale for l in loss)
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Explicitly divide the current grads by the loss scale (for grad
    clipping between backward and step, reference amp.unscale). The
    following trainer.step()/update() skips its own unscale; the scaler's
    scale/state are untouched."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise ValueError("call amp.init_trainer(trainer) first")
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        try:
            g = p.grad()
        except RuntimeError:        # no gradient this step
            continue
        g._data = (g._data.astype(jnp.float32) * inv).astype(g._data.dtype)
    trainer._amp_unscaled = True
