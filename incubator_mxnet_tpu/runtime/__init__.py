"""Native runtime bindings (SURVEY.md §2.27): C++ threaded dependency
engine, pooled host-storage allocator, bounded prefetch queue — the rebuild
of the reference's src/engine + src/storage + src/io prefetcher for
host-side work (device compute is scheduled by XLA's async dispatch).

The .so is built on first import with g++ (no pybind11 — plain C API via
ctypes). If the toolchain is unavailable everything degrades to functional
pure-Python equivalents, so the framework never hard-depends on the native
layer. `native_available()` reports which path is live.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections import deque

from .features import Feature, Features, feature_list

__all__ = ["Engine", "StoragePool", "TokenQueue", "native_available",
           "get_engine", "engine_type", "Feature", "Features",
           "feature_list"]

_DIR = os.path.dirname(os.path.abspath(__file__))


def _so_dir():
    """Directory for first-use-compiled .so files: the package dir when
    writable (source checkouts — keeps the artifact next to its source),
    else a user cache dir (read-only site-packages installs must not
    silently lose the native engine)."""
    if os.access(_DIR, os.W_OK):
        return _DIR
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "incubator_mxnet_tpu")
    os.makedirs(cache, exist_ok=True)
    return cache


def _so_path(stem, src_name):
    """Cache artifact path keyed by a hash of the C++ source: a cached .so
    surviving a package upgrade (the user-cache dir outlives read-only
    site-packages installs) must never be loaded against newer source with
    a changed ABI — the hash suffix makes version skew a cache miss, not a
    crash. Stale siblings from older sources are removed opportunistically."""
    import hashlib
    try:
        with open(os.path.join(_DIR, "src", src_name), "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:12]
    except OSError:
        return os.path.join(_so_dir(), f"{stem}.so")
    d = _so_dir()
    path = os.path.join(d, f"{stem}.{tag}.so")
    try:
        import re
        for fn in os.listdir(d):
            # only hash-suffixed siblings: a plain <stem>.so may be a
            # developer's deliberate Makefile artifact, not our cache
            if re.fullmatch(re.escape(stem) + r"\.[0-9a-f]{12}\.so", fn) \
                    and os.path.join(d, fn) != path:
                os.unlink(os.path.join(d, fn))
    except OSError:
        pass
    return path


_SO = _so_path("libmxtpu_runtime", "runtime.cc")
_lib = None
_build_failed = False
_build_lock = threading.Lock()


def _build_so(src_name, so_path, extra_flags=()):
    """First-use g++ build of a native component: compiles to a pid-unique
    temp file and os.replace()s it into place (atomic on POSIX), so
    concurrent importers (pytest-xdist, DataLoader workers) never observe
    a partially written .so. Returns the loaded CDLL or None.

    Two passes: a concurrent process sharing the cache dir (e.g. a
    different package version doing its stale-sibling cleanup) can unlink
    the artifact between our exists() check and CDLL load — rebuild once
    instead of permanently disabling the native engine."""
    for _ in range(2):
        if not os.path.exists(so_path):
            src = os.path.join(_DIR, "src", src_name)
            tmp = f"{so_path}.tmp.{os.getpid()}"
            try:
                subprocess.run(["g++", "-O2", "-std=c++17", "-fPIC",
                                "-shared", "-o", tmp, src, *extra_flags],
                               check=True, capture_output=True, timeout=120)
                os.replace(tmp, so_path)
            except Exception:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return None
        try:
            return ctypes.CDLL(so_path)
        except OSError:
            try:
                os.unlink(so_path)   # corrupt or raced away: rebuild
            except OSError:
                pass
    return None


def _build_and_load():
    """Native engine load, guarded by a double-checked lock."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _build_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        lib = _build_so("runtime.cc", _SO, ("-pthread",))
        if lib is None:
            _build_failed = True
            return None
        return _register_and_set(lib)


def _register_and_set(lib):
    global _lib
    lib.mxtpu_engine_create.restype = ctypes.c_void_p
    lib.mxtpu_engine_create.argtypes = [ctypes.c_int]
    lib.mxtpu_engine_destroy.argtypes = [ctypes.c_void_p]
    lib.mxtpu_engine_new_var.restype = ctypes.c_int64
    lib.mxtpu_engine_new_var.argtypes = [ctypes.c_void_p]
    lib.mxtpu_engine_push.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.mxtpu_engine_wait_for_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxtpu_engine_wait_all.argtypes = [ctypes.c_void_p]
    lib.mxtpu_pool_create.restype = ctypes.c_void_p
    lib.mxtpu_pool_destroy.argtypes = [ctypes.c_void_p]
    lib.mxtpu_pool_alloc.restype = ctypes.c_void_p
    lib.mxtpu_pool_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.mxtpu_pool_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.mxtpu_pool_stats.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_size_t),
                                     ctypes.POINTER(ctypes.c_size_t)]
    lib.mxtpu_queue_create.restype = ctypes.c_void_p
    lib.mxtpu_queue_create.argtypes = [ctypes.c_size_t]
    lib.mxtpu_queue_destroy.argtypes = [ctypes.c_void_p]
    lib.mxtpu_queue_push.restype = ctypes.c_int
    lib.mxtpu_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.mxtpu_queue_pop.restype = ctypes.c_int
    lib.mxtpu_queue_pop.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint64)]
    lib.mxtpu_queue_close.argtypes = [ctypes.c_void_p]
    lib.mxtpu_queue_size.restype = ctypes.c_size_t
    lib.mxtpu_queue_size.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


_OP_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_ENGINE_ENV = "MXTPU_ENGINE"


def native_available() -> bool:
    return _build_and_load() is not None


# ---------------------------------------------------------------------------
# dependency engine
# ---------------------------------------------------------------------------

class Engine:
    """MXNet-style dependency engine: `push(fn, const_vars, mutable_vars)`
    runs fn on a worker thread once all its var deps resolve (concurrent
    reads, exclusive writes, program order per var)."""

    def __init__(self, num_threads=None, force_python=False):
        num_threads = num_threads or max(2, (os.cpu_count() or 4) // 2)
        self._lib = None if force_python else _build_and_load()
        self._callbacks = {}          # op id -> (fn, vars) until it runs
        self._cb_lock = threading.Lock()
        self._cb_id = 0
        self._errors = []             # [(exc, frozenset(vars))] until raised
        if self._lib is not None:
            # ONE persistent trampoline for all ops: the C side passes the
            # op id as arg, so no per-op CFUNCTYPE object ever gets freed
            # while a worker thread is inside it
            self._dispatch = _OP_FN(self._run_cb)
            self._h = self._lib.mxtpu_engine_create(num_threads)
        else:
            self._py = _PyEngine(num_threads)

    def _run_cb(self, arg):
        cid = int(arg) if arg is not None else 0
        with self._cb_lock:
            ent = self._callbacks.pop(cid, None)
        if ent is None:
            return
        fn, op_vars = ent
        try:
            fn()
        except BaseException as e:  # noqa: BLE001
            # an exception must not escape into the ctypes trampoline (it
            # would be printed and dropped); stash it and re-raise at the
            # next wait_for_var/wait_all — reference engine error semantics
            with self._cb_lock:
                self._errors.append((e, op_vars))

    def _raise_pending(self, var=None):
        with self._cb_lock:
            if not self._errors:
                return
            if var is None:
                exc, _ = self._errors.pop(0)
            else:
                hit = next((i for i, (_, vs) in enumerate(self._errors)
                            if var in vs), None)
                if hit is None:
                    return
                exc, _ = self._errors.pop(hit)
        raise exc

    def new_var(self) -> int:
        if self._lib is not None:
            if not self._h:
                return -1  # destroyed (GC finalization order)
            return self._lib.mxtpu_engine_new_var(self._h)
        return self._py.new_var()

    def push(self, fn, const_vars=(), mutable_vars=()):
        if self._lib is None:
            self._py.push(fn, const_vars, mutable_vars)
            return
        if not self._h:
            return  # destroyed (GC finalization order)
        with self._cb_lock:
            self._cb_id += 1
            cid = self._cb_id
            self._callbacks[cid] = (
                fn, frozenset(const_vars) | frozenset(mutable_vars))
        cv = (ctypes.c_int64 * max(1, len(const_vars)))(*const_vars)
        mv = (ctypes.c_int64 * max(1, len(mutable_vars)))(*mutable_vars)
        self._lib.mxtpu_engine_push(
            self._h, ctypes.cast(self._dispatch, ctypes.c_void_p),
            ctypes.c_void_p(cid),
            cv, len(const_vars), mv, len(mutable_vars))

    def wait_for_var(self, var: int):
        if self._lib is not None:
            if not self._h:
                self._raise_pending(var)  # still surface stashed errors
                return
            self._lib.mxtpu_engine_wait_for_var(self._h, var)
            self._raise_pending(var)
        else:
            self._py.wait_for_var(var)

    def wait_all(self):
        if self._lib is not None:
            if not self._h:
                self._raise_pending()  # still surface stashed errors
                return
            self._lib.mxtpu_engine_wait_all(self._h)
            self._raise_pending()
        else:
            self._py.wait_all()

    def __del__(self):
        if getattr(self, "_lib", None) is not None and \
                getattr(self, "_h", None):
            try:
                self._lib.mxtpu_engine_destroy(self._h)
            except Exception:
                pass
            self._h = None


class _PyEngine:
    """Pure-Python fallback with the same semantics (GIL-bound):
    reads of a var run concurrently after the last write; a write waits for
    the last write AND all reads issued since it."""

    def __init__(self, num_threads):
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(num_threads)
        self._lock = threading.Lock()
        self._last_write = {}         # var -> future of last write
        self._readers = {}            # var -> futures reading since last write
        self._next = 1
        self._futures = set()

    def new_var(self):
        with self._lock:
            v = self._next
            self._next += 1
            return v

    def push(self, fn, const_vars=(), mutable_vars=()):
        with self._lock:
            deps = []
            for v in const_vars:
                d = self._last_write.get(v)
                if d is not None:
                    deps.append(d)
            for v in mutable_vars:
                d = self._last_write.get(v)
                if d is not None:
                    deps.append(d)
                deps.extend(self._readers.get(v, ()))

            def run():
                for d in deps:
                    d.result()
                fn()

            fut = self._pool.submit(run)
            self._futures.add(fut)
            fut.add_done_callback(lambda f: self._futures.discard(f))
            for v in const_vars:
                self._readers.setdefault(v, []).append(fut)
            for v in mutable_vars:
                self._last_write[v] = fut
                self._readers[v] = []

    def wait_for_var(self, var):
        with self._lock:
            futs = [self._last_write.get(var)] + \
                list(self._readers.get(var, ()))
        for fut in futs:
            if fut is not None:
                fut.result()

    def wait_all(self):
        for fut in list(self._futures):
            fut.result()


_global_engine = None
_global_lock = threading.Lock()


def engine_type() -> str:
    """'native' (C++ threaded engine) unless MXTPU_ENGINE=python or the
    toolchain is unavailable."""
    from ..autotune.knobs import env_str
    if env_str(_ENGINE_ENV, "native") == "python" or \
            not native_available():
        return "python"
    return "native"


def get_engine() -> Engine:
    """Process-wide engine singleton, honoring MXTPU_ENGINE."""
    global _global_engine
    with _global_lock:
        if _global_engine is None:
            _global_engine = Engine(force_python=engine_type() == "python")
        return _global_engine


# ---------------------------------------------------------------------------
# pooled storage
# ---------------------------------------------------------------------------

class StoragePool:
    """Size-bucketed host buffer pool (reference pooled_storage_manager).
    alloc() returns a ctypes void_p usable as a staging buffer; free()
    returns it to the pool rather than the OS."""

    def __init__(self):
        self._lib = _build_and_load()
        if self._lib is not None:
            self._h = self._lib.mxtpu_pool_create()
        else:
            self._buckets = {}
            self._live = {}
            self._used = 0
            self._pooled = 0
            self._plock = threading.Lock()

    @staticmethod
    def _round(size):
        b = 256
        while b < size:
            b <<= 1
        return b

    def alloc(self, size):
        if self._lib is not None:
            if not self._h:
                return None  # destroyed (GC finalization order)
            return self._lib.mxtpu_pool_alloc(self._h, size)
        b = self._round(size)
        with self._plock:
            lst = self._buckets.get(b)
            if lst:
                buf = lst.pop()
                self._pooled -= b
            else:
                buf = ctypes.create_string_buffer(b)
            addr = ctypes.addressof(buf)
            self._live[addr] = (buf, b)
            self._used += b
            return addr

    def free(self, ptr):
        if self._lib is not None:
            if self._h:
                self._lib.mxtpu_pool_free(self._h, ptr)
            return
        with self._plock:
            ent = self._live.pop(ptr, None)
            if ent is None:
                return
            buf, b = ent
            self._buckets.setdefault(b, []).append(buf)
            self._used -= b
            self._pooled += b

    def stats(self):
        if self._lib is not None:
            if not self._h:
                return {"bytes_in_use": 0, "bytes_pooled": 0}
            used = ctypes.c_size_t()
            pooled = ctypes.c_size_t()
            self._lib.mxtpu_pool_stats(self._h, ctypes.byref(used),
                                       ctypes.byref(pooled))
            return {"bytes_in_use": used.value, "bytes_pooled": pooled.value}
        with self._plock:
            return {"bytes_in_use": self._used, "bytes_pooled": self._pooled}

    def __del__(self):
        if getattr(self, "_lib", None) is not None and \
                getattr(self, "_h", None):
            try:
                self._lib.mxtpu_pool_destroy(self._h)
            except Exception:
                pass
            self._h = None


# ---------------------------------------------------------------------------
# bounded token queue (prefetch pipeline backbone)
# ---------------------------------------------------------------------------

class TokenQueue:
    """Bounded blocking queue of u64 tokens; C-side blocking releases the
    GIL, so producer threads in the native engine and the Python consumer
    overlap. push/pop return False after close()."""

    def __init__(self, capacity):
        self._lib = _build_and_load()
        if self._lib is not None:
            self._h = self._lib.mxtpu_queue_create(capacity)
        else:
            self._q = deque()
            self._cap = max(1, capacity)
            self._qlock = threading.Lock()
            self._not_full = threading.Condition(self._qlock)
            self._not_empty = threading.Condition(self._qlock)
            self._closed = False

    def push(self, token) -> bool:
        if self._lib is not None:
            if not self._h:
                return False  # destroyed (GC finalization order)
            return bool(self._lib.mxtpu_queue_push(self._h, token))
        with self._not_full:
            while not self._closed and len(self._q) >= self._cap:
                self._not_full.wait()
            if self._closed:
                return False
            self._q.append(token)
            self._not_empty.notify()
            return True

    def pop(self):
        """Returns token or None when closed+drained."""
        if self._lib is not None:
            if not self._h:
                return None  # destroyed (GC finalization order)
            tok = ctypes.c_uint64()
            ok = self._lib.mxtpu_queue_pop(self._h, ctypes.byref(tok))
            return tok.value if ok else None
        with self._not_empty:
            while not self._closed and not self._q:
                self._not_empty.wait()
            if not self._q:
                return None
            tok = self._q.popleft()
            self._not_full.notify()
            return tok

    def close(self):
        if self._lib is not None:
            # _h is None once __del__ ran: GC may finalize this queue
            # before an abandoned generator's finally calls close()
            if self._h:
                self._lib.mxtpu_queue_close(self._h)
            return
        with self._qlock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()

    def __len__(self):
        if self._lib is not None:
            if not self._h:
                return 0
            return self._lib.mxtpu_queue_size(self._h)
        with self._qlock:
            return len(self._q)

    def __del__(self):
        if getattr(self, "_lib", None) is not None and \
                getattr(self, "_h", None):
            try:
                self._lib.mxtpu_queue_destroy(self._h)
            except Exception:
                pass
            self._h = None


# ---------------------------------------------------------------------------
# native JPEG decode (src/imgdec.cc, its own .so linked against libjpeg):
# GIL-free decompression for the record-IO pipeline — the rebuild of the
# reference's opencv decode in src/io/iter_image_recordio_2.cc. Missing
# toolchain/libjpeg only disables this path; callers fall back to PIL.
# ---------------------------------------------------------------------------

_IMG_SO = _so_path("libmxtpu_imgdec", "imgdec.cc")
_img_lib = None
_img_build_failed = False
_img_lock = threading.Lock()


def _imgdec_lib():
    global _img_lib, _img_build_failed
    if _img_lib is not None:
        return _img_lib
    if _img_build_failed:
        return None
    with _img_lock:
        if _img_lib is not None or _img_build_failed:
            return _img_lib
        lib = _build_so("imgdec.cc", _IMG_SO, ("-ljpeg",))
        if lib is None:
            _img_build_failed = True
            return None
        lib.mxtpu_jpeg_info.restype = ctypes.c_int
        lib.mxtpu_jpeg_info.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int)]
        lib.mxtpu_jpeg_decode.restype = ctypes.c_int
        lib.mxtpu_jpeg_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int]
        lib.mxtpu_jpeg_decode_once.restype = ctypes.c_int
        lib.mxtpu_jpeg_decode_once.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        _img_lib = lib
        return lib


def jpeg_decode_available():
    """True when the native libjpeg decoder built and loaded."""
    return _imgdec_lib() is not None


# PIL's decompression-bomb threshold: the native path enforces the same
# cap so a crafted header can't trigger a multi-GB allocation
_MAX_IMAGE_PIXELS = 178956970


_scratch = threading.local()


def decode_jpeg(data, channels=3):
    """Decode JPEG bytes to an HWC uint8 numpy array via the native
    decoder (channels: 3=RGB, 1=grayscale via libjpeg's Y channel).
    Returns None when the native path is unavailable, the stream is
    corrupt/truncated, or the size exceeds the decompression-bomb cap —
    callers fall back to PIL.

    Hot path does ONE native call (single header parse) into a growable
    per-thread scratch buffer; the pixels are then copied out into an
    exact-size array (one memcpy, still far cheaper than a reparse)."""
    import numpy as _np
    lib = _imgdec_lib()
    if lib is None:
        return None
    data = bytes(data)
    buf = getattr(_scratch, "buf", None)
    if buf is None:
        buf = _scratch.buf = _np.empty(1 << 20, _np.uint8)  # 1 MiB start
    w = ctypes.c_int()
    h = ctypes.c_int()
    for _ in range(2):
        rc = lib.mxtpu_jpeg_decode_once(
            data, len(data), buf.ctypes.data_as(ctypes.c_void_p),
            buf.nbytes, channels, ctypes.byref(w), ctypes.byref(h))
        if rc == 0:
            break
        if rc < 0 or w.value * h.value > _MAX_IMAGE_PIXELS:
            return None
        buf = _scratch.buf = _np.empty(rc, _np.uint8)   # grow + retry
    else:
        return None
    n = w.value * h.value * channels
    return buf[:n].reshape(h.value, w.value, channels).copy()


__all__ += ["decode_jpeg", "jpeg_decode_available"]
