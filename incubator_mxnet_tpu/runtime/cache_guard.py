"""Persistent compile-cache integrity guard.

PR 4 found that this jaxlib's CPU backend can MIS-DESERIALIZE
persistent-compilation-cache entries for donated fused-train-step
executables: a process that re-reads executables written by a previous
process gets garbage numerics (1e19 → nan losses) with no error raised.
The fused-step test module was opted out of the cache wholesale; that
made tests safe but left production runs paying a full recompile every
process start — or worse, silently training on garbage when the cache
was enabled anyway.

This module is the re-entry path: a one-time-per-process CANARY that
exercises the exact failure shape (a donated, multi-output, scanned XLA
program) THROUGH the persistent cache and checks the result against its
analytic value. The canary uses dyadic constants (0.5/0.25) so every
intermediate is exact in float32 — the comparison is bitwise, not a
tolerance. On the first process start the canary compiles fresh and
WRITES its cache entry (cheap: a 4-step scan over an (8,128) tile); on
every later start the canary compile is a cache READ, so corrupt
deserialization shows up here — before the real train step compiles —
and the guard disables the persistent cache for the process (with a
warning and a `compile_cache.guard_tripped` counter) instead of letting
training proceed on a broken executable.

`FusedTrainStep` runs the check before its first build; bench.py arms it
right after backend init. MXTPU_CACHE_GUARD=0 skips the check (trust the
cache).
"""
from __future__ import annotations

import threading
import warnings
from contextlib import contextmanager

import numpy as np

__all__ = ["check", "verdict", "donated_read_quarantine",
           "_reset_for_tests"]

# None = not yet checked; True = cache ok (or not in use); False = tripped
_VERDICT = None

# -- donated-executable read quarantine (PR 17) ---------------------------
#
# The canary certifies ONE cache read per process; PR 17's flake hunt
# showed the donated-executable corruption is PROBABILISTIC PER READ
# (resilience suite: 6/10 process crashes with a warm cache vs 1/12
# with the cache wiped before every run — heap corruption detonating at
# later allocations, i.e. a deserialized executable whose donation
# aliasing writes through stale addresses). So donated fused-step
# executables must never read the cache at all. Toggling
# ``jax_enable_compilation_cache`` around the dispatch does NOT do
# this: ``compilation_cache.is_cache_used`` latches its verdict at the
# first compile of the process and ignores the flag afterwards. The
# quarantine therefore filters the read primitive itself
# (``get_executable_and_time`` → miss while quarantined); cache WRITES
# still happen, serialization is sound — only deserialization is not.

_READ_QUARANTINE = threading.local()


def _install_read_filter():
    from jax._src import compilation_cache as cc
    if getattr(cc, "_mxtpu_donated_read_filter", None) is not None:
        return
    real_get = cc.get_executable_and_time

    def _filtered_get(cache_key, compile_options, backend):
        if getattr(_READ_QUARANTINE, "on", False):
            return None, None        # forced miss -> fresh backend compile
        return real_get(cache_key, compile_options, backend)

    cc.get_executable_and_time = _filtered_get
    cc._mxtpu_donated_read_filter = real_get


@contextmanager
def donated_read_quarantine():
    """Force persistent-compile-cache MISSES for any compile triggered
    inside the scope (this thread only). Entered by FusedTrainStep
    around every donating dispatch on XLA:CPU — the compile, when one
    happens, then always goes through the sound fresh-compile path."""
    _install_read_filter()
    prev = getattr(_READ_QUARANTINE, "on", False)
    _READ_QUARANTINE.on = True
    try:
        yield
    finally:
        _READ_QUARANTINE.on = prev


def verdict():
    """The cached canary verdict (None when the check hasn't run)."""
    return _VERDICT


def check(force=False) -> bool:
    """Run the persistent-cache canary once per process. Returns True when
    the cache read path is sound (or no persistent cache is configured);
    False when corruption was detected and the cache has been disabled."""
    global _VERDICT
    if _VERDICT is None or force:
        _VERDICT = _run()
    return _VERDICT


def _disabled_by_env():
    from ..autotune.knobs import env_flag
    return not env_flag("MXTPU_CACHE_GUARD", True)


def _cache_active():
    import jax
    try:
        enabled = bool(jax.config.jax_enable_compilation_cache)
        cache_dir = jax.config.jax_compilation_cache_dir
    except AttributeError:          # much older jax: no persistent cache
        return False
    return enabled and bool(cache_dir)


def _run() -> bool:
    from .. import profiler as _prof

    if _disabled_by_env():
        return True
    if not _cache_active():
        return True                 # nothing to guard

    import jax

    # the canary must actually flow THROUGH the persistent cache: lower
    # the size/time thresholds for its one tiny compile, restore after
    overrides = {"jax_persistent_cache_min_entry_size_bytes": -1,
                 "jax_persistent_cache_min_compile_time_secs": 0.0}
    old = {}
    for k, v in overrides.items():
        try:
            old[k] = getattr(jax.config, k)
            jax.config.update(k, v)
        except Exception:  # noqa: BLE001 — knob absent on this jax
            pass
    try:
        got_c, got_s = _canary_values()
        exp = _expected()
        ok = (np.array_equal(got_s, exp)
              and np.array_equal(got_c, np.full((8, 128), exp[-1],
                                                np.float32)))
        if not ok:
            _trip(f"canary mismatch: expected row values {exp.tolist()}, "
                  f"got {got_s.tolist()}")
            return False
        _prof.set_gauge("compile_cache.canary_ok", 1)
        return True
    except Exception as e:  # noqa: BLE001 — failure to run == can't trust it
        _trip(f"canary raised {type(e).__name__}: {e}")
        return False
    finally:
        for k, v in old.items():
            try:
                jax.config.update(k, v)
            except Exception:  # noqa: BLE001
                pass


def _canary_values():
    """Compile+run the canary program (donated carry, scan, two outputs —
    the fused-step executable family) and return its concrete outputs.
    Split out so tests can monkeypatch a corrupted read."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def prog(w, xs):
        def one(c, x):
            c = c * 0.5 + x
            return c, c[0, 0]
        c, heads = lax.scan(one, w, xs)
        return c, heads

    f = jax.jit(prog, donate_argnums=(0,))
    w = jnp.full((8, 128), 1.0, jnp.float32)
    xs = jnp.full((4, 8, 128), 0.25, jnp.float32)
    with warnings.catch_warnings():
        # CPU ignores donation with a warning; that's fine for the canary
        warnings.simplefilter("ignore")
        c, heads = f(w, xs)
    return np.asarray(c), np.asarray(heads)


def _expected():
    # c_{i} = c_{i-1} * 0.5 + 0.25 from 1.0 — all dyadic, exact in f32
    vals, c = [], 1.0
    for _ in range(4):
        c = c * 0.5 + 0.25
        vals.append(c)
    return np.asarray(vals, np.float32)


def _trip(why):
    from .. import profiler as _prof
    import jax

    warnings.warn(
        "persistent compile-cache integrity canary FAILED — disabling the "
        "persistent compilation cache for this process (executables "
        "deserialized from a previous run cannot be trusted; recompiling "
        f"fresh). Detail: {why}. Delete the cache directory "
        f"({getattr(jax.config, 'jax_compilation_cache_dir', '?')}) to "
        "clear the corrupt entries.", RuntimeWarning, stacklevel=3)
    try:
        jax.config.update("jax_enable_compilation_cache", False)
        from jax._src import compilation_cache as cc
        cc.reset_cache()            # drop the already-initialized object
    except Exception:  # noqa: BLE001 — best effort; worst case slow, not wrong
        pass
    _prof.counter("compile_cache.guard_tripped").increment()
    _prof.set_gauge("compile_cache.canary_ok", 0)


def _reset_for_tests():
    global _VERDICT
    _VERDICT = None
