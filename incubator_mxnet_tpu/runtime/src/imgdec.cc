// Native JPEG decode for the IO pipeline (the rebuild's analogue of the
// reference's opencv decode inside src/io/iter_image_recordio_2.cc):
// GIL-free libjpeg decompression callable from the prefetch engine's
// worker threads, so record decode scales across cores instead of
// serializing on the interpreter.
//
// Built as its own shared object (libmxtpu_imgdec.so, linked -ljpeg) so a
// missing libjpeg only disables this fast path — the Python caller falls
// back to PIL.
//
// API (ctypes):
//   mxtpu_jpeg_info(buf, len, &w, &h, &c)        -> 0 ok / -1 bad stream
//   mxtpu_jpeg_decode(buf, len, out, out_len, channels) -> 0 ok / -1
//     channels: 3 = RGB interleaved, 1 = grayscale. out must hold
//     w*h*channels bytes (from mxtpu_jpeg_info).

#include <csetjmp>
#include <cstddef>
#include <cstdio>
#include <cstring>

#include <jpeglib.h>

namespace {

// libjpeg's default error handler calls exit(); trap into longjmp instead
struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(mgr->jump, 1);
}

void silent_output(j_common_ptr) {}  // no stderr spam on partial streams

}  // namespace

extern "C" {

int mxtpu_jpeg_info(const unsigned char* buf, size_t len, int* w, int* h,
                    int* c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *w = static_cast<int>(cinfo.image_width);
  *h = static_cast<int>(cinfo.image_height);
  *c = cinfo.num_components;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int mxtpu_jpeg_decode(const unsigned char* buf, size_t len,
                      unsigned char* out, size_t out_len, int channels) {
  if (channels != 1 && channels != 3) return -1;
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = (channels == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);
  const size_t stride =
      static_cast<size_t>(cinfo.output_width) * cinfo.output_components;
  const size_t need = stride * cinfo.output_height;
  if (cinfo.output_components != channels || need > out_len) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  // libjpeg treats premature EOF as a WARNING (it injects a fake EOI and
  // fills with gray) — surface it as failure so corrupt records don't
  // silently train on garbage (the PIL fallback raises for the same bytes)
  const long warnings = cinfo.err->num_warnings;
  jpeg_destroy_decompress(&cinfo);
  return warnings == 0 ? 0 : -1;
}

int mxtpu_jpeg_decode_once(const unsigned char* buf, size_t len,
                           unsigned char* out, size_t out_len, int channels,
                           int* w, int* h) {
  // Single-pass decode for the hot record-IO path: ONE header parse.
  // Returns 0 on success (dims in *w/*h), -1 on a bad/truncated stream,
  // or the REQUIRED byte count (> 0) when out_len is too small — the
  // caller grows its scratch buffer and retries (rare).
  if (channels != 1 && channels != 3) return -1;
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = (channels == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_calc_output_dimensions(&cinfo);
  const size_t stride =
      static_cast<size_t>(cinfo.output_width) * channels;
  const size_t need = stride * cinfo.output_height;
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  if (need > out_len) {
    jpeg_destroy_decompress(&cinfo);
    if (need > static_cast<size_t>(1) << 31) return -1;  // bomb guard
    return static_cast<int>(need);
  }
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != channels) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  const long warnings = cinfo.err->num_warnings;
  jpeg_destroy_decompress(&cinfo);
  return warnings == 0 ? 0 : -1;
}

}  // extern "C"
