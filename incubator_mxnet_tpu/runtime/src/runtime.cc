// Native runtime: threaded dependency engine, pooled storage allocator,
// bounded token queue. The TPU-native rebuild of the reference's C++ core
// (src/engine/threaded_engine*.cc, src/storage/pooled_storage_manager,
// src/io prefetcher) for HOST-side work: device compute is scheduled by
// XLA's async dispatch; this engine orders and parallelizes the host tasks
// around it (IO, decode, prefetch, checkpoint writes) with the same
// var read/write dependency semantics as the reference engine.
//
// C API only (consumed via ctypes; no pybind11 in the image).
//
// Build: make -C .. (produces libmxtpu_runtime.so next to __init__.py)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {
typedef void (*op_fn)(void*);
}

namespace {

// ---------------------------------------------------------------------------
// dependency engine
// ---------------------------------------------------------------------------

struct Op {
    op_fn fn;
    void* arg;
    // (var id, is_write) pairs, deduplicated
    std::vector<std::pair<int64_t, bool>> vars;
    size_t grants = 0;   // vars that have admitted this op
};

struct Var {
    // pending ops in program order; bool = is_write
    std::deque<std::pair<Op*, bool>> q;
    int active_readers = 0;
    bool active_writer = false;
};

class Engine {
  public:
    explicit Engine(int num_threads) {
        if (num_threads <= 0) num_threads = 2;
        for (int i = 0; i < num_threads; ++i)
            workers_.emplace_back([this] { worker_loop(); });
    }

    ~Engine() {
        {
            std::unique_lock<std::mutex> lk(mu_);
            shutdown_ = true;
            ready_cv_.notify_all();
        }
        for (auto& t : workers_) t.join();
        for (auto& kv : vars_) delete kv.second;
    }

    int64_t new_var() {
        std::unique_lock<std::mutex> lk(mu_);
        int64_t id = next_var_++;
        vars_[id] = new Var();
        return id;
    }

    void push(op_fn fn, void* arg, const int64_t* const_vars, int n_const,
              const int64_t* mut_vars, int n_mut) {
        Op* op = new Op{fn, arg, {}, 0};
        {
            std::unique_lock<std::mutex> lk(mu_);
            // dedup: a var both read and written is a write dep
            for (int i = 0; i < n_mut; ++i) {
                bool dup = false;
                for (auto& vb : op->vars)
                    if (vb.first == mut_vars[i]) { dup = true; break; }
                if (!dup) add_dep(op, mut_vars[i], true);
            }
            for (int i = 0; i < n_const; ++i) {
                bool dup = false;
                for (auto& vb : op->vars)
                    if (vb.first == const_vars[i]) { dup = true; break; }
                if (!dup) add_dep(op, const_vars[i], false);
            }
            ++pending_;
            if (op->vars.empty()) {
                ready_.push(op);
                ready_cv_.notify_one();
            } else {
                for (auto& vb : op->vars) {
                    Var* v = vars_.at(vb.first);
                    v->q.emplace_back(op, vb.second);
                }
                for (auto& vb : op->vars) try_dispatch(vars_.at(vb.first));
            }
        }
    }

    void wait_for_var(int64_t id) {
        std::unique_lock<std::mutex> lk(mu_);
        auto it = vars_.find(id);
        if (it == vars_.end()) return;  // unknown var: nothing pending
        Var* v = it->second;
        done_cv_.wait(lk, [&] {
            return v->q.empty() && !v->active_writer && v->active_readers == 0;
        });
    }

    void wait_all() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [&] { return pending_ == 0; });
    }

  private:
    void add_dep(Op* op, int64_t id, bool write) {
        auto it = vars_.find(id);
        if (it == vars_.end()) vars_[id] = new Var();
        op->vars.emplace_back(id, write);
    }

    // admit runnable ops from the front of v's queue (caller holds mu_)
    void try_dispatch(Var* v) {
        while (!v->q.empty()) {
            Op* op = v->q.front().first;
            bool write = v->q.front().second;
            if (write) {
                if (v->active_writer || v->active_readers > 0) break;
                v->active_writer = true;
            } else {
                if (v->active_writer) break;
                ++v->active_readers;
            }
            v->q.pop_front();
            if (++op->grants == op->vars.size()) {
                ready_.push(op);
                ready_cv_.notify_one();
            }
            if (write) break;  // writer is exclusive; stop admitting
        }
    }

    void worker_loop() {
        for (;;) {
            Op* op;
            {
                std::unique_lock<std::mutex> lk(mu_);
                ready_cv_.wait(lk, [&] { return shutdown_ || !ready_.empty(); });
                if (shutdown_ && ready_.empty()) return;
                op = ready_.front();
                ready_.pop();
            }
            op->fn(op->arg);
            {
                std::unique_lock<std::mutex> lk(mu_);
                for (auto& vb : op->vars) {
                    Var* v = vars_.at(vb.first);
                    if (vb.second) v->active_writer = false;
                    else --v->active_readers;
                }
                for (auto& vb : op->vars) try_dispatch(vars_.at(vb.first));
                --pending_;
                done_cv_.notify_all();
            }
            delete op;
        }
    }

    std::mutex mu_;
    std::condition_variable ready_cv_, done_cv_;
    std::unordered_map<int64_t, Var*> vars_;
    std::queue<Op*> ready_;
    std::vector<std::thread> workers_;
    int64_t next_var_ = 1;
    size_t pending_ = 0;
    bool shutdown_ = false;
};

// ---------------------------------------------------------------------------
// pooled storage allocator (host staging buffers)
// ---------------------------------------------------------------------------

class Pool {
  public:
    ~Pool() {
        for (auto& kv : free_) for (void* p : kv.second) std::free(p);
    }

    void* alloc(size_t size) {
        size_t bucket = round_up(size);
        {
            std::unique_lock<std::mutex> lk(mu_);
            auto it = free_.find(bucket);
            if (it != free_.end() && !it->second.empty()) {
                void* p = it->second.back();
                it->second.pop_back();
                pooled_bytes_ -= bucket;
                live_[p] = bucket;
                used_bytes_ += bucket;
                return p;
            }
        }
        void* p = std::malloc(bucket);
        if (!p) return nullptr;
        std::unique_lock<std::mutex> lk(mu_);
        live_[p] = bucket;
        used_bytes_ += bucket;
        return p;
    }

    void release(void* p) {
        std::unique_lock<std::mutex> lk(mu_);
        auto it = live_.find(p);
        if (it == live_.end()) return;  // not ours; ignore
        size_t bucket = it->second;
        live_.erase(it);
        used_bytes_ -= bucket;
        free_[bucket].push_back(p);
        pooled_bytes_ += bucket;
    }

    void stats(size_t* used, size_t* pooled) {
        std::unique_lock<std::mutex> lk(mu_);
        *used = used_bytes_;
        *pooled = pooled_bytes_;
    }

  private:
    static size_t round_up(size_t s) {
        size_t b = 256;
        while (b < s) b <<= 1;
        return b;
    }

    std::mutex mu_;
    std::unordered_map<size_t, std::vector<void*>> free_;
    std::unordered_map<void*, size_t> live_;
    size_t used_bytes_ = 0, pooled_bytes_ = 0;
};

// ---------------------------------------------------------------------------
// bounded blocking token queue (prefetch pipeline backbone)
// ---------------------------------------------------------------------------

class TokenQueue {
  public:
    explicit TokenQueue(size_t cap) : cap_(cap ? cap : 1) {}

    // blocks while full; returns false if closed
    bool push(uint64_t tok) {
        UserGuard g(this);
        std::unique_lock<std::mutex> lk(mu_);
        cv_push_.wait(lk, [&] { return closed_ || q_.size() < cap_; });
        if (closed_) return false;
        q_.push_back(tok);
        cv_pop_.notify_one();
        return true;
    }

    // blocks while empty; returns false if closed and drained
    bool pop(uint64_t* tok) {
        UserGuard g(this);
        std::unique_lock<std::mutex> lk(mu_);
        cv_pop_.wait(lk, [&] { return closed_ || !q_.empty(); });
        if (q_.empty()) return false;
        *tok = q_.front();
        q_.pop_front();
        cv_push_.notify_one();
        return true;
    }

    void close() {
        std::unique_lock<std::mutex> lk(mu_);
        closed_ = true;
        cv_push_.notify_all();
        cv_pop_.notify_all();
    }

    size_t size() {
        UserGuard g(this);
        std::unique_lock<std::mutex> lk(mu_);
        return q_.size();
    }

    // Safe teardown: a producer thread can still be inside push() (woken by
    // close(), about to return) when the consumer drops the queue. Deleting
    // then is a use-after-free. close + spin until no thread is inside.
    void drain_users() {
        close();
        while (users_.load(std::memory_order_acquire) > 0)
            std::this_thread::yield();
    }

  private:
    struct UserGuard {
        explicit UserGuard(TokenQueue* q) : q_(q) {
            q_->users_.fetch_add(1, std::memory_order_acq_rel);
        }
        ~UserGuard() { q_->users_.fetch_sub(1, std::memory_order_acq_rel); }
        TokenQueue* q_;
    };

    std::mutex mu_;
    std::condition_variable cv_push_, cv_pop_;
    std::deque<uint64_t> q_;
    size_t cap_;
    bool closed_ = false;
    std::atomic<int> users_{0};
};

}  // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

void* mxtpu_engine_create(int num_threads) { return new Engine(num_threads); }
void mxtpu_engine_destroy(void* e) {
    if (!e) return;
    delete static_cast<Engine*>(e);
}
int64_t mxtpu_engine_new_var(void* e) {
    if (!e) return -1;
    return static_cast<Engine*>(e)->new_var();
}
void mxtpu_engine_push(void* e, op_fn fn, void* arg,
                       const int64_t* const_vars, int n_const,
                       const int64_t* mut_vars, int n_mut) {
    if (!e) return;  // destroyed handle (python GC finalization order)
    static_cast<Engine*>(e)->push(fn, arg, const_vars, n_const, mut_vars,
                                  n_mut);
}
void mxtpu_engine_wait_for_var(void* e, int64_t v) {
    if (!e) return;
    static_cast<Engine*>(e)->wait_for_var(v);
}
void mxtpu_engine_wait_all(void* e) {
    if (!e) return;
    static_cast<Engine*>(e)->wait_all();
}

void* mxtpu_pool_create() { return new Pool(); }
void mxtpu_pool_destroy(void* p) {
    if (!p) return;
    delete static_cast<Pool*>(p);
}
void* mxtpu_pool_alloc(void* p, size_t size) {
    if (!p) return nullptr;
    return static_cast<Pool*>(p)->alloc(size);
}
void mxtpu_pool_free(void* p, void* ptr) {
    if (!p) return;
    static_cast<Pool*>(p)->release(ptr);
}
void mxtpu_pool_stats(void* p, size_t* used, size_t* pooled) {
    if (!p) { *used = 0; *pooled = 0; return; }
    static_cast<Pool*>(p)->stats(used, pooled);
}

void* mxtpu_queue_create(size_t cap) { return new TokenQueue(cap); }
void mxtpu_queue_destroy(void* q) {
    if (!q) return;
    auto* tq = static_cast<TokenQueue*>(q);
    tq->drain_users();
    delete tq;
}
int mxtpu_queue_push(void* q, uint64_t tok) {
    if (!q) return 0;
    return static_cast<TokenQueue*>(q)->push(tok) ? 1 : 0;
}
int mxtpu_queue_pop(void* q, uint64_t* tok) {
    if (!q) return 0;
    return static_cast<TokenQueue*>(q)->pop(tok) ? 1 : 0;
}
void mxtpu_queue_close(void* q) {
    if (!q) return;
    static_cast<TokenQueue*>(q)->close();
}
size_t mxtpu_queue_size(void* q) {
    if (!q) return 0;
    return static_cast<TokenQueue*>(q)->size();
}

}  // extern "C"
