"""Runtime feature detection (parity: python/mxnet/runtime.py —
`mx.runtime.Features()`, `is_enabled`, feature_list)."""
from __future__ import annotations

import jax

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    backend = jax.default_backend()
    try:
        from ..ops import pallas as _pallas
        pallas_ok = _pallas.enabled()
    except Exception:
        pallas_ok = False
    try:
        from ..ops.pallas import is_tpu as _is_tpu
        on_tpu = _is_tpu()
    except Exception:  # noqa: BLE001
        on_tpu = backend == "tpu"
    return {
        "TPU": on_tpu,
        "CPU": True,
        "CUDA": backend == "gpu",          # reference flag name; XLA:GPU here
        "BF16": True,                       # native MXU dtype
        "F16C": True,
        "PALLAS": pallas_ok,                # custom TPU kernels
        "DIST_MESH": len(jax.devices()) > 1,  # multi-device collectives
        "OPENCV": False,
        "BLAS_OPEN": True,                  # XLA handles BLAS
        "SSE": True,
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": False,
        "PROFILER": True,
    }


class Features(dict):
    """dict of name -> Feature with `is_enabled`, like the reference."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _detect().items()})

    def is_enabled(self, name):
        feat = self.get(name.upper())
        return bool(feat and feat.enabled)

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"


def feature_list():
    return list(Features().values())
