"""Evaluation metrics (parity: python/mxnet/metric.py)."""
from __future__ import annotations

import numpy as _numpy

from .base import _Registry
from .ndarray import NDArray

registry = _Registry("metric")
register = registry.register


def create(name, *args, **kwargs):
    if isinstance(name, list):
        c = CompositeEvalMetric()
        for n in name:
            c.add(create(n, *args, **kwargs))
        return c
    return registry.create(name, *args, **kwargs)


def _np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _numpy.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None):
        self.name = name
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register("acc")
@register("accuracy")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kw):
        self.axis = axis
        super().__init__(name)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            label = _np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_numpy.int64).ravel()
            label = label.astype(_numpy.int64).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register("top_k_accuracy")
@register("topkaccuracy")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kw):
        self.top_k = top_k
        super().__init__(f"{name}_{top_k}")

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            label = _np(label).astype(_numpy.int64).ravel()
            topk = _numpy.argsort(-pred, axis=-1)[:, :self.top_k]
            self.sum_metric += sum(l in t for l, t in zip(label, topk))
            self.num_inst += len(label)


@register("f1")
class F1(EvalMetric):
    """average='micro': one F1 from globally pooled counts;
    'macro' (default, reference semantics): mean of per-update F1 scores."""

    def __init__(self, name="f1", average="macro", **kw):
        self.average = average
        super().__init__(name)

    def reset(self):
        self.tp = self.fp = self.fn = 0
        self._batch_f1 = []
        self.num_inst = 0
        self.sum_metric = 0.0

    @staticmethod
    def _f1(tp, fp, fn):
        prec = tp / max(tp + fp, 1)
        rec = tp / max(tp + fn, 1)
        return 2 * prec * rec / max(prec + rec, 1e-12)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            label = _np(label).astype(_numpy.int64).ravel()
            pred = pred.astype(_numpy.int64).ravel()
            tp = ((pred == 1) & (label == 1)).sum()
            fp = ((pred == 1) & (label == 0)).sum()
            fn = ((pred == 0) & (label == 1)).sum()
            self.tp += tp
            self.fp += fp
            self.fn += fn
            self._batch_f1.append(self._f1(tp, fp, fn))
            self.num_inst += 1

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        if self.average == "micro":
            return self.name, self._f1(self.tp, self.fp, self.fn)
        return self.name, float(_numpy.mean(self._batch_f1))


@register("mcc")
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kw):
        super().__init__(name)

    def reset(self):
        self.tp = self.fp = self.fn = self.tn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            label = _np(label).astype(_numpy.int64).ravel()
            pred = pred.astype(_numpy.int64).ravel()
            self.tp += ((pred == 1) & (label == 1)).sum()
            self.fp += ((pred == 1) & (label == 0)).sum()
            self.fn += ((pred == 0) & (label == 1)).sum()
            self.tn += ((pred == 0) & (label == 0)).sum()
            self.num_inst += 1

    def get(self):
        num = self.tp * self.tn - self.fp * self.fn
        den = _numpy.sqrt(float((self.tp + self.fp) * (self.tp + self.fn) *
                            (self.tn + self.fp) * (self.tn + self.fn)))
        return self.name, num / den if den else 0.0


@register("mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", **kw):
        super().__init__(name)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _np(label), _np(pred)
            self.sum_metric += _numpy.abs(label.reshape(pred.shape) - pred).mean()
            self.num_inst += 1


@register("mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", **kw):
        super().__init__(name)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _np(label), _np(pred)
            self.sum_metric += _numpy.square(label.reshape(pred.shape) - pred).mean()
            self.num_inst += 1


@register("rmse")
class RMSE(MSE):
    def __init__(self, name="rmse", **kw):
        super().__init__(name)

    def get(self):
        name, v = super().get()
        return name, float(_numpy.sqrt(v))


@register("ce")
@register("cross-entropy")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kw):
        self.eps = eps
        super().__init__(name)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _np(label).astype(_numpy.int64).ravel()
            pred = _np(pred)
            prob = pred[_numpy.arange(len(label)), label]
            self.sum_metric += (-_numpy.log(prob + self.eps)).sum()
            self.num_inst += len(label)


@register("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kw):
        super().__init__(eps, name)


@register("perplexity")
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, name="perplexity", **kw):
        self.ignore_label = ignore_label
        super().__init__(name=name)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _np(label).astype(_numpy.int64).ravel()
            pred = _np(pred).reshape(len(label), -1)
            mask = (label != self.ignore_label) if self.ignore_label is not None \
                else _numpy.ones_like(label, bool)
            prob = pred[_numpy.arange(len(label)), label]
            self.sum_metric += (-_numpy.log(prob[mask] + 1e-12)).sum()
            self.num_inst += mask.sum()

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(_numpy.exp(self.sum_metric / self.num_inst))


@register("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kw):
        super().__init__(name)

    def reset(self):
        self._labels = []
        self._preds = []
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_np(label).ravel())
            self._preds.append(_np(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        l = _numpy.concatenate(self._labels)
        p = _numpy.concatenate(self._preds)
        return self.name, float(_numpy.corrcoef(l, p)[0, 1])


@register("loss")
class Loss(EvalMetric):
    """Average of pre-computed per-batch loss values."""

    def __init__(self, name="loss", **kw):
        super().__init__(name)

    def update(self, _, preds):
        for pred in _as_list(preds):
            v = _np(pred)
            self.sum_metric += v.sum()
            self.num_inst += v.size


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kw):
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]
        super().__init__(name)

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str) else metric)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, vals = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            vals.append(v)
        return names, vals


register("composite")(CompositeEvalMetric)


class CustomMetric(EvalMetric):
    """Wrap feval(label, pred) -> float as a metric (reference
    metric.CustomMetric; `mx.metric.np(f)` builds one from a numpy fn)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs
        name = name or getattr(feval, "__name__", "custom")
        # reference wraps only anonymous callables ('<lambda>')
        if "<" in name:
            name = f"custom({name})"
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        if not self._allow_extra_outputs and len(labels) != len(preds):
            raise ValueError(
                f"labels/preds count mismatch {len(labels)} vs {len(preds)}"
                " (pass allow_extra_outputs=True to permit)")
        for l, p in zip(labels, preds):
            val = self._feval(_np(l), _np(p))
            if isinstance(val, tuple):
                s, n = val
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += val
                self.num_inst += 1


register("custom")(CustomMetric)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Decorator: numpy feval -> CustomMetric factory (reference metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = name or getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, name, allow_extra_outputs)
