"""Step-time decomposition: where does a training step's wall time go?

Wall-clock step time alone cannot distinguish a chip working from a chip
waiting — through an async dispatch path (XLA's dependency engine, and
doubly so through a remote relay) the host returns at enqueue, so a
62 ms step could be 60 ms of MXU work or 10 ms of work behind 50 ms of
input starvation. :class:`StepBudget` combines the signals the earlier
observability layers already export into one per-step budget::

    step_ms = device_compute + collective + input_wait + host_gap + other

* **device_compute** — measured by a post-steady probe
  (:meth:`probe_device_time`): a few extra steps each terminated by a
  host value fetch. A fetch is the one true barrier on every backend
  this repo runs on (through the axon relay ``block_until_ready()``
  returns at enqueue — PERF.md's protocol note), so the synchronized
  per-step wall minus the measured host dispatch share is the device
  time. When an ``mxtpu.devicescope`` capture window completed for the
  run, the window's MEASURED device busy time replaces the probe value
  and the budget's provenance upgrades to ``measured(profile)`` (the
  probe stays beside it in the reconciliation block); the probe is the
  portable fallback that works with no window on the CPU tier-1 path.
* **collective** — delta of the ``kvstore.collective_ms`` counter over
  the steady phase (zero on single-process runs).
* **input_wait** — delta of ``io.wait_ms`` (DevicePrefetcher's consumer
  starvation counter) over the steady phase.
* **host_gap** — the host's per-step dispatch share: wall time spent
  INSIDE the step/chunk dispatch call (accumulated by the caller, or by
  ``trainloop.dispatch_ms`` in whole-loop mode). This is the time the
  device may sit idle between programs because the host hasn't enqueued
  the next one.
* **other** — the signed residual, clamped at zero: what the model
  cannot attribute (allocator stalls, GC, untimed host work). A large
  ``other`` is itself a finding.

Everything lands in ``perfscope.*`` gauges through the shared registry
(so /metrics, flight dumps and BENCH json carry it with zero wiring) and
in the dict :meth:`finish` returns, which bench.py embeds as
``extra.perfscope.decomposition``.
"""
from __future__ import annotations

import time

from ..profiler.counters import (counters as _registry_snapshot,
                                 observe as _observe,
                                 set_gauge as _set_gauge)

__all__ = ["StepBudget", "probe_device_time", "counter_value"]


def counter_value(full_name: str) -> float:
    """Current numeric value of a registry metric (0.0 when absent)."""
    v = _registry_snapshot().get(full_name)
    return float(v) if isinstance(v, (int, float)) else 0.0


def probe_device_time(sync_step_fn, iters: int = 5) -> dict:
    """Measure synchronized per-step wall time: run ``sync_step_fn``
    (one step ENDING IN A HOST FETCH) ``iters`` times. Returns
    {"median_ms", "min_ms", "max_ms", "iters"}. The median is robust to
    a single scheduler burp on a 1-core box; each observation also lands
    in the ``perfscope.device_step_ms`` histogram so the distribution is
    exported, not just the point estimate."""
    times = []
    for _ in range(max(1, int(iters))):
        t0 = time.perf_counter()
        sync_step_fn()
        ms = (time.perf_counter() - t0) * 1e3
        times.append(ms)
        _observe("perfscope.device_step_ms", ms, "perfscope")
    times.sort()
    n = len(times)
    median = times[n // 2] if n % 2 else 0.5 * (times[n // 2 - 1]
                                                + times[n // 2])
    return {"median_ms": median, "min_ms": times[0], "max_ms": times[-1],
            "iters": n}


class StepBudget:
    """Accumulate the steady-phase signals and settle the budget.

    Usage (bench.py's shape)::

        budget = StepBudget()
        budget.begin()                      # snapshot counters
        for _ in range(steps):
            t = time.perf_counter()
            loss = step(x, y)               # async dispatch
            budget.add_dispatch(time.perf_counter() - t)
        loss_val = float(loss)              # fetch = end of steady wall
        budget.end(steps=steps, steady_s=dt)
        probe = budget.probe(lambda: float(step(x, y)))   # sync probe
        decomp = budget.finish()            # the budget dict + gauges
    """

    def __init__(self, steps_per_dispatch: int = 1):
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        self._dispatch_s = 0.0
        self._snap0 = {}
        self._snap1 = {}
        self._steps = 0
        self._steady_s = 0.0
        self._probe = None
        self._begin_monotonic = None

    _TRACKED = ("io/io.wait_ms", "mxtpu/kvstore.collective_ms",
                "trainloop/trainloop.dispatch_ms")

    def _snapshot(self):
        snap = _registry_snapshot()
        return {k: float(snap.get(k) or 0.0) for k in self._TRACKED}

    def begin(self):
        self._snap0 = self._snapshot()
        # steady-phase start marker: the devicescope reconciliation only
        # accepts capture windows completed AFTER this point — a window
        # from an earlier run in the same process measured someone
        # else's steady phase
        self._begin_monotonic = time.monotonic()
        return self

    def add_dispatch(self, seconds: float):
        """One dispatch call's host wall time (covers steps_per_dispatch
        micro-steps in chunked mode)."""
        self._dispatch_s += float(seconds)

    def end(self, steps: int, steady_s: float):
        self._steps = max(1, int(steps))
        self._steady_s = float(steady_s)
        self._snap1 = self._snapshot()

    def probe(self, sync_step_fn, iters: int = 5,
              steps_per_call: int | None = None) -> dict:
        """Run the synchronized device-time probe; ``steps_per_call``
        divides the measured wall when one call drives a whole chunk."""
        p = probe_device_time(sync_step_fn, iters=iters)
        div = max(1, int(steps_per_call or self.steps_per_dispatch))
        p = dict(p, median_ms=p["median_ms"] / div,
                 min_ms=p["min_ms"] / div, max_ms=p["max_ms"] / div,
                 steps_per_call=div)
        self._probe = p
        return p

    def _delta(self, key: str) -> float:
        return max(0.0, self._snap1.get(key, 0.0)
                   - self._snap0.get(key, 0.0))

    @staticmethod
    def _in_program_collectives() -> bool:
        """True when a multi-device mesh means the step's collectives
        run inside the jit program (where the kvstore counter cannot
        see them). Checks the process-global registry AND the last
        published layout (publish_param_stats runs with the executor's
        ACTUAL mesh, so an explicit ``mesh=`` FusedTrainStep — which
        never registers one — is still seen)."""
        try:
            from ..parallel import sharding as _sh
            mesh = _sh.get_mesh()
            if mesh is not None and int(getattr(mesh, "size", 1)) > 1:
                return True
            shape = (_sh.summary() or {}).get("mesh")
            if isinstance(shape, dict) and shape:
                n = 1
                for s in shape.values():
                    n *= int(s)
                return n > 1
            return False
        except Exception:  # noqa: BLE001
            return False

    @staticmethod
    def _commscope_estimate():
        """The steady train program's per-step collective estimate from
        mxtpu.commscope, or None when commscope is unarmed / captured
        nothing."""
        try:
            from .. import commscope as _cs
            if _cs._CS is None:
                return None
            return _cs.step_estimate()
        except Exception:  # noqa: BLE001
            return None

    def finish(self, model_flops_per_step=None, dtype="float32") -> dict:
        """Settle the budget and publish the ``perfscope.*`` gauges.

        With ``model_flops_per_step`` the result also carries the MFU
        decomposition: achieved MFU plus the counterfactual MFU with
        each non-compute component removed — the "what would fixing X
        buy" table ``mxdiag.py perf`` prints."""
        from . import cost as _cost
        steps = self._steps
        step_ms = self._steady_s / steps * 1e3
        input_wait = self._delta("io/io.wait_ms") / steps
        collective = self._delta("mxtpu/kvstore.collective_ms") / steps
        # collective PROVENANCE: the kvstore counter only times the
        # explicit-collective path. Under a GSPMD mesh the collectives
        # are compiler-inserted INSIDE the jit program, the counter
        # reads ~0, and reporting `collective: 0.0` as if measured would
        # silently fold all-reduce/all-gather time into device_compute —
        # exactly the attribution lie this field pins down:
        #   measured     kvstore counter (or a genuinely unsharded run)
        #   estimated    commscope's static-HLO link-time estimate for
        #                the steady train program (marked, never a
        #                measurement)
        #   unavailable  sharded in-program mode with commscope unarmed:
        #                the component is unknown, NOT zero
        collective_source = "measured"
        collective_est = None
        if collective <= 0.0:
            # the captured train program's OWN mesh is the primary
            # signal — it is correct even for an explicit mesh= executor
            # that never touched the registry; the registry/last-layout
            # check is the fallback for commscope-off runs
            est = self._commscope_estimate()
            if est is not None and est.get("devices", 1) > 1 \
                    and est.get("hlo_available", True) \
                    and isinstance(est.get("est_ms"), (int, float)):
                # hlo_available=False means commscope LOOKED and could
                # not read the program: that zero is ignorance, and
                # must fall through to "unavailable", not masquerade
                # as an estimated empty inventory
                collective = min(float(est["est_ms"]), step_ms)
                collective_source = "estimated"
                collective_est = est
            elif self._in_program_collectives() \
                    or (est is not None and est.get("devices", 1) > 1):
                collective_source = "unavailable"
        # host dispatch share: caller-accumulated wall, plus the whole-
        # loop executor's own dispatch counter when that path ran. On a
        # SYNCHRONOUS backend (XLA:CPU blocks in the jit call) this
        # includes the device compute itself, so it bounds host_gap from
        # above but is never attributed wholesale.
        disp_ms = (self._dispatch_s * 1e3
                   + self._delta("trainloop/trainloop.dispatch_ms")) / steps
        if self._probe is not None:
            # synchronized per-step wall IS the device-paced step time;
            # clip at the steady wall — the probe's extra host fetch can
            # only overstate it, and in steady state the device cannot
            # have been busy longer than the wall per step
            device = min(self._probe["median_ms"], step_ms)
            if collective_source == "estimated":
                # the probe's wall CONTAINS the in-program collectives;
                # peel the estimate out so the two components don't
                # double-count the same milliseconds
                device = max(0.0, device - collective)
        else:
            # no probe: peel the measured host/input/collective shares
            # off the wall and attribute the middle to the device
            device = max(0.0, step_ms - min(disp_ms, step_ms)
                         - input_wait - collective)
        budget_source = "probe" if self._probe is not None else "residual"
        # devicescope reconciliation: when a completed capture window
        # measured the device timeline, the MEASURED busy/collective
        # numbers replace the probe/estimate (provenance upgraded to
        # measured(profile)); the analytic values stay beside them in
        # the reconciliation block, and a >25% disagreement fires the
        # loud drift warning (docs/devicescope.md). With no window this
        # whole branch is one predicate and the budget settles exactly
        # as above — pinned by tests both ways.
        reconciliation = None
        try:
            from .. import devicescope as _ds
            upd = _ds.budget_overrides(
                step_ms=step_ms, device=device, collective=collective,
                collective_source=collective_source,
                source=budget_source, since=self._begin_monotonic)
        except Exception:  # noqa: BLE001 — measurement must never
            upd = None                 # destroy the settled budget
        if upd is not None:
            device = upd["device_compute_ms"]
            collective = upd["collective_ms"]
            collective_source = upd["collective_source"]
            budget_source = upd["source"]
            reconciliation = upd["reconciliation"]
            # prefetch wait can OVERLAP measured device busy (that is
            # the prefetcher's whole point), but the budget is a wall-
            # time accounting identity: the measured device/collective
            # claims are the strong ones, so input_wait keeps only the
            # share the device was actually idle for — otherwise an
            # input-starved-but-overlapped run sums past step_ms and
            # trace_check rejects the artifact as malformed
            input_wait = min(input_wait,
                             max(0.0, step_ms - device - collective))
        # host gap: steady time neither the device nor input/collective
        # explains, capped by the host time actually measured inside
        # dispatch calls (a gap the host didn't spend can't be its fault)
        remaining = step_ms - device - input_wait - collective
        host_gap = max(0.0, min(remaining, disp_ms))
        other = step_ms - (device + collective + input_wait + host_gap)
        decomp = {
            "step_ms": round(step_ms, 4),
            "device_compute_ms": round(device, 4),
            "collective_ms": round(collective, 4),
            "collective_source": collective_source,
            "collective_est": collective_est,
            "input_wait_ms": round(input_wait, 4),
            "host_gap_ms": round(host_gap, 4),
            "other_ms": round(max(0.0, other), 4),
            "residual_ms": round(other, 4),     # signed, pre-clamp
            "dispatch_ms": round(disp_ms, 4),   # raw host-dispatch share
            "steps": steps,
            "probe": self._probe,
            "source": budget_source,
            "reconciliation": reconciliation,
        }
        comp_sum = (decomp["device_compute_ms"] + decomp["collective_ms"]
                    + decomp["input_wait_ms"] + decomp["host_gap_ms"]
                    + decomp["other_ms"])
        decomp["sum_ms"] = round(comp_sum, 4)
        decomp["coverage"] = round(comp_sum / step_ms, 4) if step_ms else None
        for key in ("step_ms", "device_compute_ms", "collective_ms",
                    "input_wait_ms", "host_gap_ms", "other_ms"):
            _set_gauge("perfscope." + key, decomp[key], "perfscope")
        if model_flops_per_step:
            peaks = _cost.device_peaks()
            pk = _cost.peak_flops_for(dtype, peaks)
            f = float(model_flops_per_step)

            def mfu_at(ms):
                return round(f / (ms * 1e-3) / pk, 6) if ms > 0 else None

            mfu = mfu_at(step_ms)
            decomp["mfu"] = mfu
            _set_gauge("perfscope.mfu", mfu or 0.0, "perfscope")
            decomp["mfu_if_removed"] = {
                comp: mfu_at(step_ms - decomp[comp + "_ms"])
                for comp in ("collective", "input_wait", "host_gap", "other")
            }
            decomp["mfu_device_only"] = mfu_at(decomp["device_compute_ms"])
            decomp["peak_flops"] = pk
            decomp["model_flops_per_step"] = f
        return decomp
