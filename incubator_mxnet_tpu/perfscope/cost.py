"""Per-program cost analysis: XLA ``cost_analysis()`` + analytic roofline.

Every compiled hot program in this framework passes through a handful of
well-known compile sites — the HybridBlock jit cache, FusedTrainStep's
single-step and k-step programs, TrainLoop chunks, FrozenModel serving
buckets. When perfscope is enabled, each site hands its lowered (or
to-be-lowered) program to :func:`analyze_lowered` / :func:`analyze_jit`,
which:

* pulls ``flops`` / ``bytes accessed`` out of XLA's HLO cost analysis
  (host-side — no device work, no tunnel traffic);
* classifies the program against the device's peak-FLOPs/HBM-bandwidth
  point (:func:`classify`): **compute_bound** when its arithmetic
  intensity clears the ridge, **hbm_bound** when it doesn't,
  **trivial** when the FLOP count is too small for the verdict to mean
  anything, **unknown** when the backend's analysis is missing keys
  (XLA:CPU reports ``{}`` for data-movement-only programs);
* records the verdict as a flight-recorder compile span (so crash dumps
  and bench artifacts say not just *that* a program compiled but *what
  it is bound by*), bumps the ``perfscope.*`` counters, and files the
  program in a process-wide table that ``bench.py`` embeds under
  ``extra.perfscope.programs`` and ``tools/mxdiag.py perf`` renders.

The peak tables cover the chips this repo actually runs on (v5e via the
axon tunnel, v4, CPU fallback for tier-1); ``MXTPU_PEAK_FLOPS`` /
``MXTPU_PEAK_BW`` override both for new hardware without a code change.
"""
from __future__ import annotations

import os
import threading

from ..diagnostics import flight as _flight
from ..profiler.counters import counter as _counter

__all__ = ["device_peaks", "classify", "analyze_lowered", "analyze_jit",
           "programs", "reset_programs", "ROOFLINE_VERDICTS",
           "TRIVIAL_FLOPS"]

ROOFLINE_VERDICTS = ("compute_bound", "hbm_bound", "trivial", "unknown")

# below this many FLOPs a program's runtime is dominated by fixed launch/
# dispatch overhead, not by either roofline ceiling — calling it compute-
# or bandwidth-bound would be noise dressed up as analysis
TRIVIAL_FLOPS = 1e7

# (peak_flops_f32, peak_flops_bf16, hbm_bytes_per_s) per table row.
# Chip numbers are the published per-chip peaks; the CPU row is a
# deliberately round fallback so tier-1 roofline verdicts are stable
# across boxes (absolute CPU estimates are not the point — the verdict
# taxonomy and the schema are).
_PEAK_TABLE = {
    # TPU v5e (v5 litepod): 197 Tf bf16 / 99 Tf f32, 819 GB/s HBM2
    "v5e": (99e12, 197e12, 819e9),
    # TPU v4: 275 Tf bf16 (no fp32 MXU mode: same peak), 1228 GB/s HBM2
    "v4": (137.5e12, 275e12, 1228e9),
    # TPU v5p: 459 Tf bf16, 2765 GB/s HBM2e
    "v5p": (229.5e12, 459e12, 2765e9),
    # CPU fallback: order-of-magnitude single-socket numbers
    "cpu": (5e10, 5e10, 2e10),
}

# ordered (patterns, row): matched against the device_kind string with
# spaces/hyphens/underscores collapsed, so "TPU v5 lite" (what jax
# reports for a v5e), "v5litepod" (the GCE accelerator type) and a
# plain "v5e" all land on the v5e row. v5p checks first — "v5" alone
# would shadow it.
_KIND_PATTERNS = (
    (("v5p",), "v5p"),
    (("v5e", "v5lite"), "v5e"),
    (("v4",), "v4"),
)


def _env_float(name):
    # malformed override: keep the table (the analysis path promises it
    # never raises)
    from ..autotune.knobs import env_float
    return env_float(name, None, on_error="default")


def device_peaks(device=None) -> dict:
    """Peak FLOP/s (f32 + bf16) and HBM bandwidth for a device.

    Resolution: ``MXTPU_PEAK_FLOPS``/``MXTPU_PEAK_BW`` env overrides >
    the device-kind pattern table > the CPU fallback row."""
    kind = "cpu"
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        kind = str(getattr(device, "device_kind", "cpu")).lower()
    except Exception:  # noqa: BLE001 — no backend yet: CPU row
        pass
    collapsed = kind.replace(" ", "").replace("-", "").replace("_", "")
    row, matched = _PEAK_TABLE["cpu"], "cpu"
    for patterns, key in _KIND_PATTERNS:
        if any(p in collapsed for p in patterns):
            row, matched = _PEAK_TABLE[key], key
            break
    f32, bf16, bw = row
    env_f = _env_float("MXTPU_PEAK_FLOPS")
    env_b = _env_float("MXTPU_PEAK_BW")
    if env_f:
        f32 = bf16 = env_f
    if env_b:
        bw = env_b
    return {"device_kind": kind, "table_row": matched,
            "peak_flops_f32": f32, "peak_flops_bf16": bf16,
            "hbm_bytes_per_s": bw}


def peak_flops_for(dtype, peaks) -> float:
    """bf16-class dtypes ride the MXU's doubled peak; everything else
    gets the f32 ceiling."""
    d = str(dtype)
    if "bfloat16" in d or "float16" in d:
        return peaks["peak_flops_bf16"]
    return peaks["peak_flops_f32"]


def classify(flops, bytes_accessed, peaks=None, dtype="float32") -> dict:
    """Analytic roofline verdict for one program.

    Returns {verdict, flops, bytes_accessed, ai, ridge, est_compute_ms,
    est_memory_ms, peak_flops, hbm_bytes_per_s}. Never raises: missing
    or non-numeric inputs produce verdict "unknown" (the XLA:CPU backend
    returns an empty analysis for data-movement-only programs), zero/
    tiny-FLOP programs produce "trivial"."""
    peaks = peaks or device_peaks()
    pk = peak_flops_for(dtype, peaks)
    bw = peaks["hbm_bytes_per_s"]
    out = {"verdict": "unknown", "flops": None, "bytes_accessed": None,
           "ai": None, "ridge": pk / bw if bw else None,
           "est_compute_ms": None, "est_memory_ms": None,
           "peak_flops": pk, "hbm_bytes_per_s": bw}
    try:
        f = float(flops) if flops is not None else None
        b = float(bytes_accessed) if bytes_accessed is not None else None
    except (TypeError, ValueError):
        return out
    if f is None or f != f:           # missing/NaN flops: no verdict
        return out
    out["flops"] = f
    out["bytes_accessed"] = b
    out["est_compute_ms"] = f / pk * 1e3 if pk else None
    if b is not None and b >= 0:
        out["est_memory_ms"] = b / bw * 1e3 if bw else None
    trivial = _env_float("MXTPU_PERFSCOPE_TRIVIAL_FLOPS") or TRIVIAL_FLOPS
    if f < trivial:
        out["verdict"] = "trivial"
        return out
    if not b or b <= 0:
        # real FLOPs, no reported traffic: the analysis says everything
        # stays on-chip — compute is the only ceiling left
        out["verdict"] = "compute_bound"
        return out
    out["ai"] = f / b
    out["verdict"] = "compute_bound" if out["ai"] >= out["ridge"] \
        else "hbm_bound"
    return out


# process-wide table of analyzed programs: name -> record (last analysis
# wins per name — recompiles of the same site overwrite, they don't grow
# the table unboundedly)
_PROGRAMS: "dict[str, dict]" = {}
_plock = threading.Lock()

# mxlint strict-mode recompile detector (mxlint/runtime.py pushes its
# note_program here when armed — one predicate per capture when off,
# the devicescope/commscope hook discipline)
_STRICT_HOOK = None


def programs() -> list:
    """Snapshot of every analyzed program, insertion-ordered."""
    with _plock:
        return [dict(v) for v in _PROGRAMS.values()]


def reset_programs() -> None:
    with _plock:
        _PROGRAMS.clear()


def _extract_costs(obj):
    """Normalize the two cost_analysis() shapes: Lowered returns a flat
    dict; Compiled returns a list of per-module dicts (sum them)."""
    if obj is None:
        return None, None
    if isinstance(obj, (list, tuple)):
        f = b = None
        for mod in obj:
            mf, mb = _extract_costs(mod)
            if mf is not None:
                f = (f or 0.0) + mf
            if mb is not None:
                b = (b or 0.0) + mb
        return f, b
    if isinstance(obj, dict):
        f = obj.get("flops")
        b = obj.get("bytes accessed")
        if b is None:
            # some backends report only the per-operand breakdown
            parts = [v for k, v in obj.items()
                     if k.startswith("bytes accessed") and k != "bytes accessed"]
            b = float(sum(parts)) if parts else None
        return f, b
    return None, None


def record_program(name: str, flops, bytes_accessed, dtype="float32",
                   kind: str = "program", extra: dict | None = None) -> dict:
    """Classify + publish one program's costs (the shared tail of
    analyze_lowered/analyze_jit; also the entry point for callers that
    computed flops themselves). Returns the stored record."""
    peaks = device_peaks()
    rec = classify(flops, bytes_accessed, peaks, dtype)
    rec.update({"name": name, "kind": kind, "dtype": str(dtype)})
    if extra:
        rec.update(extra)
    with _plock:
        _PROGRAMS[name] = rec
    if _STRICT_HOOK is not None:
        # a re-capture of a known name after warmup is a steady-state
        # recompile — the strict auditor counts + names it
        _STRICT_HOOK(name, kind)
    _counter("perfscope.programs_analyzed", "perfscope").increment()
    _counter(f"perfscope.{rec['verdict']}", "perfscope").increment()
    if _flight._REC is not None:
        # the compile-span record gains the cost fields — a crash dump or
        # bench artifact now says what each program is bound by
        _flight.record("compile", f"perfscope.cost:{name}", {
            "flops": rec["flops"], "bytes_accessed": rec["bytes_accessed"],
            "roofline": rec["verdict"], "ai": rec["ai"],
            "est_compute_ms": rec["est_compute_ms"],
            "est_memory_ms": rec["est_memory_ms"]})
    return rec


def _devicescope_register(name, lowered):
    """Record the program's HLO module name with mxtpu.devicescope when
    armed — the join key between measured trace lanes (whose op events
    carry ``hlo_module``) and this program table. Never raises."""
    try:
        from .. import devicescope as _ds
        if _ds._DS is not None and lowered is not None:
            _ds.register_program(name, _ds.module_name_of(lowered))
    except Exception:  # noqa: BLE001 — registration never breaks compiles
        pass


def _commscope_capture(name, lowered=None, compiled=None, mesh=None,
                       mode=None, kind="program"):
    """Hand the program to mxtpu.commscope when armed — the collective/
    resharding extraction rides perfscope's capture hooks (one gate, one
    set of compile sites). Never raises."""
    try:
        from .. import commscope as _cs
        if _cs._CS is not None:
            _cs.capture(name, lowered=lowered, compiled=compiled,
                        mesh=mesh, mode=mode, kind=kind)
    except Exception:  # noqa: BLE001 — extraction never breaks compiles
        pass


def _memscope_capture(name, lowered=None, compiled=None, kind="program"):
    """Hand the program to mxtpu.memscope when armed — the static
    memory-footprint capture rides perfscope's capture hooks (one gate,
    one set of compile sites, the commscope discipline). Never
    raises."""
    try:
        from .. import memscope as _ms
        if _ms._MS is not None:
            _ms.capture(name, lowered=lowered, compiled=compiled,
                        kind=kind)
    except Exception:  # noqa: BLE001 — capture never breaks compiles
        pass


def analyze_lowered(lowered, name: str, dtype="float32",
                    kind: str = "program", extra: dict | None = None,
                    compiled=None, mesh=None, mode=None):
    """Cost-analyze an already-lowered (or compiled) jax stage object.
    Never raises — a backend without cost analysis yields an "unknown"
    record rather than breaking the compile site that called us.

    ``compiled``/``mesh``/``mode`` feed the commscope collective
    extraction when armed: a site that already holds the compiled
    executable (serving buckets) passes it so commscope reads the
    optimized HLO for free instead of compiling again."""
    costs = None
    try:
        costs = lowered.cost_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent surface
        costs = None
    flops, nbytes = _extract_costs(costs)
    rec = record_program(name, flops, nbytes, dtype=dtype, kind=kind,
                         extra=extra)
    _devicescope_register(name, lowered)
    _commscope_capture(name, lowered=lowered, compiled=compiled,
                       mesh=mesh, mode=mode, kind=kind)
    _memscope_capture(name, lowered=lowered, compiled=compiled,
                      kind=kind)
    return rec


def analyze_jit(jit_fn, args, name: str, dtype="float32",
                kind: str = "program", extra: dict | None = None,
                kwargs: dict | None = None, mesh=None, mode=None):
    """Lower ``jit_fn`` against abstract ShapeDtypeStructs of ``args``
    and cost-analyze the result. Tracing happens on the host only (no
    device compile, no buffers touched — safe to call on arguments that
    are about to be donated). Never raises.

    ``mesh``/``mode`` describe the sharded layout for commscope's
    collective extraction (armed separately; it compiles the lowered
    program to read the optimized HLO — see docs/commscope.md for the
    cost model)."""
    try:
        import jax
        from ..ops import select as _sel

        def spec(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        specs = jax.tree_util.tree_map(spec, tuple(args))
        # quiet: this re-trace is purely to read the cost analysis —
        # the pallas selection counters already counted this program's
        # real trace, and must not count it again
        with _sel.quiet():
            lowered = jit_fn.lower(*specs, **(kwargs or {}))
    except Exception:  # noqa: BLE001 — analysis must never break training
        return record_program(name, None, None, dtype=dtype, kind=kind,
                              extra=extra)
    return analyze_lowered(lowered, name, dtype=dtype, kind=kind,
                           extra=extra, mesh=mesh, mode=mode)
