"""mxtpu.perfscope — roofline-aware performance attribution.

The fourth observability layer (docs/observability.md): the profiler
answers *what ran when*, diagnostics *what the process is doing*,
healthmon *which rank is unhealthy* — perfscope answers **why a step is
slow and what fixing it would buy**:

* **per-program cost analysis** (:mod:`.cost`) — every compile site
  (HybridBlock jit cache, FusedTrainStep, TrainLoop chunks, FrozenModel
  serving buckets) captures XLA ``cost_analysis()`` FLOPs/bytes per
  executable and derives an analytic roofline verdict — compute-bound,
  HBM-bound, trivially small, or unknown — against per-device peak
  tables (v5e/v4/v5p/CPU fallback, ``MXTPU_PEAK_FLOPS``/``MXTPU_PEAK_BW``
  overrides). Verdicts land in the flight recorder's compile spans and
  the ``perfscope.*`` counter family.
* **step-time decomposition** (:mod:`.decomp`) — the per-step budget
  ``step_ms = device_compute + collective + input_wait + host_gap +
  other``, assembled from signals the earlier layers already export
  (``io.wait_ms``, ``kvstore.collective_ms``, dispatch wall) plus a
  fetch-barrier device-time probe. ``bench.py`` embeds it as
  ``extra.perfscope`` in every training BENCH json;
  ``tools/mxdiag.py perf`` renders the MFU-decomposition report.
* **regression gate** — ``tools/perf_regress.py`` compares BENCH
  artifacts with noise-aware thresholds and skips ``env_failure``
  artifacts, so every future perf PR gets a machine verdict instead of
  an anecdote.

Cost capture costs one extra host-side trace per compiled signature, so
it is **off by default** outside bench runs: ``enable()`` arms it
(bench.py does, unless ``BENCH_PERFSCOPE=0``), ``MXTPU_PERFSCOPE=1``
arms it at import. The fast-path contract matches healthmon: every hook
site checks the single module global ``_PS`` and pays one predicate when
perfscope is off.
"""
from __future__ import annotations

import os

from . import cost
from . import decomp
from .cost import (analyze_jit, analyze_lowered, classify, device_peaks,
                   programs, record_program, reset_programs,
                   ROOFLINE_VERDICTS)
from .decomp import StepBudget, probe_device_time

__all__ = ["enable", "disable", "enabled", "enable_from_env",
           "analyze_jit", "analyze_lowered", "classify", "device_peaks",
           "programs", "record_program", "reset_programs", "StepBudget",
           "probe_device_time", "bench_extra", "ROOFLINE_VERDICTS",
           "cost", "decomp"]

# module global: None = perfscope off (THE fast-path predicate; compile
# sites guard with `if _ps._PS is not None:`)
_PS = None


class _PerfScope:
    """Marker object holding enable-time options (mirrors the healthmon
    module-global discipline; the object exists so future options have a
    home without changing the predicate)."""

    def __init__(self, capture_jit_cache: bool = True):
        self.capture_jit_cache = bool(capture_jit_cache)


def enable(capture_jit_cache: bool = True):
    """Arm cost capture at every compile site. ``capture_jit_cache=False``
    keeps FusedTrainStep/TrainLoop/FrozenModel capture but skips the
    per-signature HybridBlock jit-cache analysis (one extra host trace
    per hybridized signature — measurable in compile-heavy suites)."""
    global _PS
    _PS = _PerfScope(capture_jit_cache=capture_jit_cache)
    return _PS


def disable():
    global _PS
    _PS = None


def enabled() -> bool:
    return _PS is not None


def enable_from_env():
    """MXTPU_PERFSCOPE=1 arms perfscope at import (like MXTPU_DIAG /
    MXTPU_HEALTHMON); =jit0 arms it without jit-cache capture."""
    v = os.environ.get("MXTPU_PERFSCOPE", "")
    if v == "1":
        enable()
    elif v == "jit0":
        enable(capture_jit_cache=False)


def bench_extra(decomposition=None) -> dict:
    """The ``extra.perfscope`` payload for BENCH json: the step budget
    (when the bench ran one), every analyzed program's roofline record,
    and the peak table the verdicts were scored against."""
    out = {"programs": programs(), "peaks": device_peaks()}
    if decomposition is not None:
        out["decomposition"] = decomposition
    return out
