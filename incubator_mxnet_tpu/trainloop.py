"""mxtpu.trainloop — the whole-loop train executor.

The reference's hot loop is host-driven: Python sits between every step
(CachedOp fwd/bwd → kvstore → per-weight optimizer kernels). PR 2–5
fused the *step*; this module fuses the *loop*: N micro-steps — forward,
backward, gradient collective, optimizer update, AND the lr schedule —
compile into ONE donated, remat-policy-tuned XLA program, losses
accumulate on device, and a double-buffered prefetcher
(io.DevicePrefetcher) lands the next chunk's batches on the chip while
the current chunk runs. The host's only per-chunk work is a queue pop
and one dispatch; between chunk boundaries it never touches the device.

What this fixes over the bench-only ``FusedTrainStep.run_k`` knob:

* **scheduler granularity** — lr is per MICRO-STEP, not per chunk:
  closed-form schedulers (optimizer/lr_scheduler.as_jax) compute lr
  IN-PROGRAM from the on-device step counter ``t``; custom schedulers
  fall back to a host-sampled (k,) lr table. Either way a k-chunked run
  matches a sequential loop step-for-step. (wd has no scheduler in this
  framework — it is sampled once at chunk start, like every other
  constant hyperparameter.)
* **input starvation is visible** — the prefetcher exports ``io.*``
  counters (batches_prefetched / wait_ms / put_ms / depth / buffer_fill)
  through the shared registry, so "TPU starved by input" shows up in
  /metrics, flight dumps and BENCH json next to step times.
* **first-class selection** — ``Trainer(..., loop_chunk=N)`` or
  ``MXTPU_LOOP_CHUNK=N`` marks a training setup for whole-loop
  execution; ``TrainLoop(net, loss, trainer)`` picks the chunk size up.
* **Pallas hot paths** — the traced step routes through the kernel-
  selection layer (ops/select.py), so flash-attention / fused layernorm
  / fused BN+relu kernels land inside the loop program when shapes
  qualify.
* **mesh-native parallelism** — ``Trainer(..., sharding='dp'|'fsdp'|
  'auto')`` (or an explicit ``mesh=``) lowers the whole chunk with the
  resolved per-param NamedShardings (mxtpu.sharding), so XLA inserts
  the dp gradient all-reduce / FSDP all-gathers INSIDE the one compiled
  program; see docs/sharding.md.

Telemetry (domain ``trainloop``): ``trainloop.chunks`` /
``trainloop.steps`` counters, ``trainloop.k`` / ``trainloop.chunk_ms`` /
``trainloop.in_program_lr`` gauges — plus the existing
``trainer.dispatches_per_step`` gauge, which reads 1/k under the
executor (the smoke test asserts < 1). The chunk program's compile
capture (perfscope roofline + commscope collective inventory) rides
FusedTrainStep's ``fused_step_k<k>`` hook — a scan-body inventory is
static, i.e. PER MICRO-STEP, which is exactly the granularity the step
budget's estimated ``collective`` component needs (docs/commscope.md).

See docs/trainloop.md for lifecycle, remat-policy knobs, prefetch-depth
tuning and the Pallas selection table.
"""
from __future__ import annotations

import time

import numpy as np

from . import devicescope as _devicescope
from . import memscope as _memscope
from . import profiler as _prof
from .autotune import knobs as _knobs
from .io.prefetch import DevicePrefetcher
from .parallel.trainer_step import FusedTrainStep

__all__ = ["TrainLoop", "resolve_chunk"]


def resolve_chunk(explicit=None, optimizer=None, default=4):
    """Chunk-size resolution: explicit argument > Trainer.loop_chunk >
    env/cached-winner layers > default. The env layers
    (BENCH_LOOP_CHUNK > MXTPU_LOOP_CHUNK > autotune cached winner) are
    the ONE knob table's (autotune.knobs) — every consumer resolves the
    same spellings in the same order, so bench.py and a hand-built
    TrainLoop can never disagree on what the env means. The default
    stays 4 here: constructing a TrainLoop IS choosing whole-loop
    execution, so an unconfigured chunk of 0 would be self-
    contradictory."""
    if explicit:
        return int(explicit)
    lc = getattr(optimizer, "loop_chunk", None)
    if lc:
        return int(lc)
    v, src = _knobs.resolve("loop_chunk")
    if v and src != "default":
        return int(v)
    return int(default)


class TrainLoop:
    """Whole-loop executor: ``run_chunk`` dispatches k train steps as one
    XLA program; ``fit`` drives a data source through the device
    prefetcher for a whole run.

        loop = TrainLoop(net, loss_fn, trainer)          # or optimizer
        losses = loop.fit(train_iter, steps=500)         # np (500,)

        # or hand-fed chunks:
        losses = loop.run_chunk(xs, ys)                  # (k,) NDArray

    Parameters mirror FusedTrainStep (mesh/data_axis/donate/remat/
    remat_policy); ``chunk`` defaults through
    Trainer.loop_chunk → MXTPU_LOOP_CHUNK → 4, ``prefetch_depth`` sizes
    the device-side input buffer (2 = double buffering), ``io_workers``
    sizes the ingest decode pool (docs/io.md).

    Donation safety: every chunk donates the parameter/optimizer-state
    buffers into the program and rebinds the live Parameters to the
    outputs — reading ``net.collect_params()`` between chunks is always
    valid; stale references to raw pre-chunk ``jax.Array``s are not (the
    same contract as FusedTrainStep)."""

    def __init__(self, net, loss_fn, optimizer, chunk=None, mesh=None,
                 data_axis=None, donate=True, remat=False, remat_policy=None,
                 prefetch_depth=None, schedule_in_program=True,
                 sharding=None, io_workers=None, io_transform=None):
        self.chunk = resolve_chunk(explicit=chunk, optimizer=optimizer)
        if self.chunk < 1:
            raise ValueError(f"loop chunk must be >= 1, got {self.chunk}")
        # buffer depth through the one knob table: explicit arg >
        # BENCH_PREFETCH_DEPTH > MXTPU_PREFETCH_DEPTH > cached winner >
        # 2 (classic double buffering). An explicit 0 is rejected HERE
        # (not deferred to the first _prefetcher build) so the error
        # names the constructor argument, same verdict as the env parse
        self.prefetch_depth = int(
            prefetch_depth if prefetch_depth is not None
            else _knobs.resolve("prefetch_depth")[0])
        if self.prefetch_depth < 1:
            raise ValueError(f"prefetch_depth must be >= 1, "
                             f"got {self.prefetch_depth}")
        # decode-pool width through the same table: explicit arg >
        # BENCH_IO_WORKERS > MXTPU_IO_WORKERS > cached winner > 2;
        # io_transform is a per-item decode hook (docs/io.md) run on
        # the pool threads, off the training thread's critical path
        self.io_workers = int(
            io_workers if io_workers is not None
            else _knobs.resolve("io_workers")[0])
        if self.io_workers < 1:
            raise ValueError(f"io_workers must be >= 1, "
                             f"got {self.io_workers}")
        self.io_transform = io_transform
        # sharding mode and mesh resolve exactly like FusedTrainStep's:
        # explicit arg > Trainer.sharding > MXTPU_SHARDING; explicit
        # mesh > process-global sharding.set_mesh (docs/sharding.md)
        self.step = FusedTrainStep(
            net, loss_fn, optimizer, mesh=mesh, data_axis=data_axis,
            donate=donate, remat=remat, remat_policy=remat_policy,
            schedule_in_program=schedule_in_program, sharding=sharding)
        self._c_chunks = _prof.counter("trainloop.chunks", "trainloop")
        self._c_steps = _prof.counter("trainloop.steps", "trainloop")
        # cumulative host wall spent INSIDE run_chunk dispatches — the
        # whole-loop host_gap signal perfscope's step-time decomposition
        # reads (per-step share = dispatch_ms delta / steps)
        self._c_dispatch = _prof.counter("trainloop.dispatch_ms",
                                         "trainloop")
        # Trainer(..., resilience=dir) marks the setup for supervised
        # recovery the same way loop_chunk marks it for whole-loop
        # execution; fit() picks it up unless overridden per call
        self._resilience_default = getattr(optimizer, "resilience", None)
        _prof.set_gauge("trainloop.k", self.chunk, "trainloop")

    # -- properties -------------------------------------------------------
    @property
    def net(self):
        return self.step.net

    @property
    def optimizer(self):
        return self.step.optimizer

    @property
    def num_update(self):
        return self.step._num_update

    @property
    def in_program_lr(self) -> bool:
        """True once the compiled loop computes lr on device from the
        step counter (closed-form scheduler); False = host lr table."""
        return self.step._lr_program is not None

    # -- execution --------------------------------------------------------
    def run_chunk(self, xs, ys):
        """Run one chunk: xs/ys stacked (k, batch, ...) arrays (or lists
        of k batches). Returns the k per-step losses as an NDArray —
        still on device; fetch at run end, not per chunk."""
        t0 = time.perf_counter()
        losses = self.step.run_k(xs, ys)
        k = int(losses.shape[0])
        self._c_chunks.increment()
        self._c_steps.increment(k)
        # dispatch wall time: through an async dispatch path this is the
        # HOST cost per chunk (the device runs behind), which is exactly
        # the quantity the executor exists to shrink
        chunk_ms = (time.perf_counter() - t0) * 1e3
        self._c_dispatch.increment(chunk_ms)
        _prof.set_gauge("trainloop.chunk_ms", round(chunk_ms, 3),
                        "trainloop")
        _prof.set_gauge("trainloop.in_program_lr",
                        int(self.in_program_lr), "trainloop")
        # devicescope capture windows bound themselves in STEPS, and the
        # executor is the only one who knows a dispatch was k of them —
        # mark the active window so `with devicescope.capture(): fit()`
        # needs no user-side plumbing (one predicate when no window).
        # The sync thunk fetches this chunk's last loss, a true barrier
        # (steps chain through donated params), so a window closing at
        # this mark never closes with its own steps still in flight —
        # it only runs if this mark IS the window boundary. No
        # dispatch_ms here: the trainloop.dispatch_ms counter above
        # already carries this chunk's wall, and the window reads that
        # counter's delta — passing it again would double-count the
        # dispatch share in the gap taxonomy
        win = _devicescope.active_window()
        if win is not None:
            win.step(k, sync=lambda: float(losses[k - 1]),
                     workload="train")
        # memscope watermark ride-along at the same chunk boundary: one
        # allocator sample per dispatch, one predicate when off
        if _memscope._MS is not None:
            _memscope.sample(step=self.num_update, workload="train")
        return losses

    def fit(self, data, steps=None, epochs=None, cycle=None,
            skip_batches=0, resilience=None):
        """Drive the executor from a data source.

        data   : DataIter / iterable of DataBatch or (x, y) pairs.
        steps  : total optimizer steps to run (rounded DOWN to whole
                 chunks). With ``steps``, DataIter sources are cycled
                 (reset + refeed) across epoch ends.
        epochs : alternatively, full passes over the source (chunk
                 remainders at each epoch tail are dropped — static
                 shapes can't take short chunks).
        skip_batches : discard the first N source batches before
                 training (the data-cursor resume path — a restarted
                 run must not replay consumed batches).
        resilience : arm mxtpu.resilience for this run — a
                 ``resilience.Supervisor``, or a checkpoint-directory
                 string (a default Supervisor is built on it); also
                 picked up from ``Trainer(..., resilience=dir)`` /
                 ``MXTPU_RESILIENCE_DIR`` (pass ``False`` to override
                 that default off for one call). The run then
                 checkpoints every N steps asynchronously, resumes from
                 the manifest when the directory already holds
                 checkpoints (restart-from-last-good), and rolls back +
                 retries on a NaN loss instead of training on garbage
                 (docs/resilience.md). Steps-driven only: an EXPLICIT
                 resilience= on an epochs-driven call raises; the
                 ambient Trainer/env default instead degrades that call
                 to an unsupervised fit with a warning, so exporting
                 MXTPU_RESILIENCE_DIR can never crash epoch-driven
                 scripts that predate it.

        Returns the per-step losses as a numpy array — fetched ONCE at
        the end (per CHUNK under resilience: the NaN check needs the
        scalars); the loop itself never blocks on device values."""
        from_default = False
        if resilience is None:
            resilience = self._resilience_default
            from_default = resilience is not None
        elif resilience is False:
            resilience = None
        if resilience is not None:
            unsupervisable = (steps is None or epochs is not None
                              or skip_batches)
            if unsupervisable and from_default:
                import warnings
                warnings.warn(
                    "resilience armed by Trainer/MXTPU_RESILIENCE_DIR "
                    "but this fit() is epochs-driven or passes "
                    "skip_batches — supervision needs steps= only; "
                    "running UNSUPERVISED (no checkpoints, no recovery) "
                    "for this call", stacklevel=2)
            else:
                from .resilience import Supervisor
                sup = (resilience if isinstance(resilience, Supervisor)
                       else Supervisor(str(resilience)))
                if steps is None or epochs is not None:
                    raise ValueError(
                        "resilient fit is steps-driven: pass steps= only "
                        "(epoch accounting does not survive a mid-epoch "
                        "restart)")
                if skip_batches:
                    raise ValueError(
                        "skip_batches is incompatible with resilience=: "
                        "the resume cursor from the checkpoint manifest "
                        "owns batch skipping, and a second offset would "
                        "silently double- or under-train the data")
                return sup.drive(self, data, steps=steps,
                                 cycle=True if cycle is None else cycle)
        if (steps is None) == (epochs is None):
            raise ValueError("pass exactly one of steps= or epochs=")
        histories = []
        if steps is not None:
            n_chunks = steps // self.chunk
            if n_chunks < 1:
                raise ValueError(
                    f"steps={steps} is less than one chunk of "
                    f"{self.chunk}; lower loop_chunk or raise steps")
            cycle = True if cycle is None else cycle
            with self._prefetcher(data, cycle=cycle,
                                  skip=skip_batches) as pf:
                for i in range(n_chunks):
                    try:
                        xs, ys = next(pf)
                    except StopIteration:
                        # never let a bare StopIteration escape (it would
                        # be swallowed by any enclosing iterator frame)
                        raise ValueError(
                            f"data source exhausted after "
                            f"{i * self.chunk} of {steps} steps and "
                            f"cannot be rewound (pass a DataIter or a "
                            f"re-iterable, or lower steps=)") from None
                    self._check_labeled(ys)
                    histories.append(self.run_chunk(xs, ys))
        else:
            for e in range(epochs):
                # MXNet epoch convention: DataIter sources rewind at each
                # epoch start (without this, epoch 2+ would iterate an
                # exhausted iterator and silently contribute nothing)
                if hasattr(data, "reset"):
                    data.reset()
                n_before = len(histories)
                with self._prefetcher(data, cycle=False,
                                      skip=skip_batches if e == 0
                                      else 0) as pf:
                    for xs, ys in pf:
                        self._check_labeled(ys)
                        histories.append(self.run_chunk(xs, ys))
                if len(histories) == n_before:
                    # an empty epoch is always a caller bug (one-shot
                    # iterator that can't rewind, or fewer batches than
                    # one chunk) — never silently under-train
                    raise ValueError(
                        f"epoch {e + 1} produced no chunks: the source "
                        f"is exhausted/non-rewindable or yields fewer "
                        f"than chunk={self.chunk} batches (pass a "
                        f"DataIter or a re-iterable)")
        if not histories:
            return np.zeros((0,), np.float32)
        return np.concatenate([h.asnumpy() for h in histories])

    @staticmethod
    def _check_labeled(ys):
        if ys is None:
            raise ValueError(
                "TrainLoop.fit needs labeled batches ((x, y) pairs or "
                "DataBatch with labels); got a label-less batch — for "
                "self-supervised inputs yield (x, x)")

    def _prefetcher(self, data, cycle, skip=0):
        # the stacked-batch sharding only exists after the first build;
        # hand the prefetcher a late-bound getter instead of a value
        return DevicePrefetcher(
            data, depth=self.prefetch_depth, chunk=self.chunk,
            sharding=lambda: self.step._stacked_sharding, cycle=cycle,
            skip=skip, workers=self.io_workers,
            transform=self.io_transform)
